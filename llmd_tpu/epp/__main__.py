"""`python -m llmd_tpu.epp` — the router entry point.

Standalone (no-Kubernetes) deployment: endpoints come from a JSON file
watched for changes (the reference's `file-discovery` plugin,
guides/no-kubernetes-deployment/README.md), the scheduler from an
EndpointPickerConfig JSON (or the built-in optimized-baseline / pd preset).
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import logging
import os
import signal


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("llmd-tpu router")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8800)
    p.add_argument(
        "--endpoints-file", default=None,
        help="JSON endpoints file (no-Kubernetes file-discovery)",
    )
    p.add_argument(
        "--k8s-selector", default=None,
        help="pod label selector for in-cluster discovery "
        "(e.g. 'llm-d.ai/role in (decode,prefill-decode)')",
    )
    p.add_argument(
        "--inference-pool", default=None,
        help="bind discovery to an InferencePool object: its "
        "spec.selector + spec.targetPortNumber replace --k8s-selector/"
        "--k8s-target-port (Gateway-API inference extension shape)",
    )
    p.add_argument("--k8s-namespace", default=None)
    p.add_argument("--k8s-target-port", type=int, default=8000)
    p.add_argument(
        "--k8s-discovery-mode", default="watch", choices=["watch", "poll"],
        help="watch = LIST once + WATCH stream with resourceVersion "
        "resume (sub-second endpoint joins, O(changes) API load); "
        "poll = periodic LIST",
    )
    p.add_argument(
        "--k8s-poll-interval", type=float, default=2.0,
        help="pod LIST poll period in poll mode / watch-retry backoff "
        "(apiserver load; separate from the per-endpoint metrics "
        "--scrape-interval)",
    )
    p.add_argument("--config", default=None, help="EndpointPickerConfig JSON file")
    p.add_argument(
        "--preset", default="default",
        choices=["default", "pd", "epd", "precise", "predicted-latency"],
        help="built-in config preset when --config is not given",
    )
    p.add_argument(
        "--kv-events-port", type=int, default=5556,
        help="default engine KV-event port for precise prefix routing",
    )
    p.add_argument(
        "--prefix-tier-weights", default=None,
        help="prefix-index tier weight overrides, 'tier=w,...' (e.g. "
        "'cpu=0.7,store=0.4'); same syntax as LLMD_PREFIX_TIER_WEIGHTS "
        "and takes precedence over it (kv-federation.md tri-state "
        "scoring)",
    )
    p.add_argument(
        "--lora-tier-weights", default=None,
        help="adapter-residency tier weight overrides for the "
        "lora-affinity scorer, 'tier=w,...' (tiers: resident, "
        "registered, cold — e.g. 'registered=0.6'); same syntax as "
        "LLMD_LORA_TIER_WEIGHTS and takes precedence over it "
        "(docs/architecture/multi-tenant-lora.md tri-state residency "
        "scoring)",
    )
    p.add_argument(
        "--predictor-url", default=None,
        help="prediction sidecar base URL (predicted-latency routing)",
    )
    p.add_argument(
        "--trainer-url", default=None,
        help="training sidecar base URL (predicted-latency routing)",
    )
    p.add_argument("--scrape-interval", type=float, default=1.0)
    p.add_argument(
        "--max-resumes", type=int, default=None,
        help="mid-stream failover budget: how many times one request's "
        "cut stream may resume on a fresh replica before the failure "
        "surfaces to the client (default LLMD_EPP_MAX_RESUMES or 2; "
        "0 disables resume — mid-stream failures still feed the "
        "circuit breaker)",
    )
    p.add_argument(
        "--ext-proc-port", type=int, default=None,
        help="ALSO serve the Envoy ext-proc gRPC protocol on this port "
        "(the reference EPP's primary deployment shape; the HTTP fused "
        "proxy stays up for /metrics and no-Envoy clients)",
    )
    p.add_argument(
        "--ext-proc-mode", default="streamed", choices=["streamed", "buffered"],
        help="ext-proc body mode: streamed = FULL_DUPLEX_STREAMED (GAIE "
        "protocol, default); buffered = legacy BUFFERED Envoy configs",
    )
    p.add_argument(
        "--otlp-traces-endpoint", default=None,
        help="OTLP/HTTP collector base URL (e.g. http://otel:4318)",
    )
    p.add_argument("--trace-file", default=None, help="JSONL span log path")
    p.add_argument("--trace-sample-ratio", type=float, default=0.1)
    args = p.parse_args(argv)

    if args.otlp_traces_endpoint or args.trace_file:
        from llmd_tpu.obs.tracing import configure_tracing

        configure_tracing(
            "llmd-router",
            otlp_endpoint=args.otlp_traces_endpoint,
            trace_file=args.trace_file,
            sample_ratio=args.trace_sample_ratio,
        )

    from aiohttp import web

    from llmd_tpu.epp.config import (
        DEFAULT_CONFIG,
        EPD_CONFIG,
        PD_CONFIG,
        PRECISE_CONFIG,
        PREDICTED_LATENCY_CONFIG,
        build_flow_control,
        build_scheduler,
    )
    from llmd_tpu.epp.datalayer import (
        EndpointStore,
        FileDiscoverySource,
        MetricsCollector,
    )
    from llmd_tpu.epp.server import Router

    if args.config:
        with open(args.config) as f:
            config = json.load(f)
    else:
        config = {
            "default": DEFAULT_CONFIG, "pd": PD_CONFIG, "epd": EPD_CONFIG,
            "precise": PRECISE_CONFIG,
            "predicted-latency": PREDICTED_LATENCY_CONFIG,
        }[args.preset]

    if not args.endpoints_file and not args.k8s_selector and not args.inference_pool:
        p.error(
            "one of --endpoints-file, --k8s-selector, or --inference-pool "
            "is required"
        )
    if args.endpoints_file and (args.k8s_selector or args.inference_pool):
        # Both sources reconcile the store to THEIR full set, so running
        # two would alternately wipe each other's endpoints every poll.
        p.error("--endpoints-file excludes the k8s discovery flags")

    store = EndpointStore()
    router = Router(
        store=store,
        scheduler=build_scheduler(config),
        flow_control=build_flow_control(config),
        collector=MetricsCollector(store, interval_s=args.scrape_interval),
        discovery=(
            FileDiscoverySource(store, args.endpoints_file)
            if args.endpoints_file
            else None
        ),
        default_parser=config.get("requestHandler", {}).get(
            "parser", "openai-parser"
        ),
        max_resumes=args.max_resumes,
    )
    # Wires token-producer + KV-event subscription iff the config declares
    # a precise-prefix-cache-scorer (no-op otherwise).
    from llmd_tpu.epp.precise_prefix import attach_precise_routing

    attach_precise_routing(
        router,
        default_events_port=args.kv_events_port,
        tier_weights=args.prefix_tier_weights,
    )
    if args.lora_tier_weights:
        # Flag-level overrides land on every lora-affinity scorer in the
        # chain (defaults < env < scorer config < flag — the same
        # precedence ladder as --prefix-tier-weights).
        from llmd_tpu.epp.config import find_plugins
        from llmd_tpu.epp.scorers import LoraAffinityScorer
        from llmd_tpu.events.index import parse_tier_weights

        for scorer in find_plugins(router.scheduler, LoraAffinityScorer):
            scorer.tier_weights.update(
                parse_tier_weights(args.lora_tier_weights)
            )
    # Wires the predictor producer + feedback + SLO admitter iff the config
    # declares a latency-scorer or slo-headroom-tier filter (no-op otherwise).
    from llmd_tpu.epp.predicted_latency import maybe_attach_predicted_latency

    maybe_attach_predicted_latency(
        router, predict_url=args.predictor_url, train_url=args.trainer_url
    )
    app = router.build_app()
    if args.k8s_selector or args.inference_pool:
        from llmd_tpu.epp.k8s_discovery import (
            K8sPodDiscoverySource, resolve_inference_pool,
        )

        k8s = K8sPodDiscoverySource(
            store,
            label_selector=args.k8s_selector or "",
            namespace=args.k8s_namespace,
            target_port=args.k8s_target_port,
            poll_s=args.k8s_poll_interval,
            mode=args.k8s_discovery_mode,
        )

        async def _start_k8s(app):
            if args.inference_pool:
                await resolve_inference_pool(k8s, args.inference_pool)
            k8s.start()

        app.on_startup.append(_start_k8s)
        router.closables.append(k8s)
    if args.ext_proc_port is not None:
        from llmd_tpu.epp.extproc import ExtProcServer

        extproc = ExtProcServer(
            router, host=args.host, port=args.ext_proc_port,
            mode=args.ext_proc_mode,
        )

        async def _start_extproc(app):
            await extproc.start()

        async def _stop_extproc(app):
            await extproc.stop()

        app.on_startup.append(_start_extproc)
        app.on_cleanup.append(_stop_extproc)
    asyncio.run(_serve(app, args.host, args.port, router))


async def _serve(app, host: str, port: int, router) -> None:
    """Run the app with a two-phase graceful shutdown.

    ``web.run_app`` closes the listening socket before the app's
    cleanup_ctx teardown runs, so flipping readiness there is invisible
    — the gateway's probe sees connection-refused, not the graceful
    503. Here SIGTERM/SIGINT first flips readiness WHILE the socket is
    still serving, waits ``LLMD_EPP_DRAIN_GRACE_S`` (default 5s) for
    the probe to observe it and routing to move away, and only then
    tears the runner down (which drains flow control and evicts)."""
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logging.getLogger("llmd.epp").info("router serving on %s:%d", host, port)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_signal() -> None:
        router.begin_shutdown()
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        with contextlib.suppress(NotImplementedError):
            loop.add_signal_handler(sig, _on_signal)
    await stop.wait()
    grace = float(os.environ.get("LLMD_EPP_DRAIN_GRACE_S", "5"))
    if grace > 0:
        await asyncio.sleep(grace)
    await runner.cleanup()


if __name__ == "__main__":
    main()
