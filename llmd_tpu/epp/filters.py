"""Filter plugins (reference scheduling.md:77-83)."""

from __future__ import annotations

import random

from llmd_tpu.epp.plugins import Filter, register
from llmd_tpu.epp.prefix_approx import ApproxPrefixIndex, prompt_block_hashes
from llmd_tpu.epp.types import (
    BATCH_PRIORITY,
    KV_CACHE_USAGE,
    ROLE_BOTH,
    ROLE_DECODE,
    ROLE_ENCODE,
    ROLE_PREFILL,
    WAITING_QUEUE_SIZE,
    Endpoint,
    LLMRequest,
)


@register("label-selector-filter")
class LabelSelectorFilter(Filter):
    """Keep endpoints whose labels match all given key=value pairs."""

    def __init__(self, **labels: str) -> None:
        self.labels = labels

    def filter(self, req: LLMRequest, pods: list[Endpoint]) -> list[Endpoint]:
        return [
            p
            for p in pods
            if all(p.labels.get(k) == v for k, v in self.labels.items())
        ]


@register("prefill-filter")
class PrefillFilter(Filter):
    """Endpoints able to run prefill (role prefill or prefill-decode)."""

    def filter(self, req, pods):
        return [p for p in pods if p.role in (ROLE_PREFILL, ROLE_BOTH)]


@register("decode-filter")
class DecodeFilter(Filter):
    """Endpoints able to run decode (role decode or prefill-decode)."""

    def filter(self, req, pods):
        return [p for p in pods if p.role in (ROLE_DECODE, ROLE_BOTH)]


@register("encode-filter")
class EncodeFilter(Filter):
    """Dedicated vision-encode workers (E/P/D multimodal disaggregation,
    reference e-p-d-disaggregation.values.yaml encode profile)."""

    def filter(self, req, pods):
        return [p for p in pods if p.role == ROLE_ENCODE]


# Fail-open accounting: times the healthy-filter saw a wholly-unhealthy
# pool and passed it through anyway. Module-global because filter plugin
# instances are config-created and the router's /metrics renderer has no
# handle on them.
_fail_open_total = 0


def note_fail_open() -> None:
    global _fail_open_total
    _fail_open_total += 1


def fail_open_total() -> int:
    return _fail_open_total


@register("healthy-filter")
class HealthyFilter(Filter):
    """Keep healthy endpoints — failing OPEN when none are.

    An all-unhealthy pool usually means the health DATA is bad (scrape
    outage, collector restart), not that every replica is down; filtering
    to zero candidates turns a telemetry gap into a guaranteed 503. Pass
    the full pool through instead (scorers still order it) and count the
    event so the condition is loud on /metrics rather than silent."""

    def filter(self, req, pods):
        healthy = [p for p in pods if p.healthy]
        if healthy or not pods:
            return healthy
        note_fail_open()
        return pods


@register("model-filter")
class ModelFilter(Filter):
    """Keep endpoints serving the request's model (multi-model pools)."""

    def filter(self, req, pods):
        if not req.model:
            return pods
        return [p for p in pods if p.model in (None, req.model)]


@register("kv-headroom-filter")
class KVHeadroomFilter(Filter):
    """Drop endpoints whose KV cache is above a utilization ceiling.

    The load-gate half of the reference's prefix-cache-affinity filter
    (scheduling.md:78-80): perfect cache affinity is worthless on a pod
    that has no KV headroom to run the request.
    """

    def __init__(self, max_usage: float = 0.95) -> None:
        self.max_usage = max_usage

    def filter(self, req, pods):
        kept = [p for p in pods if p.attr(KV_CACHE_USAGE) <= self.max_usage]
        return kept or pods  # never filter to zero on load alone


@register("batch-saturation-filter")
class BatchSaturationFilter(Filter):
    """Admit batch-band work only on replicas below a saturation
    watermark (docs/architecture/batch-processing.md).

    The router-side half of the backfill contract: a request at or
    below BATCH_PRIORITY (the `x-llmd-priority: batch` band) may only
    land on replicas with real headroom — KV utilization under
    ``max_kv_usage`` AND waiting queue at or under ``max_waiting`` —
    so offline work soaks idle decode capacity instead of queueing
    behind interactive traffic on a busy pod. Interactive requests
    pass through untouched.

    Unlike the healthy/KV-headroom filters this one DOES filter to
    zero on purpose: an empty candidate set turns into a retryable
    503 at the router, and the batch processor's backoff loop
    (batch/processor.py) re-offers the job — batch work WAITS for
    headroom, it never displaces. Same watermark shape as the
    SaturationGate the async processor polls (batch/asyncproc.py),
    applied per-endpoint at pick time instead of pool-wide at
    dispatch time.
    """

    def __init__(
        self, max_kv_usage: float = 0.8, max_waiting: float = 0.0
    ) -> None:
        self.max_kv_usage = max_kv_usage
        self.max_waiting = max_waiting

    def filter(self, req, pods):
        if req.priority > BATCH_PRIORITY:
            return pods
        return [
            p for p in pods
            if p.attr(KV_CACHE_USAGE) <= self.max_kv_usage
            and p.attr(WAITING_QUEUE_SIZE) <= self.max_waiting
        ]


@register("prefix-cache-affinity-filter")
class PrefixCacheAffinityFilter(Filter):
    """Epsilon-greedy sticky routing with a TTFT load gate
    (reference scheduling.md:77-80).

    Narrows candidates to "sticky" endpoints — those whose approximate
    prefix-cache match fraction for this prompt clears ``sticky_threshold``
    — so conversation turns keep landing where their KV lives. Two escape
    hatches prevent stickiness from congesting hot pods:

    * epsilon-greedy exploration: with probability ``epsilon`` the filter
      passes the full pool through, letting load-based scorers migrate
      traffic;
    * TTFT load gate: when the sticky pods' last observed TTFT is more
      than ``ttft_gate_factor`` times the non-sticky pods' (they are
      "significantly slower"), stickiness breaks for this request.

    Tracks its own approximate index via the on_routed filter hook —
    independent of (and composable with) the prefix-cache scorer.
    """

    def __init__(
        self,
        sticky_threshold: float = 0.5,
        epsilon: float = 0.05,
        ttft_gate_factor: float = 2.0,
        block_chars: int = 256,
        max_entries: int = 500_000,
        max_prefix_blocks: int = 1024,
        seed: int | None = None,
    ) -> None:
        self.sticky_threshold = sticky_threshold
        self.epsilon = epsilon
        self.ttft_gate_factor = ttft_gate_factor
        self.index = ApproxPrefixIndex(block_chars, max_entries, max_prefix_blocks)
        self._rng = random.Random(seed)

    @staticmethod
    def _mean_ttft(pods: list[Endpoint]) -> float | None:
        vals = [
            p.attrs["LastTTFT"] for p in pods
            if isinstance(p.attrs.get("LastTTFT"), (int, float))
        ]
        return sum(vals) / len(vals) if vals else None

    def filter(self, req, pods):
        hashes = prompt_block_hashes(req, self.index)
        if not hashes:
            return pods
        matches = self.index.match_lengths(hashes)
        total = len(hashes)
        sticky = [
            p for p in pods
            if matches.get(p.address, 0) / total >= self.sticky_threshold
        ]
        if not sticky or len(sticky) == len(pods):
            return pods
        if self._rng.random() < self.epsilon:
            return pods  # explore
        # TTFT load gate: break stickiness when sticky pods are
        # significantly slower than the alternatives.
        sticky_addrs = {p.address for p in sticky}
        others = [p for p in pods if p.address not in sticky_addrs]
        t_sticky = self._mean_ttft(sticky)
        t_others = self._mean_ttft(others)
        if (
            t_sticky is not None
            and t_others is not None
            and t_others > 0
            and t_sticky > self.ttft_gate_factor * t_others
        ):
            return pods
        return sticky

    def on_routed(self, req, pod):
        hashes = prompt_block_hashes(req, self.index)
        if hashes:
            self.index.record_routed(hashes, pod.address)

    def on_endpoint_removed(self, address: str) -> None:
        self.index.evict_endpoint(address)
