"""Filter plugins (reference scheduling.md:77-83)."""

from __future__ import annotations

from llmd_tpu.epp.plugins import Filter, register
from llmd_tpu.epp.types import (
    KV_CACHE_USAGE,
    ROLE_BOTH,
    ROLE_DECODE,
    ROLE_ENCODE,
    ROLE_PREFILL,
    Endpoint,
    LLMRequest,
)


@register("label-selector-filter")
class LabelSelectorFilter(Filter):
    """Keep endpoints whose labels match all given key=value pairs."""

    def __init__(self, **labels: str) -> None:
        self.labels = labels

    def filter(self, req: LLMRequest, pods: list[Endpoint]) -> list[Endpoint]:
        return [
            p
            for p in pods
            if all(p.labels.get(k) == v for k, v in self.labels.items())
        ]


@register("prefill-filter")
class PrefillFilter(Filter):
    """Endpoints able to run prefill (role prefill or prefill-decode)."""

    def filter(self, req, pods):
        return [p for p in pods if p.role in (ROLE_PREFILL, ROLE_BOTH)]


@register("decode-filter")
class DecodeFilter(Filter):
    """Endpoints able to run decode (role decode or prefill-decode)."""

    def filter(self, req, pods):
        return [p for p in pods if p.role in (ROLE_DECODE, ROLE_BOTH)]


@register("encode-filter")
class EncodeFilter(Filter):
    """Dedicated vision-encode workers (E/P/D multimodal disaggregation,
    reference e-p-d-disaggregation.values.yaml encode profile)."""

    def filter(self, req, pods):
        return [p for p in pods if p.role == ROLE_ENCODE]


@register("healthy-filter")
class HealthyFilter(Filter):
    def filter(self, req, pods):
        return [p for p in pods if p.healthy]


@register("model-filter")
class ModelFilter(Filter):
    """Keep endpoints serving the request's model (multi-model pools)."""

    def filter(self, req, pods):
        if not req.model:
            return pods
        return [p for p in pods if p.model in (None, req.model)]


@register("kv-headroom-filter")
class KVHeadroomFilter(Filter):
    """Drop endpoints whose KV cache is above a utilization ceiling.

    The load-gate half of the reference's prefix-cache-affinity filter
    (scheduling.md:78-80): perfect cache affinity is worthless on a pod
    that has no KV headroom to run the request.
    """

    def __init__(self, max_usage: float = 0.95) -> None:
        self.max_usage = max_usage

    def filter(self, req, pods):
        kept = [p for p in pods if p.attr(KV_CACHE_USAGE) <= self.max_usage]
        return kept or pods  # never filter to zero on load alone
