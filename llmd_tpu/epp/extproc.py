"""Envoy ext-proc gRPC mode for the EPP.

The reference EPP's primary deployment shape: an external-processor plugin
behind Envoy / a K8s Gateway (docs/architecture/core/router/epp/
README.md:11-18, proxy.md:16-26). Envoy streams the request over a
bidirectional gRPC `Process` call; the EPP answers with header mutations
naming the picked endpoint, and Envoy forwards the request itself. The
fused reverse-proxy mode (epp/server.py) stays as the no-K8s shape; this
module reuses its exact pipeline — parse -> admitters -> flow control ->
data producers -> schedule — only the transport differs.

Processing mode: FULL_DUPLEX_STREAMED both directions (the protocol the
reference mandates for GAIE, epp/README.md:48-50). Per request:

  Envoy -> request_headers             (held — no reply yet)
  Envoy -> request_body chunk*         (accumulated; Envoy does not
                                        forward a chunk until the EPP
                                        hands it back, so the decision
                                        gates the stream without BUFFERED
                                        mode's full-body Envoy buffer)
  [body complete] run pipeline;  reply HeadersResponse with
                                 x-gateway-destination-endpoint +
                                 x-llm-d-* mutations + clear_route_cache,
                                 then one streamed BodyResponse per held
                                 chunk — or an ImmediateResponse 429/503
                                 with x-llm-d-request-dropped-reason per
                                 flow-control.md:369-409
  Envoy -> response_headers            (record TTFT; CONTINUE)
  Envoy -> response_body chunk*        (streamed back immediately; SSE
                                        usage frames are sampled for the
                                        latency observers mid-stream,
                                        request-handling.md:56-63)
  stream end                           (release inflight accounting)

A ``mode="buffered"`` fallback keeps the old BUFFERED exchange for Envoy
configs that predate duplex streaming.

Failure semantics (flow-control.md:345-359): pipeline errors abort the
stream with a gRPC error — Envoy's `failure_mode_allow` then decides
FailOpen (route unpicked) vs FailClose (reject). Explicit rejections
(flow control, admitters) are ImmediateResponses, which Envoy returns to
the client in BOTH failure modes.
"""

from __future__ import annotations

import asyncio
import logging

import grpc

from llmd_tpu import clock
from llmd_tpu.epp import extproc_pb as pb
from llmd_tpu.epp.flow_control import OUTCOME_HTTP, Outcome
from llmd_tpu.epp.handler import ParseError, parse_request
from llmd_tpu.epp.scheduler import NoEndpointsError
from llmd_tpu.epp.types import HDR_DROP_REASON, HDR_ENCODER, HDR_PREFILLER
from llmd_tpu.obs.tracing import get_tracer

log = logging.getLogger(__name__)

METHOD = "/envoy.service.ext_proc.v3.ExternalProcessor/Process"
# The Gateway-API inference-extension destination header (GAIE protocol;
# Envoy's original_dst cluster routes on it).
HDR_DESTINATION = "x-gateway-destination-endpoint"
HDR_ENDPOINT = "x-llm-d-endpoint"


class ExtProcSession:
    """One gRPC stream == one HTTP request being processed.

    ``on_message`` returns the (possibly empty) LIST of replies to send —
    duplex streaming holds replies across messages (no reply for early
    body chunks; headers-response + all held chunks after routing).
    """

    def __init__(self, router, mode: str = "streamed") -> None:
        self.router = router
        self.mode = mode
        self.headers: dict[str, str] = {}
        self.body = bytearray()
        self.req = None
        self.pod = None
        self.t_routed: float | None = None
        self._flow_held = False
        self._ok = False
        # streamed mode: request chunks held until the routing decision;
        # _set_headers doubles as the routed/rejected discriminator.
        self._held: list[tuple[bytes, bool]] = []
        self._set_headers: dict[str, str] = {}
        self._t_first_response: float | None = None
        # SSE line reassembly across response_body chunks (a usage frame
        # split over two ext-proc chunks must still be observed); bounded.
        self._sse_tail = b""

    async def on_message(self, msg: pb.ProcessingRequest) -> list[bytes]:
        if msg.kind == "request_headers":
            self.headers = msg.headers
            if msg.end_of_stream:
                # Bodyless request (GET /v1/models etc): route on headers.
                return [await self._route()]
            if self.mode == "buffered":
                return [pb.encode_common_response("request_headers")]
            return []  # duplex: headers response deferred until routed
        if msg.kind == "request_body":
            self.body.extend(msg.body)
            if self.mode == "buffered":
                if msg.end_of_stream:
                    return [await self._route()]
                return []
            self._held.append((msg.body, msg.end_of_stream))
            if msg.end_of_stream:
                decision = await self._route()
                if not self._set_headers:
                    # No routing mutations were produced: the decision is a
                    # rejection (ImmediateResponse) — forward it as-is.
                    return [decision]
                out = [pb.encode_common_response(
                    "request_headers",
                    set_headers=self._set_headers,
                    clear_route_cache=True,
                )]
                out.extend(
                    pb.encode_streamed_body_response("request_body", chunk, eos)
                    for chunk, eos in self._held
                )
                self._held.clear()
                return out
            return []  # hold the chunk; Envoy waits for the hand-back
        if msg.kind == "response_headers":
            status = msg.headers.get(":status", "")
            if self.req is not None and self.pod is not None:
                ttft_ms = None
                if self.t_routed is not None and status.startswith("2"):
                    ttft_s = clock.monotonic() - self.t_routed
                    ttft_ms = ttft_s * 1e3
                    # Mirror the fused proxy's accounting (server.py): the
                    # latency-aware scorers and PrefixCacheAffinityFilter's
                    # TTFT load gate read these attrs, and Envoy is the
                    # EPP's primary deployment shape.
                    self.pod.attrs["LastTTFT"] = ttft_s
                    self._t_first_response = clock.monotonic()
                    self._ok = True
                # Fire-and-forget like the fused proxy (server.py): a slow
                # observer (predictor training POST) must not hold Envoy's
                # response delivery.
                task = asyncio.ensure_future(
                    self.router._run_observers(self.req, self.pod, ttft_ms, None)
                )
                self.router._observer_tasks.add(task)
                task.add_done_callback(self.router._observer_tasks.discard)
            return [pb.encode_common_response("response_headers")]
        if msg.kind == "request_trailers":
            if (
                self.mode == "streamed"
                and self.req is None
                and not self._set_headers
                and (self.body or self.headers)
            ):
                # Trailer-terminated body: Envoy signals end-of-body via
                # the trailers message (the last chunk has eos=false) —
                # route NOW or the held chunks are never handed back and
                # the request stalls until Envoy's message_timeout.
                decision = await self._route()
                if not self._set_headers:
                    return [decision, pb.encode_common_response(msg.kind)]
                out = [pb.encode_common_response(
                    "request_headers",
                    set_headers=self._set_headers,
                    clear_route_cache=True,
                )]
                out.extend(
                    pb.encode_streamed_body_response("request_body", chunk, eos)
                    for chunk, eos in self._held
                )
                self._held.clear()
                out.append(pb.encode_common_response(msg.kind))
                return out
            return [pb.encode_common_response(msg.kind)]
        if msg.kind == "response_trailers":
            return [pb.encode_common_response(msg.kind)]
        if msg.kind == "response_body":
            if self.mode == "buffered":
                return [pb.encode_common_response("response_body")]
            self._observe_response_chunk(msg.body, eos=msg.end_of_stream)
            # Stream the chunk straight back — response bodies are never
            # held (TTFT/ITL pass through untouched).
            return [pb.encode_streamed_body_response(
                "response_body", msg.body, msg.end_of_stream
            )]
        return []

    def _observe_response_chunk(self, chunk: bytes, eos: bool = False) -> None:
        """Sample streamed SSE frames for usage mid-stream (the reference
        samples usage/latency from streamed response bodies,
        request-handling.md:56-63): completion token counts yield a live
        LastTPOT for the latency-aware scorers — the same accounting the
        fused proxy derives at stream end (server.py)."""
        if self.pod is None:
            return
        # Join with the held tail so a frame split across chunks parses
        # once complete; the unterminated remainder carries over (bounded
        # — a pathological never-newline stream can't grow it unbounded).
        # At end-of-stream the tail is flushed as a final line: a last
        # data frame without a terminating newline must still count.
        buf = self._sse_tail + chunk
        *lines, tail = buf.split(b"\n")
        if eos and tail:
            lines.append(tail)
            tail = b""
        self._sse_tail = tail[-8192:]
        if b'"usage"' not in buf:
            return
        import json

        for line in lines:
            if not line.startswith(b"data:") or b"[DONE]" in line:
                continue
            try:
                usage = json.loads(line[5:].strip()).get("usage") or {}
            except (ValueError, AttributeError):
                continue
            n_out = usage.get("completion_tokens")
            if not n_out:
                continue
            self.pod.attrs["LastCompletionTokens"] = n_out
            if self._t_first_response is not None and n_out >= 2:
                decode_s = clock.monotonic() - self._t_first_response
                self.pod.attrs["LastTPOT"] = decode_s / (n_out - 1)

    def close(self) -> None:
        """Stream end: release scheduling + flow-control accounting.

        The flow slot is held for the whole stream (Envoy is proxying the
        request until it closes), matching the fused proxy's release-in-
        finally — releasing at schedule time would make the max_inflight
        saturation gate count near-zero concurrency."""
        if self._flow_held:
            self._flow_held = False
            self.router.flow.release()
        if self.pod is not None:
            if self._ok and self.t_routed is not None:
                # E2E closes when Envoy finishes proxying the stream —
                # same point the fused proxy records it (server.py).
                self.pod.attrs["LastE2E"] = clock.monotonic() - self.t_routed
            self.pod.inflight = max(0, self.pod.inflight - 1)
            if self.req is not None:
                self.pod.inflight_tokens = max(
                    0, self.pod.inflight_tokens - self.req.approx_prompt_tokens
                )
                self.router.scheduler.notify_complete(self.req, self.pod)
            self.pod = None

    # -------------------------------------------------------------- core

    def _reject(self, status: int, reason: str) -> bytes:
        # ImmediateResponse before any headers/body response has been
        # returned. NOTE (duplex streaming): some Envoy builds refuse
        # ImmediateResponse once request_body_mode is FULL_DUPLEX_STREAMED;
        # there the stream error is surfaced per failure_mode_allow
        # (FailClose still rejects the request, with a generic status). No
        # CommonResponse encoding can carry a rejection in that protocol
        # state, so this stays the best-effort encoding in both modes.
        return pb.encode_immediate_response(
            status,
            headers={HDR_DROP_REASON: reason},
            body=(
                b'{"error": {"message": "%s"}}' % reason.encode()
            ),
            details=reason,
        )

    async def _route(self) -> bytes:
        router = self.router
        router.metrics.requests_total += 1
        path = self.headers.get(":path", "/v1/completions")
        raw = bytes(self.body)
        try:
            req = parse_request(path, self.headers, raw, router.default_parser)
        except ParseError as e:
            return self._reject(400, str(e))
        self.req = req
        span = get_tracer().start_span(
            "router.extproc",
            traceparent=self.headers.get("traceparent"),
            kind="SPAN_KIND_SERVER",
        )
        span.set("gen_ai.request.model", req.model)
        req.scratch["span"] = span
        try:
            return await self._route_inner(req, raw, span)
        finally:
            span.end()

    async def _route_inner(self, req, raw: bytes, span) -> bytes:
        router = self.router
        for adm in router.admitters:
            if not adm.needs_producers:
                reason = adm.admit(req)
                if reason is not None:
                    return self._reject(429, reason)
        outcome = await router.flow.enqueue_and_wait(req, nbytes=len(raw))
        span.set("llm_d.flow_control.outcome", str(outcome.value))
        if outcome is not Outcome.DISPATCHED:
            status, reason = OUTCOME_HTTP[outcome]
            return self._reject(status, reason)
        handed_off = False
        try:
            for producer in router.producers:
                try:
                    await producer.produce(req, router.store.list())
                except Exception:
                    log.exception(
                        "data producer %s failed", type(producer).__name__
                    )
            for adm in router.admitters:
                if adm.needs_producers:
                    reason = adm.admit(req)
                    if reason is not None:
                        return self._reject(429, reason)
            router.metrics.scheduling_attempts += 1
            try:
                result = router.scheduler.schedule(req, router.store.list())
            except NoEndpointsError as e:
                router.metrics.scheduling_errors += 1
                return self._reject(503, f"no-endpoints: {e}")
            pod = result.primary
            span.set("llm_d.decision.endpoint", pod.address)
            set_headers = {
                HDR_DESTINATION: pod.address,
                HDR_ENDPOINT: pod.address,
                "x-request-id": req.request_id,
            }
            if result.prefill is not None:
                set_headers[HDR_PREFILLER] = result.prefill.address
            if result.encode is not None:
                set_headers[HDR_ENCODER] = result.encode.address
            self._set_headers = set_headers
            # Scheduling + flow accounting mirrors the fused proxy: both
            # held until stream close (Envoy owns the actual proxying).
            pod.inflight += 1
            pod.inflight_tokens += req.approx_prompt_tokens
            self.pod = pod
            self.t_routed = clock.monotonic()
            self._flow_held = True
            handed_off = True
            kind = "request_body" if self.body else "request_headers"
            return pb.encode_common_response(
                kind, set_headers=set_headers, clear_route_cache=True
            )
        finally:
            if not handed_off:
                router.flow.release()


class ExtProcServer:
    """grpc.aio server speaking the ext-proc protocol around a Router.

    ``mode``: "streamed" (FULL_DUPLEX_STREAMED, the GAIE default) or
    "buffered" (legacy Envoy configs).
    """

    def __init__(
        self, router, host: str = "127.0.0.1", port: int = 0,
        mode: str = "streamed",
    ) -> None:
        if mode not in ("streamed", "buffered"):
            raise ValueError(f"unknown ext-proc mode {mode!r}")
        self.router = router
        self.host = host
        self.port = port
        self.mode = mode
        self._server: grpc.aio.Server | None = None

    async def _process(self, request_iterator, context):
        session = ExtProcSession(self.router, mode=self.mode)
        try:
            async for raw in request_iterator:
                msg = pb.parse_processing_request(raw)
                if msg is None:
                    continue
                try:
                    replies = await session.on_message(msg)
                # llmd: allow(broad-except) -- surfaced: the stream is aborted with StatusCode.INTERNAL (context.abort raises)
                except Exception as e:  # pipeline failure -> FailOpen/Close
                    log.exception("ext-proc pipeline error")
                    await context.abort(
                        grpc.StatusCode.INTERNAL, f"epp pipeline error: {e}"
                    )
                    return
                for reply in replies:
                    yield reply
        finally:
            session.close()

    async def start(self) -> int:
        handler = grpc.stream_stream_rpc_method_handler(
            self._process,
            request_deserializer=None,
            response_serializer=None,
        )
        generic = grpc.method_handlers_generic_handler(
            "envoy.service.ext_proc.v3.ExternalProcessor",
            {"Process": handler},
        )
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((generic,))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self.router.flow.start()  # idempotent; gRPC-only deployments
        await self._server.start()
        log.info("ext-proc EPP listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None


async def run_extproc(router, host: str, port: int, mode: str = "streamed") -> None:
    server = ExtProcServer(router, host, port, mode=mode)
    await server.start()
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()
