"""Envoy ext-proc gRPC mode for the EPP.

The reference EPP's primary deployment shape: an external-processor plugin
behind Envoy / a K8s Gateway (docs/architecture/core/router/epp/
README.md:11-18, proxy.md:16-26). Envoy parks the request and streams it
over a bidirectional gRPC `Process` call; the EPP answers with header
mutations naming the picked endpoint, and Envoy forwards the request
itself. The fused reverse-proxy mode (epp/server.py) stays as the no-K8s
shape; this module reuses its exact pipeline — parse -> admitters -> flow
control -> data producers -> schedule — only the transport differs.

Exchange per request (processing mode: request headers + BUFFERED body):

  Envoy -> request_headers         (stash; CONTINUE)
  Envoy -> request_body (eos)      (run pipeline; reply BodyResponse with
                                    x-gateway-destination-endpoint +
                                    x-llm-d-* header mutations and
                                    clear_route_cache, or an
                                    ImmediateResponse 429/503 with
                                    x-llm-d-request-dropped-reason per
                                    flow-control.md:369-409)
  Envoy -> response_headers        (record status; CONTINUE)
  stream end                       (release inflight accounting)

Failure semantics (flow-control.md:345-359): pipeline errors abort the
stream with a gRPC error — Envoy's `failure_mode_allow` then decides
FailOpen (route unpicked) vs FailClose (reject). Explicit rejections
(flow control, admitters) are ImmediateResponses, which Envoy returns to
the client in BOTH failure modes.
"""

from __future__ import annotations

import asyncio
import logging
import time

import grpc

from llmd_tpu.epp import extproc_pb as pb
from llmd_tpu.epp.flow_control import OUTCOME_HTTP, Outcome
from llmd_tpu.epp.handler import ParseError, parse_request
from llmd_tpu.epp.scheduler import NoEndpointsError
from llmd_tpu.epp.types import HDR_DROP_REASON, HDR_ENCODER, HDR_PREFILLER
from llmd_tpu.obs.tracing import get_tracer

log = logging.getLogger(__name__)

METHOD = "/envoy.service.ext_proc.v3.ExternalProcessor/Process"
# The Gateway-API inference-extension destination header (GAIE protocol;
# Envoy's original_dst cluster routes on it).
HDR_DESTINATION = "x-gateway-destination-endpoint"
HDR_ENDPOINT = "x-llm-d-endpoint"


class ExtProcSession:
    """One gRPC stream == one HTTP request being processed."""

    def __init__(self, router) -> None:
        self.router = router
        self.headers: dict[str, str] = {}
        self.body = bytearray()
        self.req = None
        self.pod = None
        self.t_routed: float | None = None
        self._flow_held = False
        self._ok = False

    async def on_message(self, msg: pb.ProcessingRequest) -> bytes | None:
        if msg.kind == "request_headers":
            self.headers = msg.headers
            if msg.end_of_stream:
                # Bodyless request (GET /v1/models etc): route on headers.
                return await self._route()
            return pb.encode_common_response("request_headers")
        if msg.kind == "request_body":
            self.body.extend(msg.body)
            if msg.end_of_stream:
                return await self._route()
            return None  # streamed chunk; wait for end_of_stream
        if msg.kind == "response_headers":
            status = msg.headers.get(":status", "")
            if self.req is not None and self.pod is not None:
                ttft_ms = None
                if self.t_routed is not None and status.startswith("2"):
                    ttft_s = time.monotonic() - self.t_routed
                    ttft_ms = ttft_s * 1e3
                    # Mirror the fused proxy's accounting (server.py): the
                    # latency-aware scorers and PrefixCacheAffinityFilter's
                    # TTFT load gate read these attrs, and Envoy is the
                    # EPP's primary deployment shape.
                    self.pod.attrs["LastTTFT"] = ttft_s
                    self._ok = True
                # Fire-and-forget like the fused proxy (server.py): a slow
                # observer (predictor training POST) must not hold Envoy's
                # response delivery.
                task = asyncio.ensure_future(
                    self.router._run_observers(self.req, self.pod, ttft_ms, None)
                )
                self.router._observer_tasks.add(task)
                task.add_done_callback(self.router._observer_tasks.discard)
            return pb.encode_common_response("response_headers")
        if msg.kind in ("request_trailers", "response_trailers"):
            return pb.encode_common_response(msg.kind)
        if msg.kind == "response_body":
            return pb.encode_common_response("response_body")
        return None

    def close(self) -> None:
        """Stream end: release scheduling + flow-control accounting.

        The flow slot is held for the whole stream (Envoy is proxying the
        request until it closes), matching the fused proxy's release-in-
        finally — releasing at schedule time would make the max_inflight
        saturation gate count near-zero concurrency."""
        if self._flow_held:
            self._flow_held = False
            self.router.flow.release()
        if self.pod is not None:
            if self._ok and self.t_routed is not None:
                # E2E closes when Envoy finishes proxying the stream —
                # same point the fused proxy records it (server.py).
                self.pod.attrs["LastE2E"] = time.monotonic() - self.t_routed
            self.pod.inflight = max(0, self.pod.inflight - 1)
            if self.req is not None:
                self.pod.inflight_tokens = max(
                    0, self.pod.inflight_tokens - self.req.approx_prompt_tokens
                )
                self.router.scheduler.notify_complete(self.req, self.pod)
            self.pod = None

    # -------------------------------------------------------------- core

    def _reject(self, status: int, reason: str) -> bytes:
        return pb.encode_immediate_response(
            status,
            headers={HDR_DROP_REASON: reason},
            body=(
                b'{"error": {"message": "%s"}}' % reason.encode()
            ),
            details=reason,
        )

    async def _route(self) -> bytes:
        router = self.router
        router.metrics.requests_total += 1
        path = self.headers.get(":path", "/v1/completions")
        raw = bytes(self.body)
        try:
            req = parse_request(path, self.headers, raw, router.default_parser)
        except ParseError as e:
            return self._reject(400, str(e))
        self.req = req
        span = get_tracer().start_span(
            "router.extproc",
            traceparent=self.headers.get("traceparent"),
            kind="SPAN_KIND_SERVER",
        )
        span.set("gen_ai.request.model", req.model)
        req.scratch["span"] = span
        try:
            return await self._route_inner(req, raw, span)
        finally:
            span.end()

    async def _route_inner(self, req, raw: bytes, span) -> bytes:
        router = self.router
        for adm in router.admitters:
            if not adm.needs_producers:
                reason = adm.admit(req)
                if reason is not None:
                    return self._reject(429, reason)
        outcome = await router.flow.enqueue_and_wait(req, nbytes=len(raw))
        span.set("llm_d.flow_control.outcome", str(outcome.value))
        if outcome is not Outcome.DISPATCHED:
            status, reason = OUTCOME_HTTP[outcome]
            return self._reject(status, reason)
        handed_off = False
        try:
            for producer in router.producers:
                try:
                    await producer.produce(req, router.store.list())
                except Exception:
                    log.exception(
                        "data producer %s failed", type(producer).__name__
                    )
            for adm in router.admitters:
                if adm.needs_producers:
                    reason = adm.admit(req)
                    if reason is not None:
                        return self._reject(429, reason)
            router.metrics.scheduling_attempts += 1
            try:
                result = router.scheduler.schedule(req, router.store.list())
            except NoEndpointsError as e:
                router.metrics.scheduling_errors += 1
                return self._reject(503, f"no-endpoints: {e}")
            pod = result.primary
            span.set("llm_d.decision.endpoint", pod.address)
            set_headers = {
                HDR_DESTINATION: pod.address,
                HDR_ENDPOINT: pod.address,
                "x-request-id": req.request_id,
            }
            if result.prefill is not None:
                set_headers[HDR_PREFILLER] = result.prefill.address
            if result.encode is not None:
                set_headers[HDR_ENCODER] = result.encode.address
            # Scheduling + flow accounting mirrors the fused proxy: both
            # held until stream close (Envoy owns the actual proxying).
            pod.inflight += 1
            pod.inflight_tokens += req.approx_prompt_tokens
            self.pod = pod
            self.t_routed = time.monotonic()
            self._flow_held = True
            handed_off = True
            kind = "request_body" if self.body else "request_headers"
            return pb.encode_common_response(
                kind, set_headers=set_headers, clear_route_cache=True
            )
        finally:
            if not handed_off:
                router.flow.release()


class ExtProcServer:
    """grpc.aio server speaking the ext-proc protocol around a Router."""

    def __init__(self, router, host: str = "127.0.0.1", port: int = 0) -> None:
        self.router = router
        self.host = host
        self.port = port
        self._server: grpc.aio.Server | None = None

    async def _process(self, request_iterator, context):
        session = ExtProcSession(self.router)
        try:
            async for raw in request_iterator:
                msg = pb.parse_processing_request(raw)
                if msg is None:
                    continue
                try:
                    reply = await session.on_message(msg)
                except Exception as e:  # pipeline failure -> FailOpen/Close
                    log.exception("ext-proc pipeline error")
                    await context.abort(
                        grpc.StatusCode.INTERNAL, f"epp pipeline error: {e}"
                    )
                    return
                if reply is not None:
                    yield reply
        finally:
            session.close()

    async def start(self) -> int:
        handler = grpc.stream_stream_rpc_method_handler(
            self._process,
            request_deserializer=None,
            response_serializer=None,
        )
        generic = grpc.method_handlers_generic_handler(
            "envoy.service.ext_proc.v3.ExternalProcessor",
            {"Process": handler},
        )
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((generic,))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self.router.flow.start()  # idempotent; gRPC-only deployments
        await self._server.start()
        log.info("ext-proc EPP listening on %s:%d", self.host, self.port)
        return self.port

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None


async def run_extproc(router, host: str, port: int) -> None:
    server = ExtProcServer(router, host, port)
    await server.start()
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()
