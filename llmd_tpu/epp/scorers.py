"""Scorer plugins (reference scheduling.md:85-102).

All scores are normalized to [0, 1], higher = better; profiles combine them
with configured weights (scheduling.md:60-68).
"""

from __future__ import annotations

import collections

from llmd_tpu import clock

from llmd_tpu.epp.plugins import Scorer, register
from llmd_tpu.epp.prefix_approx import ApproxPrefixIndex, prompt_block_hashes
from llmd_tpu.epp.types import (
    KV_CACHE_USAGE,
    RUNNING_REQUESTS,
    WAITING_QUEUE_SIZE,
    Endpoint,
    LLMRequest,
)


@register("queue-scorer")
class QueueScorer(Scorer):
    """Least waiting-queue depth wins (scheduling.md:94)."""

    def score(self, req, pods):
        qs = {p.address: p.attr(WAITING_QUEUE_SIZE) for p in pods}
        worst = max(qs.values(), default=0.0)
        if worst <= 0:
            return {a: 1.0 for a in qs}
        return {a: 1.0 - q / worst for a, q in qs.items()}


@register("kv-cache-utilization-scorer")
class KVCacheUtilizationScorer(Scorer):
    """Free KV headroom wins (scheduling.md:92)."""

    def score(self, req, pods):
        return {p.address: max(0.0, 1.0 - p.attr(KV_CACHE_USAGE)) for p in pods}


@register("running-requests-scorer")
class RunningRequestsScorer(Scorer):
    """Fewest running requests wins; blends the polled metric with the
    EPP's own inflight count (fresher between scrapes)."""

    def score(self, req, pods):
        load = {
            p.address: max(p.attr(RUNNING_REQUESTS), float(p.inflight)) for p in pods
        }
        worst = max(load.values(), default=0.0)
        if worst <= 0:
            return {a: 1.0 for a in load}
        return {a: 1.0 - v / worst for a, v in load.items()}


@register("token-load-scorer")
class TokenLoadScorer(Scorer):
    """Fewest in-flight routed tokens wins (scheduling.md:97 token-load)."""

    def score(self, req, pods):
        load = {p.address: float(p.inflight_tokens) for p in pods}
        worst = max(load.values(), default=0.0)
        if worst <= 0:
            return {a: 1.0 for a in load}
        return {a: 1.0 - v / worst for a, v in load.items()}


@register("session-affinity-scorer")
class SessionAffinityScorer(Scorer):
    """Sticky routing by session: the pod that served this session's last
    request scores 1 (scheduling.md:98). Session key = x-session-id header
    or the fairness id."""

    def __init__(self, max_sessions: int = 100_000, ttl_s: float = 3600.0) -> None:
        self._lru: collections.OrderedDict[str, tuple[str, float]] = (
            collections.OrderedDict()
        )
        self.max_sessions = max_sessions
        self.ttl_s = ttl_s

    @staticmethod
    def _key(req: LLMRequest) -> str | None:
        return req.headers.get("x-session-id") or req.fairness_id or None

    def score(self, req, pods):
        key = self._key(req)
        if key is None:
            return {p.address: 0.0 for p in pods}
        entry = self._lru.get(key)
        if entry is None or clock.monotonic() - entry[1] > self.ttl_s:
            return {p.address: 0.0 for p in pods}
        return {p.address: 1.0 if p.address == entry[0] else 0.0 for p in pods}

    def on_routed(self, req, pod):
        key = self._key(req)
        if key is None:
            return
        self._lru[key] = (pod.address, clock.monotonic())
        self._lru.move_to_end(key)
        while len(self._lru) > self.max_sessions:
            self._lru.popitem(last=False)


@register("no-hit-lru-scorer")
class NoHitLRUScorer(Scorer):
    """For requests with NO prefix-cache hit anywhere: prefer the endpoint
    least-recently chosen by this scorer, spreading cold prompts round-robin
    instead of piling them on the momentarily-emptiest pod
    (scheduling.md:99 no-hit-lru)."""

    def __init__(self) -> None:
        self._last_routed: dict[str, float] = {}

    def score(self, req, pods):
        # Only active when the prefix producer found no hit (scratch flag).
        if req.scratch.get("prefix_hit", False):
            return {p.address: 0.0 for p in pods}
        ranked = sorted(pods, key=lambda p: self._last_routed.get(p.address, 0.0))
        n = len(ranked)
        if n <= 1:
            return {p.address: 1.0 for p in ranked}
        return {p.address: 1.0 - i / (n - 1) for i, p in enumerate(ranked)}

    def on_routed(self, req, pod):
        self._last_routed[pod.address] = clock.monotonic()


@register("prefix-cache-scorer")
class PrefixCacheScorer(Scorer):
    """Approximate prefix-affinity scoring (prefix-cache-aware-routing.md).

    Score = matched-prefix blocks / total prompt blocks for each endpoint;
    the index is updated on routing decisions. Sets scratch['prefix_hit']
    for the no-hit-lru scorer pairing (scheduling.md:99).
    """

    def __init__(
        self,
        block_chars: int = 256,
        max_entries: int = 500_000,
        max_prefix_blocks: int = 1024,
    ) -> None:
        self.index = ApproxPrefixIndex(block_chars, max_entries, max_prefix_blocks)

    def score(self, req, pods):
        hashes = prompt_block_hashes(req, self.index)
        if not hashes:
            req.scratch["prefix_hit"] = False
            return {p.address: 0.0 for p in pods}
        matches = self.index.match_lengths(hashes)
        req.scratch["prefix_hit"] = bool(matches)
        total = len(hashes)
        scores = {p.address: matches.get(p.address, 0) / total for p in pods}
        # Per-endpoint matched fraction for the disagg decider
        # (scheduler.DisaggProfileHandler._wants_prefill).
        req.scratch.setdefault("prefix_match_frac", {}).update(scores)
        return scores

    def on_routed(self, req, pod):
        hashes = prompt_block_hashes(req, self.index)
        if hashes:
            self.index.record_routed(hashes, pod.address)

    def on_endpoint_removed(self, address: str) -> None:
        self.index.evict_endpoint(address)


# Tri-state adapter residency weights (multi-tenant-lora.md), exactly
# parallel to the prefix index's resident/store/recompute table
# (kv-federation.md): a replica holding the adapter in an HBM slot
# serves it at full speed; one holding it only in the host-RAM registry
# pays a cold slot install; one that never loaded it pays the full
# fetch + install (and, pool-full, queueing behind pinned slots).
DEFAULT_LORA_TIER_WEIGHTS = {
    "resident": 1.0,
    "registered": 0.5,
    "cold": 0.0,
}

LORA_TIER_WEIGHTS_ENV = "LLMD_LORA_TIER_WEIGHTS"


def lora_tier_weights_from_env(raw: str | None = None) -> dict[str, float]:
    """The deployment's adapter-residency weight table: defaults
    overlaid with ``LLMD_LORA_TIER_WEIGHTS`` (``tier=weight,...`` — the
    same syntax as ``LLMD_PREFIX_TIER_WEIGHTS``)."""
    import os

    from llmd_tpu.events.index import parse_tier_weights

    weights = dict(DEFAULT_LORA_TIER_WEIGHTS)
    if raw is None:
        raw = os.environ.get(LORA_TIER_WEIGHTS_ENV, "")
    if raw:
        weights.update(parse_tier_weights(raw))
    return weights


@register("lora-affinity-scorer")
class LoraAffinityScorer(Scorer):
    """Tri-state adapter-residency scoring (scheduling.md:96 +
    docs/architecture/multi-tenant-lora.md): resident HBM slot >
    one-install-away in the replica's adapter registry > cold load.

    Residency comes from the ``resident_lora_adapters`` /
    ``available_lora_adapters`` labels of ``vllm:lora_requests_info``
    (data-layer attrs ``ResidentAdapters`` / ``AvailableAdapters``,
    refreshed by the metrics collector). Engines predating the paged
    pool emit no resident label; their running/waiting
    (``LoadedAdapters``) list stands in for residency. Weights are
    configurable per deployment: defaults < ``LLMD_LORA_TIER_WEIGHTS``
    env < scorer ``tier_weights`` parameters < the router's
    ``--lora-tier-weights`` flag."""

    def __init__(self, tier_weights: dict | None = None) -> None:
        self.tier_weights = lora_tier_weights_from_env()
        if tier_weights:
            self.tier_weights.update(
                {k: float(v) for k, v in tier_weights.items()}
            )

    def score(self, req, pods):
        adapter = req.body.get("model") or req.model
        w = self.tier_weights
        out = {}
        for p in pods:
            resident = (
                p.attrs.get("ResidentAdapters")
                or p.attrs.get("LoadedAdapters")
                or []
            )
            available = p.attrs.get("AvailableAdapters") or []
            if adapter in resident:
                out[p.address] = w["resident"]
            elif adapter in available:
                out[p.address] = w["registered"]
            else:
                out[p.address] = w["cold"]
        return out


@register("topology-affinity-scorer")
class TopologyAffinityScorer(Scorer):
    """TPU-slice-topology-aware pairing (north-star deliverable: prefix
    and latency routing must become slice-topology aware).

    Anchored on an earlier profile's pick in the same scheduling pass
    (DisaggProfileHandler runs decode before prefill), endpoints score:
    same host 1.0 > same slice 0.75 > elsewhere 0.0 — a same-slice P->D
    pair ships KV over ICI; cross-slice pays DCN. Locality labels:
    ``llm-d.ai/slice`` (set explicitly or derived by pod discovery from
    the LeaderWorkerSet group) and ``llm-d.ai/node`` (folded in by
    discovery from the pod's node).
    """

    SLICE_LABEL = "llm-d.ai/slice"
    NODE_LABEL = "llm-d.ai/node"

    def __init__(self, anchor_profiles: tuple = ("decode",)) -> None:
        self.anchor_profiles = tuple(anchor_profiles)

    def _anchor(self, req: LLMRequest) -> Endpoint | None:
        picks = req.scratch.get("profile_picks", {})
        for name in self.anchor_profiles:
            ep = picks.get(name)
            if ep is not None:
                return ep
        return None

    def score(self, req, pods):
        anchor = self._anchor(req)
        if anchor is None:
            return {p.address: 0.0 for p in pods}
        a_node = anchor.labels.get(self.NODE_LABEL)
        a_slice = anchor.labels.get(self.SLICE_LABEL)
        out = {}
        for p in pods:
            if a_node and p.labels.get(self.NODE_LABEL) == a_node:
                out[p.address] = 1.0
            elif a_slice and p.labels.get(self.SLICE_LABEL) == a_slice:
                out[p.address] = 0.75
            else:
                out[p.address] = 0.0
        return out
