"""Request Handler: parser plugins + admitters + data producers.

Reference: docs/architecture/core/router/epp/request-handling.md:50-86 —
the `openai-parser` understands /v1/chat/completions, /v1/completions,
/v1/embeddings; DataProducers annotate the request (prefix hashes, inflight
load, predicted latency) before admission and scheduling; Admitters can
reject up front. Header contract: docs/api-reference/epp-http-headers.md.
"""

from __future__ import annotations

import hashlib
import json
import uuid
from typing import Any

from llmd_tpu.epp.types import (
    BATCH_PRIORITY,
    HDR_FAIRNESS_ID,
    HDR_OBJECTIVE,
    HDR_PRIORITY,
    HDR_TPOT_SLO,
    HDR_TTFT_SLO,
    LLMRequest,
)

GENERATE_PATHS = {
    "/v1/completions",
    "/v1/chat/completions",
    "/v1/embeddings",
    "/v1/conversations",
    "/v1/responses",
}

# vLLM gRPC surface (reference request-handling.md: `vllmgrpc-parser`
# handles Generate/Embed, token-in/token-out only). We accept the
# gRPC-JSON-transcoded form of those RPCs on these paths.
VLLMGRPC_PATHS = {
    "/vllm.Generation/Generate",
    "/vllm.Generation/Embed",
}


class ParseError(ValueError):
    pass


def _float_hdr(h: dict[str, str], name: str) -> float | None:
    v = h.get(name)
    try:
        return float(v) if v is not None else None
    except ValueError:
        return None


def _band_priority(h: dict[str, str], priority: int) -> int:
    """Fold the batch-band header into the parsed priority: the batch
    processor marks offline work with `x-llmd-priority: batch`
    (docs/architecture/batch-processing.md), which clamps the request
    to the backfill band regardless of the body's integer — batch work
    must never smuggle itself into an interactive flow-control band by
    omitting the field. Other header values are ignored."""
    if h.get(HDR_PRIORITY, "").strip().lower() == "batch":
        return min(priority, BATCH_PRIORITY)
    return priority


def _common_kwargs(h: dict[str, str]) -> dict[str, Any]:
    """LLMRequest fields every parser derives from headers the same way."""
    return {
        "request_id": h.get("x-request-id") or f"epp-{uuid.uuid4().hex}",
        "headers": h,
        "fairness_id": h.get(HDR_FAIRNESS_ID, ""),
        "ttft_slo_ms": _float_hdr(h, HDR_TTFT_SLO),
        "tpot_slo_ms": _float_hdr(h, HDR_TPOT_SLO),
    }


# Visual-token estimation defaults (reference token-producer `estimate`:
# e-p-d-disaggregation.values.yaml:31-40 — defaultResolution 1280x720,
# dynamic factor 1024 pixels/token).
MM_DEFAULT_WIDTH = 1280
MM_DEFAULT_HEIGHT = 720
MM_PIXELS_PER_TOKEN = 1024
MM_TOKEN_CAP = 16384


def estimate_mm_tokens(item: dict) -> int:
    w = int(item.get("width") or MM_DEFAULT_WIDTH)
    h = int(item.get("height") or MM_DEFAULT_HEIGHT)
    return max(1, min(MM_TOKEN_CAP, (w * h) // MM_PIXELS_PER_TOKEN))


def _mm_ref(url: str) -> str:
    """Stable content reference for an image URL / data URL. Folded into
    the prompt text so prefix hashing distinguishes different images
    (the reference's multimodal key folding, kv-indexer.md:145-151)."""
    return hashlib.sha256(url.encode()).hexdigest()[:24]


def _messages_text(msgs: list, mm_items: list[dict] | None = None) -> str:
    parts = []
    for m in msgs:
        if not isinstance(m, dict):
            continue
        c = m.get("content") or ""
        if isinstance(c, list):
            buf = []
            for p in c:
                if not isinstance(p, dict):
                    continue
                if p.get("type") == "image_url" or "image_url" in p:
                    url = (p.get("image_url") or {})
                    url = url.get("url", "") if isinstance(url, dict) else str(url)
                    ref = _mm_ref(url)
                    buf.append(f"<|image:{ref}|>")
                    # Only inline (data:) images count as schedulable mm
                    # items: the encode tier cannot fetch remote URLs, so
                    # reserving an encode worker for one wastes the slot.
                    # Remote URLs still fold a marker for prefix affinity.
                    if mm_items is not None and url.startswith("data:"):
                        item = {"ref": ref, "url": url}
                        for key in ("width", "height"):
                            if isinstance(p.get(key), int):
                                item[key] = p[key]
                        mm_items.append(item)
                else:
                    buf.append(p.get("text", ""))
            c = "".join(buf)
        parts.append(f"<|{m.get('role', 'user')}|>{c}")
    return "".join(parts)


def _prompt_from_body(
    path: str, body: dict, mm_items: list[dict] | None = None
) -> tuple[str, list[int] | None]:
    """Extract the cache-relevant prompt text (and token ids if given).

    mm_items are only collected for /chat/completions — the one generate
    surface the sidecar's encode phase can ship — so the scheduler never
    reserves an encode worker for a request that cannot reach it. Other
    message-shaped paths still fold image markers into the prompt text
    (prefix affinity) without scheduling an encode leg.
    """
    if path.endswith("/chat/completions"):
        return _messages_text(body.get("messages") or [], mm_items), None
    if path.endswith("/conversations"):
        return _messages_text(body.get("messages") or []), None
    prompt = body.get("prompt") or body.get("input") or ""
    if isinstance(prompt, list) and prompt and isinstance(prompt[0], dict):
        # /v1/responses structured input: a list of message objects.
        return _messages_text(prompt), None
    if isinstance(prompt, list):
        if prompt and isinstance(prompt[0], int):
            return "", list(prompt)
        if prompt and isinstance(prompt[0], str):
            return prompt[0], None
        if prompt and isinstance(prompt[0], list):
            return "", list(prompt[0])
        return "", None
    return str(prompt), None


def openai_parse(
    path: str, headers: dict[str, str], raw_body: bytes
) -> LLMRequest:
    """The openai-parser: HTTP request -> LLMRequest."""
    try:
        body: dict[str, Any] = json.loads(raw_body) if raw_body else {}
    except json.JSONDecodeError as e:
        raise ParseError(f"invalid JSON body: {e}") from e
    if not isinstance(body, dict):
        raise ParseError("request body must be a JSON object")
    mm_items: list[dict] = []
    prompt_text, prompt_ids = _prompt_from_body(path, body, mm_items)
    h = {k.lower(): v for k, v in headers.items()}
    try:
        priority = int(body.get("priority", 0) or 0)
    except (TypeError, ValueError) as e:
        raise ParseError(f"priority must be an int: {e}") from e
    return LLMRequest(
        model=str(body.get("model") or ""),
        prompt_text=prompt_text,
        prompt_token_ids=prompt_ids,
        body=body,
        path=path,
        streaming=bool(body.get("stream", False)),
        priority=_band_priority(h, priority),
        mm_items=mm_items,
        mm_token_estimate=sum(estimate_mm_tokens(i) for i in mm_items),
        **_common_kwargs(h),
    )


def vllmgrpc_parse(
    path: str, headers: dict[str, str], raw_body: bytes
) -> LLMRequest:
    """The vllmgrpc-parser: vLLM gRPC Generate/Embed (JSON-transcoded).

    Token-in/token-out only (reference request-handling.md:50-86 — the
    gRPC surface never carries prompt text), so prefix affinity runs on
    ``prompt_token_ids`` directly and no tokenizer round-trip is needed.
    """
    try:
        body: dict[str, Any] = json.loads(raw_body) if raw_body else {}
    except json.JSONDecodeError as e:
        raise ParseError(f"invalid JSON body: {e}") from e
    if not isinstance(body, dict):
        raise ParseError("request body must be a JSON object")
    ids = body.get("prompt_token_ids") or body.get("token_ids") or []
    if not isinstance(ids, list) or not all(isinstance(t, int) for t in ids):
        raise ParseError("prompt_token_ids must be a list of ints")
    params = body.get("sampling_params") or {}
    if not isinstance(params, dict):
        raise ParseError("sampling_params must be an object")
    try:
        priority = int(params.get("priority", 0) or 0)
    except (TypeError, ValueError) as e:
        raise ParseError(f"priority must be an int: {e}") from e
    h = {k.lower(): v for k, v in headers.items()}
    return LLMRequest(
        model=str(body.get("model") or ""),
        prompt_text="",
        prompt_token_ids=list(ids),
        body=body,
        path=path,
        streaming=bool(body.get("stream", False)),
        priority=_band_priority(h, priority),
        **_common_kwargs(h),
    )


def passthrough_parse(
    path: str, headers: dict[str, str], raw_body: bytes
) -> LLMRequest:
    """The passthrough-parser: opaque body, headers-only routing.

    For payloads the EPP must not interpret (reference
    request-handling.md:50-86): model comes from the `x-llm-d-model`
    header if present, prompt-aware plugins see an empty prompt, and the
    body bytes are forwarded untouched.
    """
    h = {k.lower(): v for k, v in headers.items()}
    try:
        priority = int(h.get("x-llm-d-priority", 0) or 0)
    except ValueError:
        priority = 0
    return LLMRequest(
        model=h.get("x-llm-d-model", ""),
        prompt_text="",
        prompt_token_ids=None,
        body={},
        path=path,
        streaming="text/event-stream" in h.get("accept", ""),
        priority=_band_priority(h, priority),
        **_common_kwargs(h),
    )


# Parser plugin registry (reference request-handling.md:50-55 names).
PARSERS = {
    "openai-parser": openai_parse,
    "vllmgrpc-parser": vllmgrpc_parse,
    "passthrough-parser": passthrough_parse,
}


def parse_request(
    path: str,
    headers: dict[str, str],
    raw_body: bytes,
    default_parser: str = "openai-parser",
) -> LLMRequest:
    """Dispatch to the parser owning this path (gRPC paths always win)."""
    if path in VLLMGRPC_PATHS:
        return vllmgrpc_parse(path, headers, raw_body)
    if path in GENERATE_PATHS:
        return openai_parse(path, headers, raw_body)
    return PARSERS[default_parser](path, headers, raw_body)


class Admitter:
    """Admission check; return a reason string to reject.

    `needs_producers=False` admitters are cheap and run *before* the
    flow-control queue, so doomed requests (e.g. oversized prompts) are
    429'd immediately instead of consuming queue capacity and a dispatch
    slot. Admitters that read DataProducer outputs (latency-slo-admitter)
    set `needs_producers=True` and run post-dispatch.
    """

    needs_producers = False

    def admit(self, req: LLMRequest) -> str | None:
        return None


class MaxPromptAdmitter(Admitter):
    def __init__(self, max_prompt_tokens: int = 1 << 20) -> None:
        self.max_prompt_tokens = max_prompt_tokens

    def admit(self, req: LLMRequest) -> str | None:
        if req.approx_prompt_tokens > self.max_prompt_tokens:
            return f"prompt exceeds {self.max_prompt_tokens} tokens"
        return None
