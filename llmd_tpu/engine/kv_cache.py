"""Paged KV cache: host-side page allocator with automatic prefix caching.

TPU-first design: the device-side pool is ONE stacked jax.Array per engine
(layer-major), so the per-layer cache slice inside ``lax.scan`` over layers is
a cheap dynamic-index, and page writes are scatters with static shapes. The
host side here manages page lifetimes: a free list, per-page refcounts, and a
content-addressed index of full pages (hash-chained over token ids) giving
automatic prefix caching -- the same chained-block-hash scheme the reference's
KV-cache indexer keys on (docs/architecture/advanced/kv-management/
kv-indexer.md:59-151) and vLLM-style APC semantics
(docs/architecture/core/model-servers.md:5-7).

Evicted-but-cached pages live in an LRU so a cache hit can resurrect them
until they are actually reused for new data.
"""

from __future__ import annotations

import collections
import functools
import threading
import dataclasses
import hashlib
from collections.abc import Iterable, Sequence

# Sentinel parent hash for the first page of a sequence.
_ROOT_HASH = b"llmd-root"


def hash_page(parent_hash: bytes, token_ids: Sequence[int], extra: bytes = b"") -> bytes:
    """Chained content hash of one full page.

    ``extra`` folds in LoRA / multimodal / cache-salt identity, mirroring the
    reference indexer's key-folding rules (kv-indexer.md:145-151).
    """
    h = hashlib.blake2b(digest_size=16)
    h.update(parent_hash)
    h.update(b"|")
    h.update(b",".join(str(t).encode() for t in token_ids))
    if extra:
        h.update(b"#")
        h.update(extra)
    return h.digest()


def page_hashes_for_tokens(
    token_ids: Sequence[int], page_size: int, extra: bytes = b""
) -> list[bytes]:
    """Hashes of all *full* pages covering a token prefix."""
    hashes: list[bytes] = []
    parent = _ROOT_HASH
    for start in range(0, len(token_ids) - page_size + 1, page_size):
        parent = hash_page(parent, token_ids[start : start + page_size], extra)
        hashes.append(parent)
    return hashes


@dataclasses.dataclass
class PageMeta:
    ref_count: int = 0
    content_hash: bytes | None = None


class KVEventSink:
    """Interface for KV-event emission (BlockStored/BlockRemoved/Cleared).

    The precise prefix-cache indexer subscribes to these (reference
    kv-indexer.md:59-63). The default sink drops events; the engine installs
    a ZMQ publisher when configured.
    """

    def blocks_stored(self, hashes: list[bytes], parent: bytes | None, token_ids: list[int]) -> None:
        pass

    def blocks_removed(self, hashes: list[bytes]) -> None:
        pass

    def all_cleared(self) -> None:
        pass



def _locked(fn):
    """Serialize an allocator method on the instance mutex (see
    PageAllocator.__init__: the multi-host pipelined import calls in
    from the fetch thread)."""

    @functools.wraps(fn)
    def inner(self, *a, **k):
        with self._lock:
            return fn(self, *a, **k)

    return inner


# The resource-lifecycle contract (static-analysis.md): every page
# reference minted by an acquire method below must be freed, committed
# into annotated owner state (`# llmd: owns(pages)`), or cross a
# declared `# llmd: transfers(pages)` boundary. The runtime twin
# (LLMD_LEAKSAN=1) mirrors the refcounts per page with acquisition
# backtraces and asserts zero outstanding at test teardown.
# llmd: resource(pages, recv=alloc, acquire=allocate|allocate_with_floor|touch:arg|lookup_and_touch_prefix|lookup_and_touch_hashes, release=free, transfer=commit_page)
class PageAllocator:
    """Refcounted page allocator with a content-addressed reuse index."""

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        enable_prefix_caching: bool = True,
        event_sink: KVEventSink | None = None,
    ) -> None:
        self.num_pages = num_pages
        self.page_size = page_size
        self.enable_prefix_caching = enable_prefix_caching
        self.event_sink = event_sink or KVEventSink()
        # Coarse mutex: the engine thread owns most calls, but the
        # multi-host pipelined P/D import allocates/frees/scatters
        # from the fetch executor thread (runner._dispatch_lock
        # orders the device ops; this orders the host bookkeeping).
        self._lock = threading.RLock()
        self._meta = [PageMeta() for _ in range(num_pages)]  # llmd: guarded_by(_lock)
        # Pages with ref_count == 0, LRU-ordered: left = oldest = evict first.
        # Freed cached pages are appended right so hot content survives longest.
        # llmd: guarded_by(_lock)
        self._free: collections.OrderedDict[int, None] = collections.OrderedDict(
            (i, None) for i in range(num_pages)
        )
        # content hash -> page id (only pages whose content is intact).
        self._cached: dict[bytes, int] = {}  # llmd: guarded_by(_lock)
        self.metrics_hits = 0  # llmd: guarded_by(_lock)
        self.metrics_queries = 0  # llmd: guarded_by(_lock)
        # Called on each newly registered full page (tiered offload pump).
        self.commit_hook = None

    # ------------------------------------------------------------------ #

    @property
    def num_free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def usage(self) -> float:
        with self._lock:
            return 1.0 - len(self._free) / self.num_pages

    def _cached_run_locked(self, hashes) -> list[int]:
        """Leading cached run for a hash chain, with hit accounting —
        the ONE walk every lookup variant delegates to (caller holds
        the lock)."""
        pages: list[int] = []
        for h in hashes:
            self.metrics_queries += 1
            pid = self._cached.get(h)
            if pid is None:
                break
            self.metrics_hits += 1
            pages.append(pid)
        return pages

    @_locked
    def lookup_cached_prefix(self, token_ids: Sequence[int], extra: bytes = b"") -> list[int]:
        """Longest run of consecutive cached full pages for this prompt.

        Returns the page ids (not yet referenced). Mirrors the reference
        indexer's longest-consecutive-prefix scoring (kv-indexer.md:120-135).
        """
        if not self.enable_prefix_caching:
            return []
        return self._cached_run_locked(
            page_hashes_for_tokens(token_ids, self.page_size, extra)
        )

    @_locked
    def peek_hash_run(self, hashes) -> int:
        """Length of the leading cached run for a pre-computed hash
        chain — NO touch, NO metrics. Probe-only (hybrid-hit candidate
        scans must not inflate prefix_cache_hit_rate or refresh LRU
        recency of pages they end up not using)."""
        n = 0
        for h in hashes:
            if h not in self._cached:
                break
            n += 1
        return n

    @_locked
    def lookup_and_touch_hashes(self, hashes) -> list[int]:
        """lookup_and_touch_prefix for a PRE-COMPUTED hash chain: the
        leading run of cached pages for exactly these hashes, touched
        atomically. Lets callers that already hold the chain (hybrid
        SWA-ring hits) avoid re-hashing the prompt."""
        if not self.enable_prefix_caching:
            return []
        pages = self._cached_run_locked(hashes)
        if pages:
            self.touch(pages)
        return pages

    @_locked
    def allocate_with_floor(self, n: int, floor: int) -> list[int]:
        """Allocate only if at least ``floor`` free pages REMAIN after —
        atomically, so concurrent reservers (streamed-import fetch
        threads) cannot jointly drain the decode headroom the floor
        protects. Raises NoFreePagesError when the floor would be
        breached."""
        if len(self._free) - n < floor:
            raise NoFreePagesError(n + floor, len(self._free))
        return self.allocate(n)

    @_locked
    def lookup_and_touch_prefix(
        self,
        token_ids: Sequence[int],
        extra: bytes = b"",
        max_pages: int | None = None,
    ) -> list[int]:
        """Atomic lookup_cached_prefix + touch of (up to ``max_pages``
        of) the hit run. The two-call form is NOT safe with concurrent
        allocators: a ref-0 cached page found by lookup can be stolen by
        a concurrent allocate() (e.g. the multi-host streamed-import
        fetch thread) before touch() claims it — touch would then
        ref-bump a page whose content is being overwritten, silently
        attending over another request's KV."""
        hashes = page_hashes_for_tokens(token_ids, self.page_size, extra)
        if max_pages is not None:
            hashes = hashes[:max_pages]
        return self.lookup_and_touch_hashes(hashes)

    @_locked
    def has_cached(self, content_hash: bytes) -> bool:
        return content_hash in self._cached

    @_locked
    def touch(self, page_ids: Iterable[int]) -> None:
        """Take a reference on cached pages (prefix-cache hit path)."""
        for pid in page_ids:
            meta = self._meta[pid]
            if meta.ref_count == 0:
                # Resurrect from the free LRU.
                del self._free[pid]
            meta.ref_count += 1

    @_locked
    def allocate(self, n: int) -> list[int]:
        """Allocate n fresh pages (ref=1), evicting cached content LRU-first."""
        if n > len(self._free):
            raise NoFreePagesError(n, len(self._free))
        out: list[int] = []
        for _ in range(n):
            pid, _ = self._free.popitem(last=False)
            meta = self._meta[pid]
            if meta.content_hash is not None:
                # Evict: the page is being reused for new content.
                self._cached.pop(meta.content_hash, None)
                self.event_sink.blocks_removed([meta.content_hash])
                meta.content_hash = None
            meta.ref_count = 1
            out.append(pid)
        return out

    @_locked
    def commit_page(
        self,
        page_id: int,
        content_hash: bytes,
        token_ids: list[int],
        parent: bytes | None,
    ) -> int:
        """Register a now-full page's content for reuse.

        Returns the canonical page id: if another page already holds this
        content, callers should deduplicate onto it (we keep it simple and
        just register the new page if the hash is absent).
        """
        if not self.enable_prefix_caching:
            return page_id
        existing = self._cached.get(content_hash)
        if existing is not None and existing != page_id:
            return existing
        self._cached[content_hash] = page_id
        self._meta[page_id].content_hash = content_hash
        self.event_sink.blocks_stored([content_hash], parent, token_ids)
        if self.commit_hook is not None:
            self.commit_hook(page_id, content_hash)
        return page_id

    @_locked
    def free(self, page_ids: Iterable[int]) -> None:
        for pid in page_ids:
            meta = self._meta[pid]
            if meta.ref_count <= 0:
                raise AssertionError(f"double free of page {pid}")
            meta.ref_count -= 1
            if meta.ref_count == 0:
                # Cached pages go to the LRU tail (evicted last); uncached
                # pages to the head (reused first).
                self._free[pid] = None
                if meta.content_hash is None:
                    self._free.move_to_end(pid, last=False)

    @_locked
    def clear(self) -> None:
        for h in list(self._cached):
            self._cached.pop(h)
        for meta in self._meta:
            meta.content_hash = None
        self.event_sink.all_cleared()

    @_locked
    def hit_ratio(self) -> float:
        if not self.metrics_queries:
            return 0.0
        return self.metrics_hits / self.metrics_queries


class NoFreePagesError(RuntimeError):
    def __init__(self, wanted: int, available: int) -> None:
        super().__init__(f"wanted {wanted} KV pages, {available} free")
        self.wanted = wanted
        self.available = available


# Runtime twin of the `# llmd: resource(pages, ...)` annotation above:
# with LLMD_LEAKSAN=1 every page reference is mirrored per allocator
# with an acquisition backtrace, and the conftest gate asserts zero
# outstanding refs at test teardown (static-analysis.md).
from llmd_tpu.analysis import sanitize as _sanitize

_sanitize.leaksan_register(
    PageAllocator, "pages",
    acquire={
        "allocate": lambda self, a, k, r: r,
        "touch": lambda self, a, k, r: list(a[0]) if a else [],
    },
    release={"free": lambda self, a, k, r: list(a[0]) if a else []},
)
