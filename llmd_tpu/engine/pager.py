"""Decode-time KV paging: bound resident HBM by the attention window.

For sliding-window models every attention read of a live sequence is
masked to the trailing ``window`` positions, yet the paged KV of a
million-token context keeps EVERY page resident for the sequence's whole
lifetime. The pager closes that gap (OffloadConfig.decode_paging):

- **Spill tick** — each step, pages of a running sequence that lie
  wholly below ``num_computed - (window + horizon)`` are copied to the
  tiered host cache (kvtransfer/offload.py) keyed by the same chained
  content hash the prefix index uses, then their HBM pages are freed.
  The stale physical ids stay in ``Request.block_ids`` (the logical page
  list must keep its length); every kernel read of those positions is
  window-masked, and the scheduler's release/truncate paths skip
  ``paged_out`` indexes. Resident HBM per sequence is then bounded by
  window + horizon + chunk, not by context length.

- **Park** — a preemption victim's computed KV is hosted and ALL its
  pages freed (``Scheduler.park_hook``); it re-queues with
  ``num_computed`` preserved instead of recomputing from zero.

- **Pump (restore)** — before each schedule, parked requests at the
  head of the queue get the attention window streamed back from the
  host tier into freshly allocated pages over the group-framed scatter
  leg (``scatter_pages(..., layers=)``, the v3 wire's per-cell write).
  While the fetch is in flight the scheduler treats the request as
  fetch-pending — a wait state, not a fault. A host-tier miss (the
  cache evicted the page under pressure) *refunds to recompute*: the
  request falls back to the plain recompute-preemption path, byte-
  identical to a never-parked preemption.

The fetch-horizon math: with page size P, window W and horizon H, a
sequence at position c needs pages ``[(c - W - H) // P, ...]`` resident;
everything below is spill-eligible, and a restore stages exactly that
range. H buys slack so decode never catches up with a page boundary
before the next tick (docs/architecture/long-context.md).
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING

import numpy as np

from llmd_tpu.engine.kv_cache import (
    _ROOT_HASH, NoFreePagesError, page_hashes_for_tokens,
)

if TYPE_CHECKING:  # pragma: no cover
    from llmd_tpu.engine.request import Request

logger = logging.getLogger(__name__)


class KVPager:
    """Spill/park/restore pump for decode-time KV paging.

    Requires: tiered offload enabled, every layer sliding-window
    (a full-attention layer reads arbitrarily far back, so nothing is
    ever cold), SWA ring OFF (the ring pool is its own window-bounding
    mechanism), single host (the group-framed scatter leg is
    leader-local). The engine checks those gates before constructing.
    """

    def __init__(
        self,
        runner,
        scheduler,
        allocator,
        host_cache,
        *,
        window: int,
        horizon: int,
        stream_groups: int = 1,
    ) -> None:
        self.runner = runner
        self.sched = scheduler
        self.allocator = allocator
        self.host = host_cache
        self.page = allocator.page_size
        self.window = int(window)
        self.horizon = int(horizon)
        self.keep_tokens = self.window + self.horizon
        self.stream_groups = max(1, int(stream_groups))
        # --- observability (EngineStats / Prometheus) ---
        self.paged_out_bytes = 0
        self.pages_spilled_total = 0
        self.pages_restored_total = 0
        self.prefetch_late_total = 0
        self.parks_total = 0
        self.refunds_total = 0

    # ------------------------------------------------------------------ #
    # hashing

    def _hashes(self, req: Request, upto: int) -> list[bytes]:
        """Chained content hashes of the first ``upto`` pages — identical
        to the prefix index's keys, so pager-hosted pages double as
        restore_for_prompt hits for future identical prompts."""
        return page_hashes_for_tokens(
            req.all_token_ids[: upto * self.page],
            self.page,
            extra=self.sched.hash_extra(req),
        )

    # ------------------------------------------------------------------ #
    # spill tick

    def tick(self, running: list[Request]) -> None:
        """Spill cold page ranges of live sequences to the host tier.

        A page is cold when every one of its positions is below the
        window + prefetch horizon of the sequence's computed frontier.
        In-flight (protected) sequences are skipped — the dispatched
        device programs still hold their page tables.
        """
        for req in running:
            if req.request_id in self.sched.protected:
                continue
            lo_page = (req.num_computed_tokens - self.keep_tokens) // self.page
            if lo_page <= 0:
                continue
            lo_page = min(lo_page, len(req.block_ids))
            idxs = [i for i in range(lo_page) if i not in req.paged_out]
            if not idxs:
                continue
            hashes = self._hashes(req, lo_page)
            self._spill(req, idxs, hashes)
            # Advance the commit chain past the spilled range:
            # _commit_full_pages at finish must never touch the stale
            # ids (the allocator may have recycled those pages). The
            # spilled range is contiguous from 0, so seeding is sound;
            # a prefix-cache hit may already have seeded further.
            _, committed = self.sched.commit_chain_state(req)
            if committed < lo_page:
                self.sched.seed_commit_chain(req, hashes[lo_page - 1], lo_page)

    def _spill(self, req: Request, idxs: list[int], hashes: list[bytes]) -> None:
        """Host-copy then free the given resident page indexes."""
        ids = [req.block_ids[i] for i in idxs]
        pages = self.runner.gather_pages(ids)  # [L, n, K, page, 2D]
        for j, i in enumerate(idxs):
            self.host.put(hashes[i], np.ascontiguousarray(pages[:, j]))
            req.paged_out[i] = hashes[i]
        self.allocator.free(ids)
        self.pages_spilled_total += len(idxs)
        self.paged_out_bytes += pages.nbytes

    # ------------------------------------------------------------------ #
    # park (Scheduler.park_hook)

    def park(self, req: Request) -> int:
        """Preemption-victim hook: host the computed KV, free all pages.

        Returns the token count preserved (page-aligned, always leaving
        at least one token to recompute so resume has a chunk to
        dispatch), or 0 when nothing is worth parking — the scheduler
        then falls through to plain recompute-preemption.
        """
        total = req.num_tokens
        bp = min(req.num_computed_tokens // self.page, (total - 1) // self.page)
        bp = min(bp, len(req.block_ids))
        if bp <= 0:
            return 0
        hashes = self._hashes(req, bp)
        need = [
            i for i in range(bp)
            if i not in req.paged_out and not self.host.has(hashes[i])
        ]
        if need:
            self._spill(req, need, hashes)
        # Everything still resident (hosted-but-not-yet-freed committed
        # pages, plus the partial frontier beyond bp whose tokens will
        # be recomputed) goes back to the allocator.
        ids = [b for i, b in enumerate(req.block_ids) if i not in req.paged_out]
        if ids:
            self.allocator.free(ids)
        req.block_ids = []
        req.paged_out = {i: hashes[i] for i in range(bp)}
        # Seed the commit chain so finish-time commits start past the
        # parked range (those pages live in the host tier, not HBM).
        self.sched.seed_commit_chain(req, hashes[bp - 1], bp)
        req.kv_fetch_pending = True
        self.parks_total += 1
        return bp * self.page

    # ------------------------------------------------------------------ #
    # restore pump

    def pump(self, waiting: list[Request]) -> None:
        """Stream attention windows back for parked requests.

        Called before each schedule(). Only the restore of the trailing
        window + horizon is staged — pages below it stay in the host
        tier (``paged_out``), exactly the spill tick's steady state, so
        resume residency equals live-decode residency.
        """
        for req in list(waiting):
            if req.kv_fetch_pending:
                self._restore(req)

    def _restore(self, req: Request) -> None:
        kept = req.num_computed_tokens
        bp = kept // self.page
        lo = max(0, kept - self.keep_tokens) // self.page
        idxs = list(range(lo, bp))
        if not idxs:
            req.kv_fetch_pending = False
            return
        pages = []
        for i in idxs:
            h = req.paged_out.get(i)
            got, tier = (None, None) if h is None else self.host.get_tagged(h)
            if got is None:
                # Host tier dropped the page under pressure (or the park
                # bookkeeping is gone): refund to recompute — the wire
                # failed, compute did not.
                self._refund(req)
                return
            if tier != "dram":
                # The page was not pre-staged in DRAM: the fetch arrived
                # late relative to the prefetch horizon.
                self.prefetch_late_total += 1
            pages.append(got)
        try:
            # llmd: allow(release-on-all-paths) -- every raise through the scatters frees via the except arm; past it ownership hands off into req.block_ids (owns(pages)) through the list concat below, which the handle-flow walk cannot see through
            new_ids = self.allocator.allocate(len(idxs))
        except NoFreePagesError:
            return  # still fetch-pending; retried next step
        try:
            arr = np.stack(pages, axis=1)  # [L, n, K, page, 2D]
            # Group-framed write-back: layer-sliced scatters ride the
            # same per-cell pool write as the v3 streamed import.
            num_layers = arr.shape[0]
            groups = min(self.stream_groups, num_layers)
            base, rem = divmod(num_layers, groups)
            l0 = 0
            for g in range(groups):
                span = base + (1 if g < rem else 0)
                if span == 0:
                    continue
                self.runner.scatter_pages(
                    new_ids, arr[l0 : l0 + span], layers=(l0, span)
                )
                l0 += span
        except Exception:
            self.allocator.free(new_ids)
            raise
        req.block_ids = [0] * lo + list(new_ids)
        for i in idxs:
            req.paged_out.pop(i, None)
        req.kv_fetch_pending = False
        self.pages_restored_total += len(idxs)

    def _refund(self, req: Request) -> None:
        """Fall back to recompute-from-zero (wire failure semantics)."""
        ids = [b for i, b in enumerate(req.block_ids) if i not in req.paged_out]
        if ids:
            self.allocator.free(ids)
        req.block_ids = []
        req.paged_out.clear()
        req.kv_fetch_pending = False
        req.num_computed_tokens = 0
        req.num_cached_tokens = 0
        # Reset the commit chain: nothing is committed any more.
        self.sched.seed_commit_chain(req, _ROOT_HASH, 0)
        self.refunds_total += 1
        logger.info(
            "kv pager refund: %s recomputes from zero (host tier miss)",
            req.request_id,
        )
