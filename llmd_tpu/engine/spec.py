"""Model-free draft proposal for speculative decoding.

Prompt-lookup / n-gram drafting (Saxena, "Prompt Lookup Decoding", 2023):
the draft for a sequence comes from the sequence's OWN token history —
match the trailing n-gram of prompt+output against earlier positions and
propose the tokens that followed a match. No draft model, no extra
weights, no device work: exactly the free lunch for the
RAG/agentic/summarization workloads the llm-d reference stack routes,
where outputs quote their inputs (and greedy decode loops quote
themselves).

Cost discipline: the proposer runs per decode row per engine step, so it
must be O(new tokens) there, not O(history). Each request carries an
incremental index of its min_match-gram end positions (history is
append-only — preemption folds output into the prompt without changing
the token sequence, so the index never invalidates); a proposal is one
dict lookup plus a short scoring scan of the most recent candidates.
Verification (ModelRunner's verify step) and acceptance (scheduler +
sampler.accept_draft_tokens) own correctness; a bad draft costs only the
wasted verify columns, never a wrong token.
"""

from __future__ import annotations


class NgramProposer:
    """Drafts up to ``k`` tokens by suffix n-gram lookup over the
    sequence's own token history.

    Candidates are every earlier end position of the trailing
    ``min_match``-gram (a longer suffix match always contains a trailing
    min_match match at the same end position, so the index misses
    nothing). They are scored by backward extension length — a longer
    matched context is likelier to predict the true continuation, which
    is what acceptance length, the whole win, depends on — with full-k
    continuations and recency as tiebreaks (a run of repeats always has
    a near-tail match whose continuation is one token; the full window
    behind it is the one that tracks the cycle).
    """

    # Candidate cap per proposal: periodic histories match at EVERY
    # period offset; scoring the most recent few is enough (and keeps
    # the host cost flat however long the sequence grows).
    _MAX_CANDIDATES = 32

    def __init__(self, min_match: int = 2, max_match: int = 8) -> None:
        if min_match < 1:
            raise ValueError(f"min_match={min_match} must be >= 1")
        self.min_match = min_match
        self.max_match = max(max_match, min_match)

    @staticmethod
    def new_state() -> dict:
        """Fresh per-request index (held on Request.spec_gram_state):
        {gram tuple -> [end positions]} plus the indexed-up-to mark."""
        return {"idx": {}, "upto": 0}

    def propose(self, tokens: list[int], k: int, state: dict | None = None) -> list[int]:
        """Draft up to ``k`` continuation tokens for ``tokens`` (the full
        committed prompt+output history). Returns [] when the trailing
        min_match-gram never occurred earlier — drafting nothing is
        free; drafting wrongly costs a verify column."""
        n = len(tokens)
        mm = self.min_match
        if k <= 0 or n < mm + 1:
            return []
        if state is None:
            state = self.new_state()
        idx = state["idx"]
        # Index the gram ENDING at each new position (end == n excluded:
        # that is the suffix itself; it becomes a real candidate once
        # later tokens append past it).
        for e in range(max(state["upto"], mm), n):
            idx.setdefault(tuple(tokens[e - mm : e]), []).append(e)
        state["upto"] = n
        ends = idx.get(tuple(tokens[n - mm :]))
        if not ends:
            return []
        best_end, best_score = -1, None
        for e in reversed(ends[-self._MAX_CANDIDATES :]):
            ext = mm
            while (
                ext < self.max_match
                and e > ext
                and tokens[e - ext - 1] == tokens[n - ext - 1]
            ):
                ext += 1
            score = (ext, e + k <= n)
            if best_score is None or score > best_score:
                best_score, best_end = score, e
        return list(tokens[best_end : best_end + k])
