"""Engine: continuous batching, paged KV cache, model runner, sampling.

Exports resolve lazily (PEP 562): LLMEngine pulls jax at import, but
accelerator-free consumers — the EPP's precise-prefix scorer reaches
``llmd_tpu.engine.kv_cache.page_hashes_for_tokens`` (pure stdlib), and
the fleet simulator imports the EPP config that registers it — must be
able to touch the package without a jax install.
"""

__all__ = ["LLMEngine", "Request", "SamplingParams"]


def __getattr__(name):
    if name == "LLMEngine":
        from llmd_tpu.engine.engine import LLMEngine

        return LLMEngine
    if name in ("Request", "SamplingParams"):
        from llmd_tpu.engine import request

        return getattr(request, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
