"""Engine: continuous batching, paged KV cache, model runner, sampling."""

from llmd_tpu.engine.engine import LLMEngine  # noqa: F401
from llmd_tpu.engine.request import Request, SamplingParams  # noqa: F401
