"""LLMEngine: the continuous-batching serving engine.

Plays the role vLLM plays in the reference stack (L3 of SURVEY.md's layer
map): accepts requests, schedules them with chunked prefill + paged KV +
automatic prefix caching, steps the jitted model, streams outputs, and
exposes the queue/KV metrics the EPP scrapes
(docs/architecture/core/model-servers.md:38-52).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
import uuid
from dataclasses import dataclass, field

import jax
import numpy as np

from llmd_tpu import faults
from llmd_tpu.config import EngineConfig, swa_ring_spec
from llmd_tpu.engine.kv_cache import KVEventSink, PageAllocator
from llmd_tpu.engine.request import (
    FinishReason,
    Request,
    RequestOutput,
    RequestStatus,
    SamplingParams,
)
from llmd_tpu.engine.runner import (
    ModelRunner,
    PendingDecode,
    PendingPrefill,
    PendingUnified,
    StagedDecode,
    StagedUnified,
    StagedVerify,
    StagedVerifyWindow,
    StepResult,
)
from llmd_tpu.engine.scheduler import EngineScheduler, ScheduledBatch
from llmd_tpu.parallel.mesh import MeshContext, build_mesh


class SwaSectionCache:
    """Retained sliding-window sections for HYBRID prefix caching under
    the SWA ring (the reference's hybrid KV-cache manager role, pd gpu
    patch-decode.yaml:19).

    Ring pages are transient per sequence, so a bare full-pool prefix
    hit would skip sliding-layer KV that no longer exists. This cache
    keeps, per recently-prefilled prefix, a COPY of the ring's
    in-window section (the same [s0, n_pre) geometry the P/D transfer
    ships — SwaRingSpec.section) in ref-held SWA-pool pages. On a
    repeated prefix, a fresh ring is seeded from the section on device
    and the request starts at num_computed = n_pre * page: exactly the
    P/D preload path, sourced locally. LRU-capped; entries own their
    pages and free them on eviction."""

    def __init__(
        self, swa_allocator, runner, capacity: int, page_budget: int
    ) -> None:
        import collections

        self._alloc = swa_allocator
        self._runner = runner
        self.capacity = capacity
        # Retention pages are PROVISIONED on top of the ring pool
        # (engine sizing); this budget keeps retention from ever eating
        # ring capacity even transiently.
        self.page_budget = page_budget
        self.retained_pages = 0
        # key -> (s0, n_pre, [section page ids])
        # llmd: owns(pages)
        self._entries: "collections.OrderedDict[bytes, tuple]" = (
            collections.OrderedDict()
        )
        self.hits = 0
        self.captures = 0

    def capture(self, key: bytes, ring_ids: list[int], s0: int, n_pre: int) -> None:
        """Copy ring slots [s0, n_pre) into retained pages (device op,
        no host bytes). No-op if the key is already retained or the SWA
        pool lacks headroom (a ring allocation must never fail because
        retention hoarded pages)."""
        from llmd_tpu.engine.kv_cache import NoFreePagesError

        if self.capacity <= 0 or key in self._entries or n_pre <= s0:
            return
        cnt = n_pre - s0
        R = len(ring_ids)
        # Entry-count LRU + page budget, evicted BEFORE allocating so
        # the budget invariant holds at the allocate call.
        while self._entries and (
            len(self._entries) >= self.capacity
            or self.retained_pages + cnt > self.page_budget
        ):
            self.evict_one()
        if self.retained_pages + cnt > self.page_budget:
            return  # a single oversized section cannot fit the budget
        try:
            dst = self._alloc.allocate(cnt)
        except NoFreePagesError:
            # Pool transiently drained past the provisioned budget
            # (preload bursts hold extra rings): skip this capture.
            return
        self.retained_pages += cnt
        src = [ring_ids[l % R] for l in range(s0, n_pre)]
        try:
            self._runner.copy_pages_on_device(src, dst, swa=True)
        except BaseException:
            # A failed device copy must refund the retained pages, or
            # the ring pool permanently shrinks by `cnt` on every retry.
            self.retained_pages -= cnt
            self._alloc.free(dst)
            raise
        self._entries[key] = (s0, n_pre, dst)
        self.captures += 1

    def evict_one(self) -> bool:
        """Free the LRU retained section (ring-pressure relief: a live
        sequence's ring allocation outranks idle retention). Returns
        True if an entry was freed."""
        if not self._entries:
            return False
        _, (_, _, ids) = self._entries.popitem(last=False)
        self._alloc.free(ids)
        self.retained_pages -= len(ids)
        return True

    def has(self, key: bytes) -> bool:
        return key in self._entries

    def candidate_lengths(self, n_pre_max: int) -> list[int]:
        """Retained entry lengths usable for a prompt whose own
        preloadable span is ``n_pre_max`` pages, longest first: a
        section captured at k <= n_pre_max pages holds the window before
        continuation k*page, so an EXTENDED prompt sharing that prefix
        can still skip its first k pages (the multi-turn grow case)."""
        return sorted(
            {e[1] for e in self._entries.values() if e[1] <= n_pre_max},
            reverse=True,
        )

    def seed(self, key: bytes, ring_ids: list[int]) -> tuple[int, int] | None:
        """Seed a freshly allocated ring from the retained section.
        Returns (s0, n_pre) on success; None on miss."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        self._entries.move_to_end(key)
        s0, n_pre, ids = entry
        R = len(ring_ids)
        dst = [ring_ids[(s0 + i) % R] for i in range(n_pre - s0)]
        self._runner.copy_pages_on_device(ids, dst, swa=True)
        self.hits += 1
        return s0, n_pre

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "hits": self.hits,
            "captures": self.captures,
        }


@dataclass
class EngineStats:
    """The EPP metrics contract (model-servers.md:38-52)."""

    num_waiting: int = 0
    num_running: int = 0
    kv_usage: float = 0.0
    prefix_hit_ratio: float = 0.0
    num_pages: int = 0
    page_size: int = 0
    # SWA ring pool (kv_swa_ring): under P/D preload bursts the ring pool
    # is the binding admission constraint, so it must be visible to
    # utilization-based routing, not just the main pool.
    swa_ring_usage: float = 0.0
    swa_ring_pages: int = 0
    # Hybrid-APC section retention (SwaSectionCache)
    swa_sections: int = 0
    swa_section_hits: int = 0
    swa_section_captures: int = 0
    # counters
    prompt_tokens: int = 0
    generation_tokens: int = 0
    requests_finished: int = 0
    preemptions: int = 0
    # tiered offload (kv-offloader metrics)
    offload_pages: int = 0
    offload_fs_pages: int = 0
    offload_saves: int = 0
    offload_restores: int = 0
    # Cross-replica KV federation (docs/architecture/kv-federation.md):
    # the store client's read path (peer pulls / failed pulls / locate
    # misses), master-accepted publications from this replica, pages
    # this replica fetched from the store, and the prompt tokens whose
    # re-prefill those committed pages avoided.
    kvstore_pulls: int = 0
    kvstore_pull_failures: int = 0
    kvstore_misses: int = 0
    kv_federation_published: int = 0
    kv_federation_hits: int = 0
    recompute_avoided_tokens: int = 0
    # P/D KV transfer (reference operations-vllm.md transfer accounting)
    kv_exported_requests: int = 0
    kv_exported_bytes: int = 0
    kv_imported_requests: int = 0
    kv_imported_bytes: int = 0
    kv_import_failures: int = 0
    # Layer-streamed transfer (the v3 group-framed wire): (layer-group x
    # chunk) cells landed by streamed imports, and the last import's
    # first-group latency — the admission-gate leg of the pipeline
    # waterfall (kv-cache.md "layer-streamed import").
    kv_stream_groups_total: int = 0
    kv_stream_first_group_ms: float = 0.0
    # Publish-budget pacing (LLMD_KV_PUBLISH_BYTES_PER_S): bytes the
    # federation publisher delayed to keep publish-on-evict bursts off
    # the transfer NIC (kv-federation.md).
    kv_publish_paced_bytes_total: int = 0
    # LoRA (reference model-servers.md:78-89 lora_requests_info)
    max_lora: int = 0
    running_lora_adapters: tuple = ()
    waiting_lora_adapters: tuple = ()
    # Multi-tenant paged adapter pool (multi-tenant-lora.md): adapters
    # resident in HBM slots right now, idle residents LRU-evicted for
    # incoming tenants, requests that had to wait for a cold weight
    # install, and /v1/load_lora_adapter fetches that failed (surfaced
    # as 4xx). resident/available ride lora_requests_info labels so the
    # EPP's tri-state LoraAffinityScorer can route on residency.
    lora_pool_resident_adapters: int = 0
    lora_pool_evictions_total: int = 0
    lora_cold_loads_total: int = 0
    lora_load_failures_total: int = 0
    resident_lora_adapters: tuple = ()
    available_lora_adapters: tuple = ()
    # Step pipeline observability (async stepping, serve/metrics.py):
    # the host gap is the per-step host time the device sits idle for —
    # schedule + array build + dispatch + output assembly in sync mode,
    # only the post-readback reconcile/patch in async mode (the rest
    # overlaps device execution). Last value + running sum + step count
    # so a scrape (or the bench) can read both a gauge and a mean.
    engine_steps_total: int = 0
    step_host_gap_ms: float = 0.0
    step_host_gap_ms_total: float = 0.0
    # Speculative rows invalidated by a late finish/abort at reconcile
    # (EOS / stop token / max-tokens landed after the next batch was
    # staged against the optimistic one-token-per-decode assumption).
    async_rollbacks_total: int = 0
    # Speculative decoding (SchedulerConfig.speculative_ngram; the
    # propose/verify/accept contract in
    # docs/architecture/speculative-decoding.md): draft tokens proposed
    # and accepted across all verify steps, their ratio, and the
    # accepted-draft-length histogram — index j counts (spec row, step)
    # pairs that accepted exactly j draft tokens, so mean emitted
    # tokens/row/step reads as 1 + sum(j * hist[j]) / sum(hist).
    spec_proposed_tokens_total: int = 0
    spec_accepted_tokens_total: int = 0
    spec_acceptance_rate: float = 0.0
    spec_accepted_len_hist: tuple = ()
    # Fused verify windows (spec x decode_window composition): verify
    # row-iterations executed inside fused windows, and windowed rows
    # that went inactive (emission limit reached) before their window's
    # last iteration.
    spec_window_iters_total: int = 0
    spec_window_early_exit_total: int = 0
    # Decode-side device programs dispatched, and the ratio that is the
    # fused-window headline: decode dispatches per generated token —
    # fused decode windows and fused verify windows both push it down
    # by amortizing dispatch RTT over more emitted tokens.
    decode_dispatches_total: int = 0
    dispatches_per_emitted_token: float = 0.0
    # Unified single-dispatch steps (SchedulerConfig.unified_step): engine
    # steps whose entire window=1 batch — prefill chunks + decode rows +
    # one-shot verify rows — rode ONE ragged program. The family split of
    # decode_dispatches_total: unified_steps_total of those dispatches
    # came from the unified family, the rest from the split families.
    unified_steps_total: int = 0
    # EVERY device program engine steps dispatched (prefill bucket
    # groups + decode-side programs + unified programs): the unified
    # step's headline is step_dispatches_total / engine_steps_total
    # falling toward 1.0 on mixed workloads.
    step_dispatches_total: int = 0
    # Padding efficiency (the flattened-token step's headline,
    # SchedulerConfig.ragged_qlens): tokens the dispatched programs
    # computed for real vs the pad lanes their traced shapes paid on
    # top — the bucketed [B, Q] unified step pads every decode row to
    # the sub-row Q bucket; the flat stream pads only to the 16-token
    # T granule. padded / live is the padding-waste gauge.
    live_tokens_total: int = 0
    padded_tokens_total: int = 0
    # Per-row verify depth histogram (speculative engines): index d
    # counts decode rows dispatched with a 1 + draft width of exactly d
    # tokens (backed-off rows: 1; hot-draft rows: up to 1 + spec_k,
    # deeper windowed plans clamp to the top bucket). Two rows in
    # DIFFERENT buckets on one step is the per-row adaptive depth the
    # flattened step dispatches in one program.
    spec_row_depth_hist: tuple = ()
    # Batch serving tier (docs/architecture/batch-processing.md): the
    # backfill band's observability contract — waiting batch-band rows
    # (the engine-side backlog the WVA counts as deferrable demand),
    # tokens computed for batch rows, batch rows recompute-preempted
    # when interactive load returned, and the fraction of the LAST
    # step's token budget the band backfilled.
    batch_backlog_jobs: int = 0
    batch_tokens: int = 0
    batch_preemptions: int = 0
    batch_backfill_utilization: float = 0.0
    # Robustness trail (docs/architecture/fault-tolerance.md): watchdog
    # trips on the step loop, CRC-rejected KV bundles, transfers that
    # degraded to local recompute, and the per-(stage, policy)
    # transfer-failure breakdown — a failure that leaves no metric
    # trail is invisible to the SLO layer.
    engine_watchdog_stalls_total: int = 0
    kv_bundle_crc_failures_total: int = 0
    kv_recompute_fallbacks_total: int = 0
    # ((stage, policy), count) pairs; rendered as labeled series.
    kv_transfer_failures: tuple = ()
    # Mid-stream failover (docs/architecture/fault-tolerance.md, stream
    # continuation contract): requests admitted as RESUMES (prefill of
    # an already-delivered prefix continuing at the exact next output
    # position), the delivered tokens those admissions replayed as
    # committed prefix, and resume requests the serving layer REJECTED
    # (invalid history / unsupported shape) — a rejected resume is a
    # client-visible stream failure upstream, so it must leave a trail.
    stream_resumes_total: int = 0
    resume_replayed_tokens_total: int = 0
    stream_resume_failures_total: int = 0
    # Wide-EP MoE (docs/architecture/wide-ep.md): the per-expert load
    # census drained from the runner each step. moe_expert_tokens is the
    # cumulative routed-token count per LOGICAL expert (rendered as the
    # moe_expert_tokens_total labeled series — the EPLB control loop's
    # input); dropped slots are valid (token, expert) assignments that
    # lost the capacity race; peak demand is the largest observed
    # per-destination dispatch demand as a capacity-factor multiple (the
    # adaptive controller's input: >1.0 means the static factor would
    # have dropped); capacity_factor is the LIVE factor the compiled
    # programs were traced at; rebalances counts EPLB placements applied.
    moe_expert_tokens: tuple = ()
    moe_dropped_slots_total: int = 0
    moe_peak_demand: float = 0.0
    moe_capacity_factor: float = 0.0
    moe_rebalances_total: int = 0
    # Million-token context tier (docs/architecture/long-context.md):
    # bytes of live-sequence KV spilled to the host tier by the decode
    # pager, restores that were NOT fully pre-staged when the sequence
    # needed them (the pager's miss signal — late prefetches serialize a
    # host->HBM wait onto the decode path), and ring collective steps
    # the context-parallel prefill dispatched (cp per cp-prefill call).
    kv_paged_out_bytes: int = 0
    kv_pager_prefetch_late_total: int = 0
    cp_ring_steps_total: int = 0


@dataclass
class _InflightStep:
    """One dispatched-but-unread engine step (async stepping slot)."""

    batch: ScheduledBatch
    pending_prefill: PendingPrefill | None
    pending_decode: PendingDecode | None
    dispatch_time: float
    pending_unified: PendingUnified | None = None


class LLMEngine:
    def __init__(
        self,
        config: EngineConfig,
        mesh_ctx: MeshContext | None = None,
        params: dict | None = None,
        event_sink: KVEventSink | None = None,
    ) -> None:
        self.config = config
        import jax

        # Multi-host: staging programs (page gather/scatter) are lockstep-
        # broadcast to every process by the runner, so P/D transfer and
        # tiered offload compose with a multi-process mesh — the
        # reference's flagship 16P+16D wide-EP topology does exactly this
        # (wide-ep-lws/README.md + multi-node.md). The network-facing
        # halves (shipper server, host cache, store client) live on the
        # LEADER only; followers just mirror device programs.
        follower = jax.process_count() > 1 and jax.process_index() != 0
        self.ctx = mesh_ctx or build_mesh(config.parallel)
        # SWA ring (CacheConfig.swa_ring): sliding-window layers move to a
        # fixed per-sequence page ring in their own pool. Ring content is
        # transient per sequence; prefix caching stays ON for the main
        # (full-attention) pool and becomes HYBRID: hits are taken only
        # when a retained sliding section can seed the fresh ring
        # (SwaSectionCache — the reference's hybrid KV-cache manager
        # role). Tiered offload still refuses (host-cached pages would
        # lack sliding-layer KV).
        self._swa = swa_ring_spec(config.model, config.cache, config.scheduler)
        if self._swa is not None:
            if not config.scheduler.enable_chunked_prefill:
                raise ValueError(
                    "kv_swa_ring requires chunked prefill: a whole-prompt "
                    "chunk can exceed the ring span the step-write/read "
                    "invariant is sized for (SwaRingSpec.chunk_tokens)"
                )
            if config.offload is not None and config.offload.enabled:
                raise ValueError(
                    "kv_swa_ring does not compose with tiered KV offload: "
                    "host-cached pages would lack the sliding layers' KV "
                    "— disable one of the two"
                )
        # HYBRID prefix caching under the ring: the main pool (full-
        # attention layers) stays hashed/reusable; a hit is USABLE only
        # when the retained sliding section (SwaSectionCache) can seed
        # the fresh ring, so the scheduler's bare shortcut is disabled
        # (scheduler._apply_prefix_cache) and hits happen at admission.
        # With section retention off, hits are structurally impossible —
        # downgrade APC entirely so the engine doesn't hash and
        # advertise blocks (BlockStored events) a router would route to
        # in vain.
        prefix_caching = config.cache.enable_prefix_caching
        if (
            self._swa is not None
            and prefix_caching
            and config.cache.swa_section_cache <= 0
        ):
            logging.getLogger(__name__).info(
                "kv_swa_ring with swa_section_cache=0: disabling prefix "
                "caching (no retained sliding sections -> no usable hits)"
            )
            prefix_caching = False
        # Tiered offload wraps the event sink (device evictions of host-held
        # pages downgrade to cpu-tier stores instead of removals).
        self._host_cache = None
        self._kvstore_client = None
        self._federation = None
        if config.offload is not None and config.offload.enabled and not follower:
            from llmd_tpu.kvtransfer.offload import HostKVCache, TieredEventSink

            if config.offload.store_master_url:
                from llmd_tpu.federation import KVFederation
                from llmd_tpu.kvstore import CrossSliceStoreClient

                self._kvstore_client = CrossSliceStoreClient(
                    master_url=config.offload.store_master_url,
                    advertised_host=config.kv_host,
                    data_port=config.offload.store_data_port,
                    segment_bytes=config.offload.store_segment_bytes,
                )
                self._federation = KVFederation(
                    self._kvstore_client,
                    publish_policy=config.offload.publish_policy,
                    publish_min_hits=config.offload.publish_min_hits,
                )
            self._host_cache = HostKVCache(
                max_pages=config.offload.cpu_chunks,
                fs_dir=config.offload.fs_dir,
                fs_max_pages=config.offload.fs_max_pages,
                federation=self._federation,
            )
            event_sink = TieredEventSink(event_sink or KVEventSink(), self._host_cache)
            if self._federation is not None:
                # Accepted publications advertise the store tier
                # (BlockStored medium="store") through the same sink.
                self._federation.event_sink = event_sink
        self.allocator = PageAllocator(
            num_pages=config.cache.num_blocks,
            page_size=config.cache.page_size,
            enable_prefix_caching=prefix_caching,
            event_sink=event_sink,
        )
        # Hybrid-APC retention rides a PROVISIONED budget on top of the
        # ring pool (the auto-sized pool is exactly max_num_seqs rings —
        # retention must never eat ring capacity).
        self._swa_retention_budget = 0
        if (
            self._swa is not None
            and prefix_caching
            and config.cache.swa_section_cache > 0
        ):
            self._swa_retention_budget = (
                config.cache.swa_section_cache
                * self._swa.max_section_pages(config.cache.page_size)
            )
        self.swa_allocator = (
            PageAllocator(
                num_pages=self._swa.num_swa_blocks
                + self._swa_retention_budget,
                page_size=config.cache.page_size,
                enable_prefix_caching=False,
            )
            if self._swa is not None
            else None
        )
        self.scheduler = EngineScheduler(
            config.scheduler, config.cache, self.allocator,
            config.model.max_model_len,
            swa_allocator=self.swa_allocator,
            swa_ring_pages=self._swa.ring_pages if self._swa else 0,
            swa_chunk_tokens=self._swa.chunk_tokens if self._swa else 0,
        )
        self.runner = ModelRunner(
            config, self.ctx, params=params, swa_spec=self._swa
        )
        # Hybrid-APC section retention (ring engines with APC on).
        self._swa_sections = None
        if (
            self._swa is not None
            and prefix_caching
            and config.cache.swa_section_cache > 0
        ):
            self._swa_sections = SwaSectionCache(
                self.swa_allocator, self.runner,
                config.cache.swa_section_cache,
                self._swa_retention_budget,
            )
            self.scheduler.prefill_complete_hook = self._capture_swa_section
            self.scheduler.ring_pressure_hook = self._swa_sections.evict_one
        self.stats = EngineStats(
            num_pages=config.cache.num_blocks, page_size=config.cache.page_size
        )
        # Static surface of the adapter contract: present from the first
        # scrape, not the first step (load failures can precede steps).
        self.stats.max_lora = config.model.num_lora_adapters
        self._counter = itertools.count()
        self._embed_lock = threading.Lock()

        # Multi-tenant LoRA (docs/architecture/multi-tenant-lora.md): a
        # paged adapter pool — num_lora_adapters HBM slots over an
        # unbounded host-RAM registry. Requests naming a non-resident
        # adapter PARK in _lora_parked (the loading queue) and are
        # admitted at a step boundary once their weights install; the
        # batch never stalls on a tenant miss. Slot installs ride the
        # runner's _OP_LORA lockstep broadcast, so multi-host replicas
        # flip residency atomically.
        self.adapter_registry = None
        self.adapter_pool = None
        self._lora_parked: list = []
        # Terminal ABORT outputs for parked rows whose adapter vanished
        # (defensive; drained into the next step's return).
        self._lora_failed_outputs: list[RequestOutput] = []
        # Group-streamed KV imports (docs/architecture/kv-cache.md
        # "layer-streamed import"): requests whose transferred KV is
        # still on the wire park here — admitted by _admit_kv_streams at
        # a step boundary once the stream resolves (apply on success,
        # plain recompute on failure). The serving layer submits them as
        # soon as the FIRST layer group is resident, so admission,
        # scheduling, and host staging all overlap the remaining wire
        # transfer. Entries: (Request, KVStreamHandle).
        self._kv_parked: list = []
        if config.model.lora_dynamic and not follower:
            from llmd_tpu.lora import AdapterPool, AdapterRegistry

            self.adapter_registry = AdapterRegistry()
            self.adapter_pool = AdapterPool(
                self.adapter_registry,
                install=self.runner.set_lora_weights,
                num_slots=config.model.num_lora_adapters,
                pinned=self._adapter_pinned,
            )

        # Tiered offload pump (save-on-commit / restore-on-prefill).
        self.offloader = None
        if self._host_cache is not None:
            from llmd_tpu.kvtransfer.offload import OffloadConnector

            self.offloader = OffloadConnector(
                self.runner, self.allocator, self._host_cache
            )
            self.allocator.commit_hook = self.offloader.on_commit

        # Decode-time KV pager (OffloadConfig.decode_paging): spills cold
        # page ranges of live long-context sequences through the offload
        # tier and streams the attention window back ahead of resume, so
        # resident HBM per sequence is bounded by window + horizon, not
        # context length (docs/architecture/long-context.md).
        self.pager = None
        if (
            self.offloader is not None
            and config.offload.decode_paging
            and not follower
        ):
            windows = config.model.layer_windows
            if not windows or min(windows) <= 0:
                raise ValueError(
                    "offload.decode_paging requires every layer to be "
                    "sliding-window: a full-attention layer reads "
                    "arbitrarily far back, so no page is ever cold"
                )
            self.runner._require_single_host("decode-time KV paging")
            from llmd_tpu.engine.pager import KVPager

            self.pager = KVPager(
                self.runner,
                self.scheduler,
                self.allocator,
                self._host_cache,
                window=max(windows),
                horizon=config.offload.pager_horizon_tokens,
                stream_groups=config.kv_stream_groups,
            )
            self.scheduler.park_hook = self.pager.park

        # P/D disaggregation: optional KV-transfer connector (reference
        # TPUConnector roles, pd tpu patch-decode.yaml:17-20).
        self.kv_connector = None
        if config.kv_role and not follower:
            from llmd_tpu.kvtransfer.connector import KVTransferConfig, TPUConnector

            kv_cfg = KVTransferConfig(
                role=config.kv_role,
                host=config.kv_host,
                port=config.kv_transfer_port,
                lease_ms=config.kv_lease_ms,
                load_failure_policy=config.kv_load_failure_policy,
                transfer_dtype=config.kv_transfer_dtype,
                local_fastpath=config.kv_local_fastpath,
                stream_groups=config.kv_stream_groups,
            )
            self.kv_connector = TPUConnector(kv_cfg, self.runner, self.allocator)
            self.scheduler.finish_hook = self._on_finish

        # Async stepping (SchedulerConfig.async_scheduling): a two-slot
        # pipeline — one batch executing on device while the next is
        # speculatively scheduled and staged on host. Forced OFF where
        # the synchronous step shape is itself a correctness contract:
        # multi-host lockstep followers mirror a totally ordered op
        # stream whose cadence the leader's sync step defines, and P/D
        # eager-ACK producers answer before the readback on the promise
        # that nothing was reordered around the enqueued KV snapshots.
        self._async = bool(config.scheduler.async_scheduling)
        if self._async and jax.process_count() > 1:
            logging.getLogger(__name__).info(
                "async_scheduling disabled: multi-host lockstep engines "
                "keep the synchronous step shape"
            )
            self._async = False
        if self._async and config.kv_role in ("kv_producer", "kv_both"):
            logging.getLogger(__name__).info(
                "async_scheduling disabled: P/D eager-ACK producers rely "
                "on synchronous step ordering"
            )
            self._async = False
        self._inflight: _InflightStep | None = None
        # Aborts that arrived while their request was in flight: freeing
        # the pages immediately would hand them to another sequence while
        # the device still writes them — applied at the reconcile point.
        self._deferred_aborts: set[str] = set()

        # Speculative decoding (SchedulerConfig.speculative_ngram):
        # model-free n-gram drafting + one-pass verification. The
        # proposer is host-only; drafts are proposed at DISPATCH time
        # from committed history (async staging runs a step early), and
        # acceptance/rollback live in the scheduler's update loop.
        self._spec_proposer = None
        # Per-row verify depth histogram (index = 1 + draft width; see
        # EngineStats.spec_row_depth_hist).
        self._spec_row_depth = [0] * (2 + config.scheduler.spec_ngram_k)
        if config.scheduler.speculative_ngram:
            from llmd_tpu.engine.spec import NgramProposer

            self._spec_proposer = NgramProposer(
                min_match=config.scheduler.spec_ngram_min_match
            )

        # Wide-EP MoE control loops (docs/architecture/wide-ep.md): the
        # runner accumulates a device-side census ([E] routed tokens per
        # logical expert, dropped slots, peak dispatch demand); the engine
        # drains it at step boundaries and feeds two slow controllers —
        # adaptive capacity (ep_capacity_adaptive) and EPLB placement
        # (eplb_interval_steps). Both act through runner methods that
        # rebuild the compiled programs, so they only ever fire between
        # steps. EPLB is leader-only single-host (the remap gather is a
        # host-driven reshard).
        pc = config.parallel
        self._moe_active = self.runner._moe_census is not None
        self._moe_expert_tokens = (
            np.zeros(config.model.num_experts, np.int64)
            if self._moe_active else None
        )
        self._adaptive_cap = None
        if self._moe_active and pc.ep_capacity_adaptive:
            from llmd_tpu.parallel.eplb import AdaptiveCapacity

            self._adaptive_cap = AdaptiveCapacity(base=pc.ep_capacity_factor)
        self._eplb_interval = (
            int(pc.eplb_interval_steps)
            if self._moe_active and jax.process_count() == 1 else 0
        )
        self._eplb_redundancy = int(pc.eplb_redundancy)
        self._eplb_next = self._eplb_interval
        self._eplb_window_base = (
            np.zeros(config.model.num_experts, np.int64)
            if self._eplb_interval else None
        )
        if self._moe_active:
            self.stats.moe_expert_tokens = (0,) * config.model.num_experts
            self.stats.moe_capacity_factor = self.runner.ep_capacity

    def _on_finish(self, req) -> None:
        if self.kv_connector is not None and self.kv_connector.wants_export(req):
            req.export_params = self.kv_connector.export_finished(req)

    def _section_key(self, prompt_token_ids: list[int], extra: bytes):
        """(chain-hash key, n_pre, s0) of a prompt's retained section —
        identical derivation on capture and seed, folding the same extra
        (LoRA/multimodal) the full-pool page hashes fold."""
        from llmd_tpu.engine.kv_cache import page_hashes_for_tokens

        page = self.config.cache.page_size
        n_pre, s0, _cnt = self._swa.section(len(prompt_token_ids), page)
        if n_pre <= s0:
            return None, 0, 0
        hashes = page_hashes_for_tokens(
            list(prompt_token_ids[: n_pre * page]), page, extra=extra
        )
        if len(hashes) < n_pre:
            return None, 0, 0
        return hashes[n_pre - 1], n_pre, s0

    def _capture_swa_section(self, req) -> None:
        """Scheduler hook at prompt completion: the ring still holds the
        prompt's trailing window — retain a copy for later hybrid hits.
        (At FINISH time the ring has advanced past the prompt, which is
        why capture happens here, mirroring the P/D export's staleness
        rule.)"""
        try:
            key, n_pre, s0 = self._section_key(
                req.prompt_token_ids, self.scheduler.hash_extra(req)
            )
            if key is None or not req.swa_block_ids:
                return
            self._swa_sections.capture(key, req.swa_block_ids, s0, n_pre)
        # llmd: allow(broad-except) -- best-effort section retention; a capture failure only costs a future cache hit
        except Exception:
            logging.getLogger(__name__).exception(
                "swa section capture failed (serving unaffected)"
            )

    # ------------------------------------------------------------------ #

    def add_request(
        self,
        prompt_token_ids: list[int],
        sampling: SamplingParams | None = None,
        request_id: str | None = None,
        priority: int = 0,
        kv_transfer_params: dict | None = None,
        lora_id: int = 0,
        lora_name: str = "",
        resume_output_tokens: int = 0,
    ) -> str:
        if not prompt_token_ids:
            raise ValueError("empty prompt")
        if resume_output_tokens and not (
            0 < resume_output_tokens < len(prompt_token_ids)
        ):
            raise ValueError(
                f"resume_output_tokens {resume_output_tokens} must leave a "
                f"non-empty prompt head (prompt carries "
                f"{len(prompt_token_ids)} tokens)"
            )
        park_adapter = False
        lora_lease = ""
        if lora_name and self.adapter_pool is not None:
            # Dynamic pool path: names resolve to slots HERE (the serving
            # layer no longer owns a fixed name->slot map). Resident
            # adapters ride their slot; registered-but-cold adapters park
            # in the loading queue; unknown names are a client error.
            # acquire() holds an admission lease so a concurrent install
            # (load API prefetch / embed cold load) cannot evict the slot
            # before this row is visible to the pinned scan.
            slot = self.adapter_pool.acquire(lora_name)
            if slot is not None:
                lora_id = slot
                lora_lease = lora_name
            elif self.adapter_registry.has(lora_name):
                lora_id = 0  # assigned when the cold load installs
                park_adapter = True
            else:
                raise ValueError(
                    f"unknown lora_name {lora_name!r} (loaded adapters: "
                    f"{self.adapter_registry.names()})"
                )
        elif lora_name and not lora_id:
            # Static path: the serving layer maps names to slots before
            # add_request — a name arriving WITHOUT a slot is exactly the
            # silent-base-model bug this guard exists for.
            raise ValueError(
                f"unknown lora_name {lora_name!r} (this engine serves "
                f"{self.config.model.num_lora_adapters} fixed adapter "
                "slot(s); map the name to its slot id, or enable the "
                "dynamic pool with lora_dynamic)"
            )
        try:
            return self._admit_request(
                prompt_token_ids, sampling, request_id, priority,
                kv_transfer_params, lora_id, lora_name,
                resume_output_tokens, park_adapter,
            )
        finally:
            # The admission lease only bridges the resolve->admitted
            # window; from here the scheduler-list pinned scan (or the
            # parked queue) carries the pin.
            if lora_lease:
                self.adapter_pool.release_acquire(lora_lease)

    def _admit_request(
        self,
        prompt_token_ids: list[int],
        sampling: SamplingParams | None,
        request_id: str | None,
        priority: int,
        kv_transfer_params: dict | None,
        lora_id: int,
        lora_name: str,
        resume_output_tokens: int,
        park_adapter: bool,
    ) -> str:
        if lora_id and not (
            0 < lora_id <= self.config.model.num_lora_adapters
        ):
            raise ValueError(
                f"lora_id {lora_id} out of range "
                f"(model has {self.config.model.num_lora_adapters} adapters)"
            )
        if len(prompt_token_ids) >= self.config.model.max_model_len:
            raise ValueError(
                f"prompt length {len(prompt_token_ids)} >= max_model_len "
                f"{self.config.model.max_model_len}"
            )
        sched = self.config.scheduler
        if (
            not sched.enable_chunked_prefill
            and len(prompt_token_ids) > sched.max_num_batched_tokens
        ):
            raise ValueError(
                f"prompt length {len(prompt_token_ids)} > max_num_batched_tokens "
                f"{sched.max_num_batched_tokens} and chunked prefill is disabled"
            )
        rid = request_id or f"req-{next(self._counter)}-{uuid.uuid4().hex[:8]}"
        # P/D consumer: pull remote KV and seed the local prefix cache before
        # the request is ever scheduled, so prefill becomes a cache hit. The
        # async serving layer pre-fetches off-thread and hands the bundle in
        # via "__pulled__"; the sync path fetches inline. Ring engines
        # (kv_swa_ring) have no prefix cache — their transfers land via
        # the PRELOAD path: pages (full-group + a fresh ring holding the
        # sliding-layer section) handed straight to the Request below.
        preload = None
        kv_stream = None
        if self.kv_connector is not None and self.kv_connector.wants_import(
            kv_transfer_params
        ):
            kv_transfer_params = dict(kv_transfer_params)
            # Group-streamed import (v3 wire): the serving layer submits
            # at first-group-resident with the in-flight handle; the
            # request PARKS below and _admit_kv_streams finalizes at a
            # step boundary — admission/scheduling overlap the rest of
            # the wire transfer.
            kv_stream = kv_transfer_params.pop("__stream__", None)
            if kv_stream is None:
                if "__pulled__" in kv_transfer_params:
                    bundle = kv_transfer_params.pop("__pulled__")
                else:
                    bundle = self.kv_connector.fetch_remote_policy(
                        list(prompt_token_ids), kv_transfer_params
                    )
                if bundle is not None:
                    if self._swa is not None:
                        preload = self.kv_connector.apply_preload(
                            list(prompt_token_ids), bundle,
                            self.swa_allocator, self._swa.ring_pages,
                        )
                    else:
                        self.kv_connector.apply_bundle(
                            list(prompt_token_ids), bundle
                        )
        # Tiered offload: pull host-cached pages extending the device prefix
        # run back into HBM before scheduling (restore-on-prefill).
        # (Streamed imports defer this to finalize: the transferred pages
        # land first, then the host tiers only fill what is left.)
        if self.offloader is not None and kv_stream is None:
            self.offloader.restore_for_prompt(list(prompt_token_ids))
        req = Request(
            request_id=rid,
            prompt_token_ids=list(prompt_token_ids),
            sampling=sampling or SamplingParams(),
            priority=priority,
            kv_transfer_params=kv_transfer_params,
            lora_id=lora_id,
            lora_name=lora_name,
        )
        if resume_output_tokens:
            # Mid-stream failover resume: the prompt's TAIL is output the
            # client already received from a dead replica. Admitting it
            # through the recompute-preemption seam (delivered history
            # folded into the prompt, num_prior_output_tokens carrying
            # the output position) makes the continuation byte-identical
            # by construction: the seeded sampler derives per-(seed,
            # total_output_tokens) and the LENGTH check counts prior
            # output toward max_tokens.
            req.num_prior_output_tokens = resume_output_tokens
            self.stats.stream_resumes_total += 1
            self.stats.resume_replayed_tokens_total += resume_output_tokens
        if preload is not None:
            # Transferred KV handed straight to the request (ring mode):
            # admission skips the preloaded prefix; only the recompute
            # tail (at least the last token) is prefilled locally.
            req.block_ids = list(preload["block_ids"])
            req.swa_block_ids = list(preload["swa_block_ids"])
            req.num_computed_tokens = preload["tokens"]
            req.num_cached_tokens = preload["tokens"]
        elif (
            self._swa_sections is not None
            and not park_adapter
            and kv_stream is None
        ):
            # (Parked requests skip the hybrid probe: their cache salt
            # needs the slot id the cold load has not assigned yet.)
            self._try_hybrid_ring_hit(req)
        if kv_stream is not None:
            # Waiting on the group stream: schedulable the moment the
            # import resolves (apply on success, recompute on failure).
            self._kv_parked.append((req, kv_stream, park_adapter))
            return rid
        if park_adapter:
            # Loading queue (multi-tenant-lora.md): the request waits for
            # its adapter's cold load — admitted by _admit_cold_loads at
            # a step boundary with its assigned slot. The batch keeps
            # serving resident tenants meanwhile.
            self._lora_parked.append(req)
            return rid
        self.scheduler.add_request(req)
        return rid

    def _try_hybrid_ring_hit(self, req) -> None:
        """Hybrid prefix hit under the ring: usable only when BOTH a
        full-pool prefix run AND a retained sliding section exist for
        the SAME span — then a fresh ring is seeded from the section
        (device copy) and the request starts past that span, like a
        locally-sourced P/D preload. Sections retained at SHORTER spans
        serve extended prompts too (the multi-turn grow case): the
        longest retained span covered by this prompt wins. Any miss,
        allocation failure, or device error degrades to a normal full
        prefill (resources released)."""
        from llmd_tpu.engine.kv_cache import (
            NoFreePagesError, page_hashes_for_tokens,
        )

        page = self.config.cache.page_size
        n_pre, _s0, _cnt = self._swa.section(len(req.prompt_token_ids), page)
        if n_pre <= 0:
            return
        # Candidate lengths need only n_pre — unique-prompt traffic (no
        # usable retained span) exits before paying the hash walk.
        lengths = self._swa_sections.candidate_lengths(n_pre)
        if not lengths:
            return
        extra = self.scheduler.hash_extra(req)
        # ONE hash walk serves both the section probes and the full-pool
        # lookup (the prompt is hashed nowhere else on this path).
        hashes = page_hashes_for_tokens(
            list(req.prompt_token_ids[: n_pre * page]), page, extra=extra
        )
        for k in lengths:
            key = hashes[k - 1]
            if not self._swa_sections.has(key):
                continue
            # Probe without touching: failed candidates must not inflate
            # hit metrics or refresh LRU recency of pages left unused.
            if self.allocator.peek_hash_run(hashes[:k]) < k:
                continue
            cached = self.allocator.lookup_and_touch_hashes(hashes[:k])
            if len(cached) < k:
                # Raced an eviction between peek and touch.
                if cached:
                    self.allocator.free(cached)
                continue
            ring_ids: list[int] = []
            try:
                ring_ids = self.swa_allocator.allocate(self._swa.ring_pages)
                if self._swa_sections.seed(key, ring_ids) is None:
                    raise KeyError("section evicted between has() and seed()")
            # llmd: allow(broad-except) -- a retained-section hit must never fail the request; degrades to a plain prefill
            except Exception as e:
                # Includes device/lockstep errors from the seed copy: a
                # hit must never fail the request — release and prefill.
                self.allocator.free(cached)
                if ring_ids:
                    self.swa_allocator.free(ring_ids)
                if not isinstance(e, (NoFreePagesError, KeyError)):
                    logging.getLogger(__name__).exception(
                        "hybrid ring seed failed; recomputing locally"
                    )
                return
            req.block_ids = cached
            req.swa_block_ids = ring_ids
            req.num_computed_tokens = k * page
            req.num_cached_tokens = k * page
            # Seed the commit chain past the hit (key IS hashes[k-1]) so
            # finish does not re-hash and re-commit the cached prefix —
            # duplicate BlockStored events would reach the router's
            # indexer.
            self.scheduler.seed_commit_chain(req, key, k)
            return

    def abort_request(self, request_id: str) -> bool:
        for i, r in enumerate(self._lora_parked):
            if r.request_id == request_id:
                # Parked in the adapter loading queue: never scheduled,
                # nothing on device to reconcile.
                del self._lora_parked[i]
                return True
        for i, (r, handle, _pa) in enumerate(self._kv_parked):
            if r.request_id == request_id:
                # Parked on a group stream: abandon() releases the
                # fetched bundle (stream-reserved pages included) from
                # whichever side of the fetch-thread race holds it.
                del self._kv_parked[i]
                handle.abandon()
                return True
        if self._inflight is not None and any(
            s.request.request_id == request_id
            for s in self._inflight.batch.seqs
        ):
            # In-flight sequence (async stepping): the dispatched device
            # programs still write its pages — defer the abort to the
            # reconcile point instead of freeing pages mid-write.
            self._deferred_aborts.add(request_id)
            return True
        return self.scheduler.abort_request(request_id) is not None

    def cached_prefix_pages(self, prompt_token_ids: list[int]) -> int:
        """Leading FULL pages of this prompt already held locally (device
        prefix cache or tiered host/FS cache — restore-on-prefill pulls
        the latter in without a transfer). The P/D byte-diet probe: the
        sidecar asks before phase 1 so the producer skips staging pages
        the decode side already has (the reference's disagg decider asks
        the same question, scheduling.md:113)."""
        from llmd_tpu.engine.kv_cache import page_hashes_for_tokens

        hashes = page_hashes_for_tokens(
            list(prompt_token_ids), self.allocator.page_size
        )
        n = 0
        for h in hashes:
            if self.allocator.has_cached(h) or (
                self._host_cache is not None and self._host_cache.has(h)
            ):
                n += 1
            else:
                break
        return n

    def embed(
        self, prompts: list[list[int]], lora_id: int = 0, lora_name: str = ""
    ):
        """[n, H] mean-pooled L2-normalized embeddings (OpenAI
        /v1/embeddings surface); independent of the serving KV cache.

        Serialized: each call allocates a scratch KV pool, so unbounded
        concurrency (N executor threads x multi-GB scratch) would OOM the
        device under an embedding burst."""
        lease = ""
        if lora_name and self.adapter_pool is not None:
            # Embeddings have no loading queue (one-shot forward): make
            # the adapter resident now — the same install path a cold
            # generate pays at its step boundary — and hold the
            # admission lease across the WHOLE forward: embeds create
            # no scheduler row, so without the lease a concurrent cold
            # load could evict the slot and swap in another tenant's
            # weights mid-embed.
            for _ in range(3):
                slot = self.adapter_pool.acquire(lora_name)
                if slot is not None:
                    break
                if not self.adapter_registry.has(lora_name):
                    raise ValueError(
                        f"unknown lora_name {lora_name!r} (loaded "
                        f"adapters: {self.adapter_registry.names()})"
                    )
                self.adapter_pool.install_cold(lora_name)
            else:
                raise ValueError(
                    f"adapter {lora_name!r} cannot become resident: "
                    "every pool slot is pinned by in-flight requests"
                )
            lora_id = slot
            lease = lora_name
        try:
            if lora_id and not (
                0 < lora_id <= self.config.model.num_lora_adapters
            ):
                raise ValueError(f"lora_id {lora_id} out of range")
            with self._embed_lock:
                return self.runner.run_embed(prompts, lora_id=lora_id)
        finally:
            if lease:
                self.adapter_pool.release_acquire(lease)

    def close(self) -> None:
        """Release network-facing resources (KV connector, store client)
        and, in a multi-host world, release the follower processes."""
        self.runner.stop_followers()
        if self.kv_connector is not None:
            self.kv_connector.close()
        if self._kvstore_client is not None:
            self._kvstore_client.close()

    def set_lora_weights(self, lora_id: int, weights: dict) -> None:
        """Install trained adapter weights into slot ``lora_id``; until
        then every slot serves exactly the base model (B init == 0).

        Refuses while requests on that slot are in flight (their KV was
        computed under the old weights — swap mid-decode would mix weight
        versions silently). Device AND host/FS cached pages are cleared:
        the reference's weight-rollout analog is the AllBlocksCleared KV
        event (kv-indexer.md:63)."""
        in_flight = [
            r.request_id
            for r in (*self.scheduler.running, *self.scheduler.waiting)
            if r.lora_id == lora_id
        ]
        if in_flight:
            raise RuntimeError(
                f"cannot swap lora slot {lora_id} weights with "
                f"{len(in_flight)} request(s) in flight (pause/drain first)"
            )
        self.runner.set_lora_weights(lora_id, weights)
        self.allocator.clear()
        if self._host_cache is not None:
            self._host_cache.clear()

    # ------------------------------------------------------------------ #
    # multi-tenant adapter pool (docs/architecture/multi-tenant-lora.md)

    def _adapter_pinned(self, name: str) -> bool:
        """Pin-while-referenced: an adapter named by any running or
        queued row must keep its slot — the forward reads slot weights
        every step, and displacing a referenced tenant would silently
        mix weight versions mid-stream. (The same scheduler-list scan
        set_lora_weights uses for its in-flight refusal.)"""
        return any(
            r.lora_name == name
            for r in (*self.scheduler.running, *self.scheduler.waiting)
        )

    def _lora_rows_inflight(self, name: str) -> int:
        return sum(
            1
            for r in (
                *self.scheduler.running,
                *self.scheduler.waiting,
                *self._lora_parked,
            )
            if r.lora_name == name
        )

    def _normalize_adapter_weights(self, weights: dict) -> dict:
        """Slot-form factor tensors with ABSENT pairs zero-filled: a
        pool install must fully overwrite the evicted tenant's slot, or
        a q-only adapter would silently compose with the previous
        resident's v factors."""
        import numpy as np

        from llmd_tpu.lora.source import FACTOR_KEYS

        layers = self.runner.params["layers"]
        out = {}
        for k in FACTOR_KEYS:
            shape = (layers[k].shape[0], *layers[k].shape[2:])
            if k in weights:
                out[k] = np.ascontiguousarray(
                    np.asarray(weights[k], np.float32)
                ).reshape(shape)
            else:
                out[k] = np.zeros(shape, np.float32)
        return out

    def load_adapter(
        self, name: str, source: str = "", weights: dict | None = None
    ) -> None:
        """Register ``name`` in the serving registry (the
        ``/v1/load_lora_adapter`` contract): fetch + decode its weights
        (CRC-framed for URL/kvstore sources), then eagerly install into
        a FREE pool slot when one exists — otherwise the adapter stays
        one cold load away. Any failure raises without touching the
        registry; the caller surfaces a counted 4xx."""
        if self.adapter_pool is None:
            raise RuntimeError(
                "dynamic adapter serving is disabled "
                "(ModelConfig.lora_dynamic / --lora-pool-slots)"
            )
        if weights is None:
            from llmd_tpu.lora import AdapterFetchError, fetch_adapter

            try:
                weights = fetch_adapter(
                    source,
                    name=name,
                    model_cfg=self.config.model,
                    kvstore_get=(
                        self._kvstore_client.get
                        if self._kvstore_client is not None
                        else None
                    ),
                )
            except (AdapterFetchError, ValueError):
                self.stats.lora_load_failures_total += 1
                raise
        weights = self._normalize_adapter_weights(weights)
        _, stale_cache = self.adapter_registry.register(name, weights, source)
        if stale_cache:
            # The name was previously served with DIFFERENT weights:
            # its name-salted prefix pages are stale. Same blast radius
            # as a static weight swap (AllBlocksCleared analog).
            self.allocator.clear()
            if self._host_cache is not None:
                self._host_cache.clear()
        self.adapter_pool.install_prefetch(name)
        self._refresh_lora_stats()

    def _refresh_lora_stats(self) -> None:
        """Registry/residency stats refresh OUTSIDE the step loop too:
        an idle engine that just loaded adapters must advertise them on
        the next scrape (the tri-state scorer routes on these labels),
        not after its first generate request."""
        if self.adapter_pool is None:
            return
        pc = self.adapter_pool.counters()
        self.stats.lora_pool_resident_adapters = pc["resident"]
        self.stats.lora_pool_evictions_total = pc["evictions"]
        self.stats.lora_cold_loads_total = pc["cold_loads"]
        self.stats.resident_lora_adapters = tuple(
            self.adapter_pool.resident_names()
        )
        self.stats.available_lora_adapters = tuple(
            self.adapter_registry.names()
        )

    def unload_adapter(self, name: str) -> None:
        """Unregister ``name`` and release its slot
        (``/v1/unload_lora_adapter``). Refuses while any row references
        the adapter — mirroring set_lora_weights' in-flight refusal."""
        if self.adapter_pool is None:
            raise RuntimeError("dynamic adapter serving is disabled")
        if not self.adapter_registry.has(name):
            raise KeyError(
                f"adapter {name!r} is not loaded "
                f"(loaded: {self.adapter_registry.names()})"
            )
        n = self._lora_rows_inflight(name)
        if n:
            raise RuntimeError(
                f"cannot unload adapter {name!r} with {n} request(s) in "
                "flight (drain first)"
            )
        # remove() re-checks references UNDER the pool lock (admission
        # leases + the pinned scan), so a request admitted between the
        # friendly count above and here still refuses — a freed slot is
        # never reused under a live row.
        self.adapter_pool.remove(name)
        self.adapter_registry.unregister(name)
        self._refresh_lora_stats()

    def _admit_cold_loads(self) -> None:
        """Drain the adapter loading queue at a step boundary: install
        the head request's adapter (evicting an idle LRU resident when
        no slot is free) and admit every parked row for it. Stops when
        every slot is pinned by in-flight rows — backpressure, the
        parked rows wait for capacity."""
        while self._lora_parked:
            name = self._lora_parked[0].lora_name
            slot = self.adapter_pool.slot_of(name)
            if slot is None:
                rec = self.adapter_registry.get(name)
                if rec is None:
                    # Unloaded while parked (unload refuses this; purely
                    # defensive): fail the rows rather than hang them —
                    # a terminal ABORT output rides the step's return so
                    # subscribers see a finished stream, never silence.
                    failed = [
                        r for r in self._lora_parked if r.lora_name == name
                    ]
                    self._lora_parked = [
                        r for r in self._lora_parked if r.lora_name != name
                    ]
                    for r in failed:
                        self._lora_failed_outputs.append(RequestOutput(
                            request_id=r.request_id,
                            new_token_ids=[],
                            finished=True,
                            finish_reason=FinishReason.ABORT,
                            num_prompt_tokens=len(r.prompt_token_ids),
                            num_output_tokens=0,
                        ))
                    logging.getLogger(__name__).error(
                        "adapter %r vanished with %d parked request(s); "
                        "aborted", name, len(failed),
                    )
                    continue
                slot = self.adapter_pool.install_cold(name)
                if slot is None:
                    return  # every slot pinned; keep waiting
            still = []
            for req in self._lora_parked:
                if req.lora_name == name:
                    req.lora_id = slot
                    self.scheduler.add_request(req)
                else:
                    still.append(req)
            self._lora_parked = still

    def _admit_kv_streams(self) -> None:
        """Drain resolved group-streamed imports at a step boundary.

        A landed bundle applies (hash-chain commit only — the fetch
        thread already scattered every group into pool pages) and the
        request goes to the scheduler, where the prefill is now a
        prefix-cache hit; a failed stream admits as a plain local
        recompute (the PR 7 degradation contract, byte-identical
        either way). When streams are the ONLY pending work, block
        briefly on the oldest handle so the step loop wakes the instant
        it resolves instead of busy-spinning."""
        while True:
            still: list = []
            admitted = False
            for req, handle, park_adapter in self._kv_parked:
                if not handle.done.is_set():
                    still.append((req, handle, park_adapter))
                    continue
                bundle = handle.take()
                if bundle is not None:
                    self.kv_connector.apply_bundle(
                        list(req.prompt_token_ids), bundle
                    )
                elif self.offloader is not None:
                    # Stream failed: give the host tiers their usual
                    # restore-on-prefill shot before the recompute.
                    self.offloader.restore_for_prompt(
                        list(req.prompt_token_ids)
                    )
                if park_adapter:
                    self._lora_parked.append(req)
                else:
                    self.scheduler.add_request(req)
                admitted = True
            self._kv_parked = still
            if admitted or not still:
                return
            if (
                self._inflight is not None
                or self.scheduler.has_work()
                or self._lora_parked
            ):
                return  # other work to run; re-check next step
            # Idle except for in-flight streams: wait on the oldest —
            # bounded so the serving loop still sees aborts promptly.
            if not still[0][1].done.wait(0.05):
                return

    def has_work(self) -> bool:
        return (
            self.scheduler.has_work()
            or self._inflight is not None
            or bool(self._lora_parked)
            or bool(self._kv_parked)
        )

    # ------------------------------------------------------------------ #

    def step(self) -> list[RequestOutput]:
        # Injection site: a wedged device program (engine.step.stall)
        # stalls the whole step — the AsyncEngine watchdog's job is to
        # notice, 503 /health and terminate in-flight streams. Unarmed
        # this is one module-global None check.
        faults.delay("engine.step.stall")
        if self._kv_parked:
            self._admit_kv_streams()
        if self._lora_parked:
            self._admit_cold_loads()
        if self.pager is not None:
            # Restore parked attention windows before scheduling — a
            # still-pending fetch leaves the request fetch-pending (a
            # wait state the scheduler skips, not a fault).
            self.pager.pump(self.scheduler.waiting)
        outputs = self._step_async() if self._async else self._step_sync()
        if self._lora_failed_outputs:
            outputs = [*self._lora_failed_outputs, *outputs]
            self._lora_failed_outputs = []
        return outputs

    def _step_sync(self) -> list[RequestOutput]:
        t0 = time.monotonic()
        batch: ScheduledBatch = self.scheduler.schedule()
        if batch.is_empty:
            return []
        now = time.monotonic()
        # Eager-ACK: an export-only prefill's sampled token is thrown
        # away by the routing sidecar (the two-phase protocol only
        # consumes kv_transfer_params), so the producer's response
        # does not wait for prefill compute or the token readback —
        # device program order alone guarantees the KV snapshots the
        # consumer pulls are valid. Cuts compute + one host RTT off
        # the P/D TTFT critical path.
        eager_ack = bool(batch.prefills) and (
            self.kv_connector is not None
            and self.kv_connector.cfg.is_producer
            and all(
                s.request.kv_transfer_params is not None
                and s.request.kv_transfer_params.get("do_remote_decode")
                and s.request.sampling.max_tokens == 1
                for s in batch.prefills
            )
        )
        pend_p = pend_d = pend_u = None
        if not eager_ack and self._unified_eligible(batch):
            pend_u = self._dispatch_unified(batch, None)
        else:
            if batch.prefills:
                pend_p = self.runner.dispatch_prefill(batch.prefills)
                self.stats.step_dispatches_total += len(pend_p.entries)
                for seq in batch.prefills:
                    self.stats.prompt_tokens += seq.num_tokens
            if batch.decodes:
                pend_d = self._dispatch_decodes(batch.decodes, batch.spec_window)
        self.scheduler.note_dispatch(batch)
        t_dispatched = time.monotonic()
        # One coalesced readback for the whole step (prefill bucket
        # groups + the decode window — or the one unified program —
        # come back in a single transfer).
        pres, dres = self.runner.wait_step(
            None if eager_ack else pend_p, pend_d, pend_u
        )
        t_read = time.monotonic()
        sampled, logprobs = self._collect(batch, pres, dres)
        accepted = self.scheduler.update_after_step(batch, sampled)
        outputs = self._assemble_outputs(batch, accepted, logprobs, now)
        if self.offloader is not None:
            # One bucketed HBM->host gather for the step's committed pages.
            self.offloader.flush()
        if self.pager is not None:
            # Spill pages that fell below the window + prefetch horizon.
            self.pager.tick(self.scheduler.running)
        self._finish_step((t_dispatched - t0) + (time.monotonic() - t_read))
        return outputs

    def _step_async(self) -> list[RequestOutput]:
        """Two-slot pipelined step: while the in-flight batch executes on
        device, schedule the next batch speculatively (each in-flight
        decode assumed to land its tokens) and prestage its host arrays;
        only then block on the in-flight readback. Late finishes
        (EOS/stop token/max-tokens) invalidate their staged rows — the
        released pages follow the recompute-preemption path — and
        everything else dispatches immediately, so the host gap shrinks
        to the reconcile/patch sliver. Outputs arrive one step late
        (docs/architecture/async-scheduling.md)."""
        inflight = self._inflight
        if inflight is None:
            batch = self.scheduler.schedule()
            if batch.is_empty:
                return []
            self._dispatch_async(batch)
            return []  # pipeline is one step deep: tokens land next call
        # ---- overlapped host region: the device is executing N ----
        staged = self.scheduler.schedule()  # speculative: pending counts
        staged_dec: (
            StagedDecode | StagedVerify | StagedVerifyWindow
            | StagedUnified | None
        ) = None
        if self._unified_eligible(staged):
            # Unified single-dispatch step: the row structure and the
            # row-independent arrays (page/ring tables, knobs) prestage
            # here; the packed stream, (start, qlen, kind) metadata,
            # drafts and seeds fill at dispatch, after step N's
            # readback commits.
            staged_dec = self.runner.stage_unified(
                staged.prefills, staged.decodes
            )
        elif staged.decodes:
            if self._spec_proposer is not None:
                # Spec mode stages the verify(-window) shape; tokens,
                # drafts and seeds fill at dispatch, after step N's
                # readback commits.
                if staged.spec_window > 1:
                    staged_dec = self.runner.stage_spec_verify_window(
                        staged.decodes, staged.spec_window
                    )
                else:
                    staged_dec = self.runner.stage_spec_verify(staged.decodes)
            else:
                staged_dec = self.runner.stage_decode(
                    staged.decodes, k_steps=staged.decodes[0].num_tokens
                )
        # ---- block on step N's single coalesced readback ----
        pres, dres = self.runner.wait_step(
            inflight.pending_prefill, inflight.pending_decode,
            inflight.pending_unified,
        )
        t_read = time.monotonic()
        sampled, logprobs = self._collect(inflight.batch, pres, dres)
        accepted = self.scheduler.update_after_step(inflight.batch, sampled)
        self._inflight = None
        for rid in sorted(self._deferred_aborts):
            self.scheduler.abort_request(rid)
        self._deferred_aborts.clear()
        # ---- reconcile the speculative slot against late finishes ----
        live_p = [
            s for s in staged.prefills
            if s.request.status is RequestStatus.RUNNING
        ]
        live_d = [
            s for s in staged.decodes
            if s.request.status is RequestStatus.RUNNING
        ]
        rolled = (len(staged.prefills) - len(live_p)) + (
            len(staged.decodes) - len(live_d)
        )
        if rolled:
            # Rolled-back rows already returned every page (speculative
            # allocations included) via _finish/_release — the same
            # release the recompute-preemption path uses.
            self.stats.async_rollbacks_total += rolled
            # Surviving rows keep their planned widths/draft caps, so
            # the reconciled batch must keep its window too — dropping
            # to the default would send window-planned rows down the
            # one-shot verify path, whose arrays are only 1+k wide.
            reconciled = ScheduledBatch(
                prefills=live_p, decodes=live_d,
                spec_window=staged.spec_window,
            )
            if isinstance(staged_dec, StagedUnified):
                # Unified prestage survives a rollback by SLICING the
                # surviving rows' row-independent arrays out of the
                # full-batch staging (_slice_staged_rows) — unless the
                # reconciled step is no longer unified-shaped (e.g. it
                # collapsed to a single program's worth of work).
                if not reconciled.is_empty and self._unified_eligible(
                    reconciled
                ):
                    staged_dec = self.runner.subset_staged_unified(
                        staged_dec, live_p, live_d
                    )
                else:
                    staged_dec = None
            elif len(live_d) != len(staged.decodes):
                staged_dec = None  # row set changed: restage at dispatch
            staged = reconciled
        if staged.is_empty and rolled and self.scheduler.has_work():
            # The whole slot was invalidated; the freed pages/budget may
            # admit different work now that nothing is pending.
            staged = self.scheduler.schedule()
            staged_dec = None
        if not staged.is_empty:
            self._dispatch_async(staged, staged_dec)
        # Device idle ends at the re-dispatch above; output assembly and
        # gauge refresh below overlap step N+1's execution.
        host_gap = time.monotonic() - t_read
        outputs = self._assemble_outputs(
            inflight.batch, accepted, logprobs, inflight.dispatch_time
        )
        if self.offloader is not None:
            self.offloader.flush()
        if self.pager is not None:
            # Protected (in-flight) rows are skipped inside the tick, so
            # the staged batch's page tables stay valid.
            self.pager.tick(self.scheduler.running)
        self._finish_step(host_gap)
        return outputs

    def _dispatch_async(
        self,
        batch: ScheduledBatch,
        staged_dec: (
            StagedDecode | StagedVerify | StagedVerifyWindow
            | StagedUnified | None
        ) = None,
    ) -> None:
        now = time.monotonic()
        pend_p = pend_d = pend_u = None
        if self._unified_eligible(batch):
            pend_u = self._dispatch_unified(
                batch,
                staged_dec if isinstance(staged_dec, StagedUnified) else None,
            )
        else:
            if batch.prefills:
                pend_p = self.runner.dispatch_prefill(batch.prefills)
                self.stats.step_dispatches_total += len(pend_p.entries)
                for seq in batch.prefills:
                    self.stats.prompt_tokens += seq.num_tokens
            if batch.decodes:
                pend_d = self._dispatch_decodes(
                    batch.decodes, batch.spec_window,
                    None if isinstance(staged_dec, StagedUnified)
                    else staged_dec,
                )
        self.scheduler.note_dispatch(batch)
        self._inflight = _InflightStep(batch, pend_p, pend_d, now, pend_u)

    def _unified_eligible(self, batch: ScheduledBatch) -> bool:
        """Does this batch ride the unified single-dispatch program?
        Window=1 steps only (fused decode/verify windows keep their own
        dispatch — they already amortize the round-trip).

        Flattened-token engines (`--ragged-qlens`): EVERY window=1 step
        kind rides the ONE flat program — prefill-only, pure-decode,
        mixed, and one-shot verify mixes (a mixed drafted/plain spec
        step becomes one dispatch where the split path launched two,
        with per-row adaptive verify depth via each row's own qlen).

        Bucketed engines: only where the split engine would launch MORE
        than one program — mixed prefill+decode steps, or prefill-only
        steps spanning several Q buckets. Pure-decode window=1 steps
        are already one dispatch (mixed drafted/plain spec splits keep
        the two-program path — their staging shape depends on drafts
        only known at dispatch)."""
        if batch.spec_window != 1:
            return False
        if (
            self.runner.cp_prefill
            and batch.prefills
            and any(
                s.num_tokens >= max(self.runner.cp_min_tokens,
                                    self.runner.cp_prefill)
                for s in batch.prefills
            )
        ):
            # Context-parallel ring prefill lives in the split _forward
            # family only; long chunks divert so they ride it.
            return False
        if self.runner._flat is not None:
            if batch.is_empty:
                return False
            # Fused decode windows (non-spec K>1 rows) keep their own
            # dispatch — they already amortize the round-trip.
            if self._spec_proposer is None and any(
                s.num_tokens != 1 for s in batch.decodes
            ):
                return False
            return True
        if self.runner._unified is None:
            return False
        if not batch.prefills:
            return False
        if batch.decodes:
            # A window=1 mixed step always has one-token decode rows in
            # spec-off engines (the fused window only engages on pure-
            # decode steps); guard anyway so an unexpected fused batch
            # keeps its own program.
            if self._spec_proposer is None and any(
                s.num_tokens != 1 for s in batch.decodes
            ):
                return False
            return True
        return self.runner.prefill_group_count(batch.prefills) > 1

    def _dispatch_unified(
        self, batch: ScheduledBatch, staged: StagedUnified | None
    ) -> PendingUnified:
        """Dispatch the whole window=1 step as ONE ragged program (drafts
        proposed first, exactly like the split paths). ``staged`` reuses
        the async pipeline's prestaged arrays when the row set still
        matches."""
        if self._spec_proposer is not None and batch.decodes:
            self._propose_drafts(batch.decodes)
        reuse = (
            staged is not None
            and len(staged.prefills) == len(batch.prefills)
            and len(staged.decodes) == len(batch.decodes)
            and all(a is b for a, b in zip(staged.prefills, batch.prefills))
            and all(a is b for a, b in zip(staged.decodes, batch.decodes))
        )
        if reuse:
            pend_u = self.runner.dispatch_staged_unified(staged)
        else:
            pend_u = self.runner.dispatch_unified(
                batch.prefills, batch.decodes
            )
        for seq in batch.prefills:
            self.stats.prompt_tokens += seq.num_tokens
        self.stats.unified_steps_total += 1
        self.stats.step_dispatches_total += 1
        if batch.decodes:
            self.stats.decode_dispatches_total += 1
        return pend_u

    def _dispatch_decodes(
        self,
        decodes: list,
        spec_window: int = 1,
        staged: StagedDecode | StagedVerify | StagedVerifyWindow | None = None,
    ) -> PendingDecode:
        """Dispatch the step's decode rows: the fused verify window when
        the scheduler picked one, the one-shot speculative verify path
        when drafting is on and any row drafted, the plain decode
        program otherwise. ``staged`` reuses host arrays prebuilt by the
        async pipeline when they still match the dispatch shape —
        including SLICING the row-independent page-table/knob rows for
        mixed-step subsets instead of restaging them in the blocking
        host region."""
        pend = self._dispatch_decode_programs(decodes, spec_window, staged)
        self.stats.decode_dispatches_total += len(pend.entries)
        self.stats.step_dispatches_total += len(pend.entries)
        return pend

    def _dispatch_decode_programs(
        self,
        decodes: list,
        spec_window: int,
        staged: StagedDecode | StagedVerify | StagedVerifyWindow | None,
    ) -> PendingDecode:
        if self._spec_proposer is not None:
            self._propose_drafts(decodes)
            window_staged = (
                isinstance(staged, StagedVerifyWindow)
                and staged.window == spec_window
                and len(staged.seqs) == len(decodes)
                and all(a is b for a, b in zip(staged.seqs, decodes))
            )
            if spec_window > 1:
                if any(s.draft_tokens for s in decodes):
                    # Fused verify window: drafting AND non-drafting
                    # rows ride the same program (query-length masking
                    # degrades draft-less rows to one-token iterations
                    # on device) — one dispatch, one readback per K
                    # verify iterations.
                    if not window_staged:
                        staged = self.runner.stage_spec_verify_window(
                            decodes, spec_window
                        )
                    return self.runner.dispatch_staged_verify_window(staged)
                # NO row drafted this window: degrade to the plain fused
                # decode program at the window depth — [B, 1] columns
                # instead of [B, 1+k], so fully backed-off (adversarial)
                # traffic keeps the window's dispatch amortization
                # without paying idle verify columns. Depth stays at the
                # WINDOW (the verify window's iteration count, and the
                # proposer's probe cadence), capped by the smallest
                # planned width so no row outruns its pages, then
                # clamped DOWN to a warmed decode shape — an unwarmed K
                # would block serving on a fresh XLA compile mid-step.
                k = min(spec_window, min(s.num_tokens for s in decodes))
                k = max(w for w in self.runner.decode_windows if w <= k)
                if window_staged:
                    return self.runner.dispatch_staged_decode(
                        self.runner.degrade_staged_window(staged, k)
                    )
                return self.runner.dispatch_decode(decodes, k_steps=k)
            drafted = sum(1 for s in decodes if s.draft_tokens)
            if drafted == len(decodes):
                if not isinstance(staged, StagedVerify):
                    staged = self.runner.stage_spec_verify(decodes)
                return self.runner.dispatch_staged_verify(staged)
            if drafted == 0:
                # No row drafted anything this step: the plain one-token
                # decode program (no wasted verify columns — the
                # adversarial-traffic guard). The rows stay speculative
                # (draft_tokens == []), so acceptance accounting and
                # page truncation still run.
                return self.runner.dispatch_decode(decodes, k_steps=1)
            # Mixed step: drafting rows verify, the rest decode plainly
            # (two enqueues, one coalesced readback). The prestaged
            # full-batch verify arrays are reused by slicing their
            # row-independent rows per subset.
            return self.runner.dispatch_spec_split(
                decodes,
                staged if isinstance(staged, StagedVerify) else None,
            )
        if not isinstance(staged, StagedDecode):
            staged = self.runner.stage_decode(
                decodes, k_steps=decodes[0].num_tokens
            )
        return self.runner.dispatch_staged_decode(staged)

    def _propose_drafts(self, decodes: list) -> None:
        """Fill each speculative decode row's draft from COMMITTED
        history, at dispatch time — async staging runs a step early,
        where the history is stale and the tail token unknown. The cap
        — spec_draft_cap (windowed rows: up to window x (1+k) - 1, 0
        for backed-off rows) or num_tokens - 1 (the one-shot planned
        width) — guarantees the draft never writes a slot that wasn't
        allocated, even when a short acceptance left the row behind its
        planned position."""
        max_len = self.config.model.max_model_len
        for seq in decodes:
            req = seq.request
            # Rows planned draft-less (max_model_len cap or draft
            # backoff, scheduler._spec_eligible) get no proposer call
            # and no verify columns.
            cap = (
                seq.num_tokens - 1
                if seq.spec_draft_cap is None else seq.spec_draft_cap
            )
            cap = min(cap, max_len - req.num_computed_tokens - 1)
            if cap <= 0:
                seq.draft_tokens = []
                continue
            if req.spec_gram_state is None:
                req.spec_gram_state = self._spec_proposer.new_state()
            seq.draft_tokens = self._spec_proposer.propose(
                req.all_token_ids, cap, req.spec_gram_state
            )
        for seq in decodes:
            depth = 1 + len(seq.draft_tokens or [])
            self._spec_row_depth[
                min(depth, len(self._spec_row_depth) - 1)
            ] += 1

    def _collect(
        self,
        batch: ScheduledBatch,
        pres: StepResult | None,
        dres: StepResult | None,
    ) -> tuple[dict[str, list[int]], dict[str, list[float]]]:
        sampled: dict[str, list[int]] = {}
        logprobs: dict[str, list[float]] = {}
        if batch.prefills:
            if pres is None:
                # Eager-ACK: tokens were never fetched (the consumer
                # discards them); zeros keep the bookkeeping uniform.
                pres = StepResult(
                    np.zeros((len(batch.prefills), 1), np.int32),
                    np.zeros((len(batch.prefills), 1), np.float32),
                )
            for i, seq in enumerate(batch.prefills):
                sampled[seq.request.request_id] = pres.tokens[i].tolist()
                logprobs[seq.request.request_id] = pres.logprobs[i].tolist()
        if batch.decodes and dres is not None:
            for i, seq in enumerate(batch.decodes):
                toks, lps = dres.tokens[i], dres.logprobs[i]
                if dres.meta is not None:
                    # Fused verify window: the device already resolved
                    # acceptance — the meta columns carry the emitted
                    # count (plus drafted/accepted/iters for the
                    # scheduler's accounting), and only that prefix of
                    # the packed window is real.
                    seq.device_accept = tuple(int(v) for v in dres.meta[i])
                    m = int(dres.meta[i, 0])
                    toks, lps = toks[:m], lps[:m]
                elif seq.draft_tokens is not None and batch.spec_window == 1:
                    # One-shot speculative row: only 1 + draft_len
                    # columns are real; the rest are the verify shape's
                    # padding. (A windowed batch that degraded to the
                    # plain fused decode program keeps every column —
                    # each fused iteration emitted one committed
                    # sample.)
                    m = 1 + len(seq.draft_tokens)
                    toks, lps = toks[:m], lps[:m]
                sampled[seq.request.request_id] = toks.tolist()
                logprobs[seq.request.request_id] = lps.tolist()
        return sampled, logprobs

    def _assemble_outputs(
        self,
        batch: ScheduledBatch,
        accepted: dict[str, list[int]],
        logprobs: dict[str, list[float]],
        now: float,
    ) -> list[RequestOutput]:
        outputs: list[RequestOutput] = []
        finished = 0
        for seq in batch.seqs:
            req = seq.request
            new_tokens = accepted.get(req.request_id)
            if not new_tokens:
                continue
            if req.first_token_time is None:
                req.first_token_time = now
            if req.sampling.logprobs:
                req.output_logprobs.extend(
                    logprobs[req.request_id][: len(new_tokens)]
                )
            self.stats.generation_tokens += len(new_tokens)
            finished += int(req.is_finished)
            outputs.append(
                RequestOutput(
                    request_id=req.request_id,
                    new_token_ids=new_tokens,
                    finished=req.is_finished,
                    finish_reason=req.finish_reason,
                    num_prompt_tokens=req.num_prompt_tokens - req.num_prior_output_tokens,
                    num_output_tokens=req.total_output_tokens,
                    num_cached_tokens=req.num_cached_tokens,
                    kv_transfer_params=req.export_params,
                )
            )
        self.stats.requests_finished += finished
        return outputs

    def _finish_step(self, host_gap_s: float) -> None:
        gap_ms = host_gap_s * 1e3
        self.stats.engine_steps_total += 1
        self.stats.step_host_gap_ms = round(gap_ms, 3)
        self.stats.step_host_gap_ms_total = round(
            self.stats.step_host_gap_ms_total + gap_ms, 3
        )
        self._moe_tick()
        self._refresh_gauges()

    def _moe_tick(self) -> None:
        """Drain the wide-EP census and run the slow control loops.

        Per step: fold routed-token counts / dropped slots / peak demand
        into EngineStats, and let the adaptive-capacity controller move
        the live factor (hysteresis lives in AdaptiveCapacity, so
        retrace-causing moves are rare and deliberate). Every
        eplb_interval_steps: compute a fresh expert->shard placement from
        the loads observed SINCE the last rebalance (not all-time — the
        balancer must track drift, not history) and apply it at this
        step boundary."""
        if not self._moe_active:
            return
        census = self.runner.drain_moe_census()
        if census is None:
            return
        E = self.config.model.num_experts
        self._moe_expert_tokens += census[:E].astype(np.int64)
        self.stats.moe_expert_tokens = tuple(
            int(v) for v in self._moe_expert_tokens
        )
        self.stats.moe_dropped_slots_total += int(census[E])
        need = float(census[E + 1])
        if need > self.stats.moe_peak_demand:
            self.stats.moe_peak_demand = round(need, 4)
        if self._adaptive_cap is not None:
            factor = self._adaptive_cap.observe(need)
            if factor is not None:
                self.runner.set_ep_capacity(factor)
        self.stats.moe_capacity_factor = self.runner.ep_capacity
        steps = self.stats.engine_steps_total
        if self._eplb_interval and steps >= self._eplb_next:
            self._eplb_next = steps + self._eplb_interval
            window = self._moe_expert_tokens - self._eplb_window_base
            if window.sum() > 0:
                from llmd_tpu.parallel.eplb import compute_placement

                placement = compute_placement(
                    window,
                    world=self.ctx.world,
                    redundancy=self._eplb_redundancy,
                )
                self.runner.apply_expert_placement(placement)
                self.stats.moe_rebalances_total += 1
                self._eplb_window_base = self._moe_expert_tokens.copy()

    def _refresh_gauges(self) -> None:
        self.stats.num_waiting = self.scheduler.num_waiting
        self.stats.num_running = self.scheduler.num_running
        self.stats.kv_usage = self.allocator.usage()
        if self.swa_allocator is not None:
            self.stats.swa_ring_usage = self.swa_allocator.usage()
            self.stats.swa_ring_pages = self.swa_allocator.num_pages
            if self._swa_sections is not None:
                s = self._swa_sections.stats()
                self.stats.swa_sections = s["entries"]
                self.stats.swa_section_hits = s["hits"]
                self.stats.swa_section_captures = s["captures"]
        self.stats.prefix_hit_ratio = self.allocator.hit_ratio()
        self.stats.preemptions = self.scheduler.num_preemptions
        self.stats.batch_backlog_jobs = sum(
            1 for r in self.scheduler.waiting if r.is_batch
        )
        self.stats.batch_tokens = self.scheduler.batch_tokens
        self.stats.batch_preemptions = self.scheduler.num_batch_preemptions
        self.stats.batch_backfill_utilization = round(
            self.scheduler.last_batch_backfill_tokens
            / max(1, self.config.scheduler.max_num_batched_tokens),
            6,
        )
        if self.scheduler.spec_k:
            sch = self.scheduler
            self.stats.spec_proposed_tokens_total = sch.spec_proposed_tokens
            self.stats.spec_accepted_tokens_total = sch.spec_accepted_tokens
            self.stats.spec_acceptance_rate = round(
                sch.spec_accepted_tokens / max(1, sch.spec_proposed_tokens), 6
            )
            self.stats.spec_accepted_len_hist = tuple(sch.spec_accept_len_hist)
            self.stats.spec_window_iters_total = sch.spec_window_iters
            self.stats.spec_window_early_exit_total = sch.spec_window_early_exit
            self.stats.spec_row_depth_hist = tuple(self._spec_row_depth)
        self.stats.live_tokens_total = self.runner.live_tokens_total
        self.stats.padded_tokens_total = self.runner.padded_tokens_total
        self.stats.dispatches_per_emitted_token = round(
            self.stats.decode_dispatches_total
            / max(1, self.stats.generation_tokens),
            6,
        )
        if self.config.model.num_lora_adapters:
            self.stats.max_lora = self.config.model.num_lora_adapters
            self.stats.running_lora_adapters = tuple(
                sorted({r.lora_name for r in self.scheduler.running if r.lora_name})
            )
            self.stats.waiting_lora_adapters = tuple(
                sorted({r.lora_name for r in self.scheduler.waiting if r.lora_name})
            )
            if self.adapter_pool is not None:
                # Paged pool observability (multi-tenant-lora.md): the
                # waiting list also counts rows PARKED on cold loads —
                # they are queued demand the routing layer must see.
                if self._lora_parked:
                    self.stats.waiting_lora_adapters = tuple(sorted(
                        set(self.stats.waiting_lora_adapters)
                        | {r.lora_name for r in self._lora_parked}
                    ))
                self._refresh_lora_stats()
        if self._host_cache is not None:
            hs = self._host_cache.stats()
            self.stats.offload_pages = hs["pages"]
            self.stats.offload_fs_pages = hs["fs_pages"]
            self.stats.offload_saves = hs["saves"]
            self.stats.offload_restores = hs["restores"]
        if self._kvstore_client is not None:
            ks = self._kvstore_client.stats()
            self.stats.kvstore_pulls = ks["pulls"]
            self.stats.kvstore_pull_failures = ks["pull_failures"]
            self.stats.kvstore_misses = ks["misses"]
            self.stats.kv_publish_paced_bytes_total = ks.get(
                "paced_publish_bytes", 0
            )
        if self._federation is not None:
            fs = self._federation.stats()
            self.stats.kv_federation_published = fs["published"]
            self.stats.kv_federation_hits = fs["hits"]
        if self.offloader is not None:
            self.stats.recompute_avoided_tokens = (
                self.offloader.recompute_avoided_tokens
            )
        if self.pager is not None:
            self.stats.kv_paged_out_bytes = self.pager.paged_out_bytes
            self.stats.kv_pager_prefetch_late_total = (
                self.pager.prefetch_late_total
            )
        self.stats.cp_ring_steps_total = self.runner.cp_ring_steps_total
        if self.kv_connector is not None:
            cs = self.kv_connector.stats()
            self.stats.kv_exported_requests = cs["exported_requests"]
            self.stats.kv_exported_bytes = cs["exported_bytes"]
            self.stats.kv_imported_requests = cs["imported_requests"]
            self.stats.kv_imported_bytes = cs["imported_bytes"]
            self.stats.kv_import_failures = cs["import_failures"]
            self.stats.kv_stream_groups_total = cs["stream_groups_total"]
            self.stats.kv_stream_first_group_ms = cs["last_first_group_ms"]
            self.stats.kv_bundle_crc_failures_total = cs["crc_failures"]
            self.stats.kv_recompute_fallbacks_total = cs[
                "recompute_fallbacks"
            ]
            self.stats.kv_transfer_failures = tuple(
                sorted(cs["transfer_failures"].items())
            )

    # ------------------------------------------------------------------ #

    def generate(
        self,
        prompts: list[list[int]],
        sampling: SamplingParams | list[SamplingParams] | None = None,
        max_steps: int = 100_000,
    ) -> dict[str, list[int]]:
        """Offline batch API: run all prompts to completion."""
        if sampling is None or isinstance(sampling, SamplingParams):
            sampling = [sampling or SamplingParams()] * len(prompts)
        if len(sampling) != len(prompts):
            raise ValueError(
                f"{len(prompts)} prompts but {len(sampling)} sampling params"
            )
        order: list[str] = []
        for p, s in zip(prompts, sampling):
            order.append(self.add_request(p, s))
        done: dict[str, list[int]] = {rid: [] for rid in order}
        for _ in range(max_steps):
            if not self.has_work():
                break
            for out in self.step():
                done[out.request_id].extend(out.new_token_ids)
        return {rid: done[rid] for rid in order}
