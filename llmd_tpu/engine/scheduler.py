"""Continuous-batching scheduler with chunked prefill and prefix caching.

This is the engine-side scheduler (the reference delegates it to vLLM's
continuous batching; docs/architecture/core/model-servers.md:5-7), distinct
from the EPP *request* scheduler in ``llmd_tpu.epp``. Every engine step it
selects a token budget's worth of work: one token per running decode
sequence, plus prompt chunks for waiting/prefilling sequences (chunked
prefill so long prompts never starve decodes -- the reference's
--max-num-batched-tokens / --long-prefill-token-threshold pattern,
guides/agentic-serving/modelserver/tpu/vllm/patch-vllm.yaml:39).

Preemption is recompute-style: when KV pages run out, the youngest running
sequence is evicted, its pages freed, and it restarts from the waiting queue
(its generated tokens are folded into the prompt).
"""

from __future__ import annotations

import bisect
import dataclasses

from llmd_tpu.config import CacheConfig, SchedulerConfig
from llmd_tpu.engine.kv_cache import (
    NoFreePagesError,
    PageAllocator,
    _ROOT_HASH,
    hash_page,
)
from llmd_tpu.engine.request import FinishReason, Request, RequestStatus
from llmd_tpu.engine.sampler import accept_draft_tokens


@dataclasses.dataclass
class ScheduledSeq:
    request: Request
    num_tokens: int  # tokens to compute for this seq in this step
    # Speculative decoding: None for non-speculative rows; a (possibly
    # empty) draft for spec decode rows. The scheduler PLANS with the
    # max-acceptance count (num_tokens = 1 + spec_ngram_k for one-shot
    # verify, window x (1 + k) for fused verify windows, pages
    # included) and the engine fills the actual draft at dispatch time
    # from committed history — which is what lets async staging reuse
    # its existing speculate/rollback machinery unchanged.
    draft_tokens: list[int] | None = None
    # Fused verify windows: max draft tokens the engine may propose for
    # this row at dispatch (None = derive from num_tokens - 1, the
    # one-shot convention). Windowed rows need it explicit because a
    # backed-off row still plans a multi-token width (window one-token
    # iterations) without being allowed to draft.
    spec_draft_cap: int | None = None
    # Fused verify windows: the device-resolved acceptance meta
    # (emitted, drafted, accepted, iterations active), attached by the
    # engine from the readback before update_after_step — the host must
    # NOT re-run the acceptance rule for these rows.
    device_accept: tuple | None = None

    @property
    def start_pos(self) -> int:
        return self.request.num_computed_tokens


@dataclasses.dataclass
class ScheduledBatch:
    prefills: list[ScheduledSeq]
    decodes: list[ScheduledSeq]
    # Fused verify window chosen for this step's decode rows (1 =
    # one-shot verify / plain decode; > 1 only when speculative_ngram
    # composes with fused decode windows in the saturated regime).
    spec_window: int = 1

    @property
    def seqs(self) -> list[ScheduledSeq]:
        return self.prefills + self.decodes

    @property
    def total_tokens(self) -> int:
        return sum(s.num_tokens for s in self.seqs)

    @property
    def is_empty(self) -> bool:
        return not self.prefills and not self.decodes


class EngineScheduler:
    def __init__(
        self,
        scheduler_config: SchedulerConfig,
        cache_config: CacheConfig,
        allocator: PageAllocator,
        max_model_len: int,
        swa_allocator: PageAllocator | None = None,
        swa_ring_pages: int = 0,
        swa_chunk_tokens: int = 0,
    ) -> None:
        self.config = scheduler_config
        self.cache_config = cache_config
        self.allocator = allocator
        # Ring pool for sliding-window layers (CacheConfig.swa_ring): each
        # admitted sequence holds a fixed ring of ``swa_ring_pages`` pages
        # reused circularly, independent of sequence length. Per-seq
        # prefill chunks are capped at ``swa_chunk_tokens`` (the span R is
        # sized for); the BATCH budget may be larger.
        self.swa_allocator = swa_allocator
        self.swa_ring_pages = swa_ring_pages
        self.swa_chunk_tokens = swa_chunk_tokens
        self.max_model_len = max_model_len
        # Ordered by (-priority, arrival_time): higher priority first, FCFS
        # within a priority class (the InferenceObjective priority semantics,
        # reference docs/api-reference/*.md).
        self.waiting: list[Request] = []
        self.running: list[Request] = []
        self.num_preemptions = 0
        # request_id -> committed page hash chain tail + count
        self._chain: dict[str, tuple[bytes, int]] = {}
        # Called with the finished Request before its pages are released
        # (P/D producer KV export point).
        self.finish_hook = None
        # Ring engines: called once when a request's prompt completes
        # (the ring still holds the prompt's trailing window) — the
        # hybrid-APC section capture point.
        self.prefill_complete_hook = None
        # Called when a ring allocation fails: frees idle retained
        # sections (hybrid APC) so live sequences outrank retention.
        # Returns True if anything was freed (retry the allocation).
        self.ring_pressure_hook = None
        # Decode-time KV pager (engine/pager.py): called with a
        # preemption victim before the recompute release. Returns the
        # number of tokens preserved in the host tier (the victim
        # resumes from there instead of recomputing from zero), or 0
        # when the victim was not parked (fall through to recompute).
        self.park_hook = None
        # Async stepping: request ids whose pages the in-flight device
        # programs still read/write — preemption must never evict them
        # (their pages would be freed under the device's feet). Sync
        # engines leave this empty.
        self.protected: set[str] = set()
        # Speculative decoding (SchedulerConfig.speculative_ngram):
        # decode rows are planned at the max-acceptance token count
        # (1 + spec_k) and the accepted prefix is resolved per row at
        # update_after_step; the counters feed EngineStats / the bench.
        self.spec_k = (
            scheduler_config.spec_ngram_k
            if scheduler_config.speculative_ngram else 0
        )
        # Fused verify windows (spec x decode_window): the candidate
        # window sizes (ascending) and the max planned width any staged
        # row can carry — the async truncation keep-bound.
        self.spec_windows = scheduler_config.spec_window_set
        self.spec_plan_max = (
            (1 + self.spec_k) * scheduler_config.spec_window
            if self.spec_k else 0
        )
        self.spec_proposed_tokens = 0
        self.spec_accepted_tokens = 0
        # Fused verify-window accounting: row-iterations executed inside
        # windows, and rows that went inactive (emission limit reached)
        # before their window's last iteration.
        self.spec_window_iters = 0
        self.spec_window_early_exit = 0
        # Accepted-draft-length histogram over spec decode rows: index j
        # counts (row, step) pairs that accepted exactly j draft tokens.
        self.spec_accept_len_hist = [0] * (self.spec_k + 1)
        # Global draft-backoff clock: rows whose last drafts were fully
        # rejected retry only on steps aligned to a power-of-two of this
        # counter, so retries CLUSTER on the same steps (one mixed
        # verify+decode step per retry wave) instead of every step
        # paying the mixed-dispatch cost for one stray drafting row.
        self.spec_step = 0
        # Batch serving tier (SchedulerConfig.batch_backfill;
        # docs/architecture/batch-processing.md): rows at or below
        # PriorityClass.BATCH backfill headroom only. Counters feed
        # EngineStats (batch_tokens_total / batch_preemptions_total /
        # batch_backfill_utilization).
        self.batch_tokens = 0
        self.num_batch_preemptions = 0
        # Batch tokens the LAST schedule() planned (the per-step
        # backfill-utilization gauge's numerator).
        self.last_batch_backfill_tokens = 0

    # ------------------------------------------------------------------ #
    # queue management

    def add_request(self, request: Request) -> None:
        request.status = RequestStatus.WAITING
        bisect.insort(
            self.waiting, request, key=lambda r: (-r.priority, r.arrival_time)
        )

    def abort_request(self, request_id: str) -> Request | None:
        for req in self.running:
            if req.request_id == request_id:
                self._release(req)
                self.running.remove(req)
                req.finish(FinishReason.ABORT)
                return req
        for req in list(self.waiting):
            if req.request_id == request_id:
                self.waiting.remove(req)
                self._release(req)
                req.finish(FinishReason.ABORT)
                return req
        return None

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # ------------------------------------------------------------------ #
    # scheduling

    def schedule(self) -> ScheduledBatch:
        """Select the next batch.

        All position math uses ``num_dispatched_tokens`` (committed +
        in-flight), so the same code path serves both modes: in sync
        engines nothing is ever pending and dispatched == computed; in
        async engines this IS the speculative schedule — the next batch
        is planned assuming every in-flight row lands its tokens, and a
        late finish (EOS/max-tokens at reconcile) invalidates the
        affected staged rows (engine-side rollback).
        """
        budget = self.config.max_num_batched_tokens
        decodes: list[ScheduledSeq] = []
        prefills: list[ScheduledSeq] = []
        scheduled: set[str] = set()

        decoding = [r for r in self.running if r.in_decode_dispatched]
        mid_prefill = [r for r in self.running if not r.in_decode_dispatched]
        # Batch band (PriorityClass.BATCH, SchedulerConfig.batch_backfill):
        # batch rows are split OUT of the interactive phases and only
        # backfill whatever budget/pages those phases leave — the
        # interactive half of this method never sees them, which is what
        # makes interactive streams byte-identical batch-on vs batch-off.
        batch_decoding: list[Request] = []
        batch_prefill: list[Request] = []
        if self._batch_band:
            batch_decoding = [r for r in decoding if r.is_batch]
            batch_prefill = [r for r in mid_prefill if r.is_batch]
            if batch_decoding:
                decoding = [r for r in decoding if not r.is_batch]
            if batch_prefill:
                mid_prefill = [r for r in mid_prefill if not r.is_batch]
        in_backfill = bool(batch_decoding or batch_prefill)

        # Fused K-step decode windows apply whenever this step cannot make
        # admission progress anyway (no admissible waiting request, no
        # in-flight prompt chunks) -- in particular in the saturated regime
        # (running == max_num_seqs with a backlog), which is exactly where
        # the dispatch amortization pays off. Otherwise K=1 keeps admission
        # latency at one step. K is uniform across the batch (one compiled
        # program) and capped so no seq can run past max_model_len.
        # Batch-backfill steps pin K=1: batch rows ride the same program
        # at one-token width (a K-token fused commitment would have to be
        # unwound the moment interactive load preempts them), and a
        # uniform-K dispatch cannot mix widths.
        window = self.config.decode_window
        can_admit = bool(self.waiting) and len(self.running) < self.config.max_num_seqs
        k = 1
        spec_w = 1
        if self.spec_k:
            # Fused verify window: under the same saturated-regime gate
            # as the plain fused window, pick the LARGEST candidate
            # (SchedulerConfig.spec_window_set, the precompiled shapes)
            # whose max-acceptance width — window x (1 + k) per row —
            # fits the whole decode batch in this step's token budget.
            # Degrading the window instead of dropping rows keeps tail
            # rows from starving behind budget-hungry window peers; no
            # candidate fitting means one-shot verify steps as before.
            if (
                self.spec_windows and decoding and not mid_prefill
                and not can_admit and not in_backfill
            ):
                per_batch = (1 + self.spec_k) * len(decoding)
                for w in reversed(self.spec_windows):
                    if w * per_batch <= budget:
                        spec_w = w
                        break
        elif (
            window > 1 and decoding and not mid_prefill and not can_admit
            and not in_backfill
        ):
            k = max(
                1,
                min(
                    window,
                    min(
                        self.max_model_len - r.num_dispatched_tokens
                        for r in decoding
                    ),
                ),
            )

        if self.spec_k and decoding:
            self.spec_step += 1

        # 1. Decodes claim pages FIRST: a running decode must never be
        #    starved by prefill admission taking the last free pages.
        for req in decoding:
            if (
                req.status is not RequestStatus.RUNNING
                or not req.in_decode_dispatched
            ):
                continue  # reset by a preemption earlier in this loop
            if budget <= 0:
                break
            draft_cap = None
            if self.spec_k:
                # Speculative rows plan (budget, pages, pending counts)
                # at the MAX-acceptance count; the actual draft — capped
                # at spec_draft_cap (windowed) or num_tokens - 1
                # (one-shot) — is proposed at dispatch, so the planned
                # slots always cover its provisional KV writes.
                # Backed-off rows (consecutive full rejections) plan as
                # plain rows until their aligned retry step.
                cap = self.max_model_len - req.num_dispatched_tokens
                if spec_w > 1:
                    # Fused verify window: eligible rows plan the full
                    # window x (1 + k) width; backed-off rows still ride
                    # the window as plain one-token iterations (width
                    # spec_w) but must not draft.
                    if self._spec_eligible(req):
                        k_row = max(1, min(spec_w * (1 + self.spec_k), cap))
                        # Up to window x (1+k) - 1 pre-draft tokens: a
                        # fully-accepted iteration consumes k scored
                        # columns PLUS the bonus slot, so window x k
                        # would run the stream dry before the window's
                        # last iteration.
                        draft_cap = k_row - 1
                    else:
                        k_row = max(1, min(spec_w, cap))
                        draft_cap = 0
                else:
                    k_row = 1
                    if self._spec_eligible(req):
                        k_row += max(0, min(self.spec_k, cap - 1))
            else:
                k_row = k
            if not self._ensure_pages(req, k_row):
                # Never evict a sequence already placed in this step's batch:
                # its pages would be freed while the runner still writes them.
                if not self._preempt_for(req, exclude=scheduled):
                    continue
                if not self._ensure_pages(req, k_row):
                    continue
            decodes.append(
                ScheduledSeq(
                    req, k_row,
                    draft_tokens=[] if self.spec_k else None,
                    spec_draft_cap=draft_cap,
                )
            )
            scheduled.add(req.request_id)
            # Drafted positions are real batch compute (the verify step
            # scores 1 + draft tokens for the row), so speculative rows
            # charge their planned width; plain decodes stay at 1.
            budget -= k_row if self.spec_k else 1

        # 2. Continue chunked prefills of already-running sequences.
        for req in mid_prefill:
            if req.status is not RequestStatus.RUNNING or budget <= 0:
                continue
            chunk = min(
                req.num_prompt_tokens - req.num_dispatched_tokens, budget
            )
            if self.swa_chunk_tokens:
                chunk = min(chunk, self.swa_chunk_tokens)
            if chunk <= 0:
                continue
            if not self._ensure_pages(req, chunk):
                continue
            prefills.append(ScheduledSeq(req, chunk))
            scheduled.add(req.request_id)
            budget -= chunk

        # 3. Admit waiting sequences (priority order, FCFS within class).
        #    Interactive only: batch-band heads defer to the backfill
        #    phase below, and an interactive head blocked on slots or
        #    pages reclaims them from RUNNING batch rows first (the
        #    "preempted the moment interactive load returns" half of the
        #    backfill contract — recompute-preemption frees the victims'
        #    provisional pages immediately).
        while self.waiting and budget > 0:
            req = self.waiting[0]
            if req.kv_fetch_pending:
                # Parked by the pager and its attention window is not
                # yet resident again — a wait state, not a fault. The
                # pager's pump retries the restore each step; admission
                # stays FCFS behind it.
                break
            if self._batch_band and req.is_batch:
                break  # backfill phase owns batch admission
            if len(self.running) >= self.config.max_num_seqs:
                # A running batch row's slot yields to an interactive
                # admission; without batch victims the step is full.
                if not (
                    self._batch_band
                    and self._preempt_for(req, exclude=scheduled,
                                          batch_only=True)
                ):
                    break
                continue
            if req.num_computed_tokens == 0:
                self._apply_prefix_cache(req)
            remaining = req.num_prompt_tokens - req.num_dispatched_tokens
            chunk = min(remaining, budget)
            if self.swa_chunk_tokens:
                chunk = min(chunk, self.swa_chunk_tokens)
            if chunk <= 0:
                break
            if not self.config.enable_chunked_prefill and chunk < remaining:
                break  # whole-prompt admission only
            if not self._ensure_ring(req) and not (
                self._reclaim_waiting_ring(req) and self._ensure_ring(req)
            ):
                break  # out of ring pages; retry next step
            if not self._ensure_pages_reclaiming_batch(req, chunk, scheduled):
                # Return the ring: a still-waiting request holding R ring
                # pages would break the pool's sizing guarantee and could
                # stall a higher-priority arrival's admission. Safe only
                # while nothing has been computed into it — a PRELOADED
                # ring (P/D import, num_computed > 0) holds transferred
                # sliding-layer KV and must be kept.
                if req.swa_block_ids and req.num_computed_tokens == 0:
                    self.swa_allocator.free(req.swa_block_ids)
                    req.swa_block_ids = []
                    req.swa_table_row = None
                break  # out of pages; retry next step
            self.waiting.pop(0)
            req.status = RequestStatus.RUNNING
            self.running.append(req)
            prefills.append(ScheduledSeq(req, chunk))
            scheduled.add(req.request_id)
            budget -= chunk

        # 4. Batch backfill: rows at or below PriorityClass.BATCH harvest
        #    whatever token budget and pages the interactive phases left.
        if self._batch_band and budget > 0:
            budget = self._schedule_batch_backfill(
                batch_decoding, batch_prefill, decodes, prefills,
                scheduled, budget,
            )
        self.last_batch_backfill_tokens = sum(
            s.num_tokens
            for s in (*prefills, *decodes)
            if s.request.is_batch
        )

        return ScheduledBatch(
            prefills=prefills, decodes=decodes, spec_window=spec_w
        )

    @property
    def _batch_band(self) -> bool:
        return self.config.batch_backfill

    def _ensure_pages_reclaiming_batch(
        self, req: Request, new_tokens: int, exclude: set[str]
    ) -> bool:
        """_ensure_pages for an INTERACTIVE request, reclaiming pages
        from running batch rows (recompute-preemption, youngest first)
        until the allocation fits or no batch victim remains. With no
        batch rows running this is exactly _ensure_pages."""
        while not self._ensure_pages(req, new_tokens):
            if not (
                self._batch_band
                and self._preempt_for(req, exclude=exclude, batch_only=True)
            ):
                return False
        return True

    def _schedule_batch_backfill(
        self,
        batch_decoding: list[Request],
        batch_prefill: list[Request],
        decodes: list[ScheduledSeq],
        prefills: list[ScheduledSeq],
        scheduled: set[str],
        budget: int,
    ) -> int:
        """The batch band's whole step, run strictly AFTER the
        interactive phases (docs/architecture/batch-processing.md):

        - running batch decodes ride the same dispatch at one-token
          width (never drafting, never windowed — a wider commitment
          would have to be unwound at the next interactive preemption);
        - batch prefill chunks continue with leftover budget;
        - NEW batch rows are admitted only while no interactive request
          is blocked at the queue head, main-pool utilization is at or
          below batch_kv_watermark, and the batch_max_seqs cap (if any)
          has headroom.

        Page pressure inside the band preempts OTHER batch rows only —
        an interactive row is never a victim of batch work."""
        for req in batch_decoding:
            if (
                req.status is not RequestStatus.RUNNING
                or not req.in_decode_dispatched
            ):
                continue  # reset by a preemption earlier in this pass
            if budget <= 0:
                break
            if not self._ensure_pages(req, 1):
                if not self._preempt_for(req, exclude=scheduled,
                                         batch_only=True):
                    continue
                if not self._ensure_pages(req, 1):
                    continue
            decodes.append(
                ScheduledSeq(
                    req, 1,
                    # Spec engines: batch rows stay draft-less (cap 0)
                    # so acceptance accounting runs but no provisional
                    # verify columns are ever planned for them.
                    draft_tokens=[] if self.spec_k else None,
                    spec_draft_cap=0 if self.spec_k else None,
                )
            )
            scheduled.add(req.request_id)
            budget -= 1
        for req in batch_prefill:
            if req.status is not RequestStatus.RUNNING or budget <= 0:
                continue
            chunk = min(
                req.num_prompt_tokens - req.num_dispatched_tokens, budget
            )
            if self.swa_chunk_tokens:
                chunk = min(chunk, self.swa_chunk_tokens)
            if chunk <= 0:
                continue
            if not self._ensure_pages(req, chunk):
                continue  # wait for headroom; batch never preempts upward
            prefills.append(ScheduledSeq(req, chunk))
            scheduled.add(req.request_id)
            budget -= chunk
        while (
            self.waiting
            and budget > 0
            and len(self.running) < self.config.max_num_seqs
            and self.waiting[0].is_batch
        ):
            if (
                self.config.batch_max_seqs
                and sum(1 for r in self.running if r.is_batch)
                >= self.config.batch_max_seqs
            ):
                break
            if self.allocator.usage() > self.config.batch_kv_watermark:
                break  # pool too hot: admitting would enter the
                # preemption regime interactive rows pay for
            req = self.waiting[0]
            if req.num_computed_tokens == 0:
                self._apply_prefix_cache(req)
            remaining = req.num_prompt_tokens - req.num_dispatched_tokens
            chunk = min(remaining, budget)
            if self.swa_chunk_tokens:
                chunk = min(chunk, self.swa_chunk_tokens)
            if chunk <= 0:
                break
            if not self.config.enable_chunked_prefill and chunk < remaining:
                break  # whole-prompt admission only
            if not self._ensure_ring(req):
                break  # rings are interactive capacity: never reclaimed
            if not self._ensure_pages(req, chunk):
                if req.swa_block_ids and req.num_computed_tokens == 0:
                    self.swa_allocator.free(req.swa_block_ids)
                    req.swa_block_ids = []
                    req.swa_table_row = None
                break  # out of pages; retry next step
            self.waiting.pop(0)
            req.status = RequestStatus.RUNNING
            self.running.append(req)
            prefills.append(ScheduledSeq(req, chunk))
            scheduled.add(req.request_id)
            budget -= chunk
        return budget

    @staticmethod
    def _hash_extra(req: Request) -> bytes:
        """Cache-identity discriminator: LoRA-adapted KV (v is adapted)
        must never be shared across adapters or with the base model
        (reference kv-indexer.md:145-151 key folding)."""
        if not req.lora_id:
            return b""
        # Salt by NAME (stable across engine processes and the router's
        # token-producer, which folds `lora:<model>`). Unnamed requests
        # salt in a DISTINCT namespace: a digit-only adapter name must
        # never collide with a raw slot id.
        if req.lora_name:
            return f"lora:{req.lora_name}".encode()
        return f"lora-slot:{req.lora_id}".encode()

    def _apply_prefix_cache(self, req: Request) -> None:
        """Reuse cached full pages covering the prompt prefix."""
        if req.block_ids:
            return
        if self.swa_ring_pages:
            # Ring engines do HYBRID hits at engine admission only: a
            # full-pool hit is usable solely when a retained sliding
            # section seeds the fresh ring (engine SwaSectionCache) —
            # a bare full-pool shortcut here would skip sliding-layer
            # KV the ring never got and silently decode garbage.
            return
        # Never satisfy the *entire* prompt from cache: the last token must be
        # computed so the step emits logits for sampling. Lookup + touch
        # are one atomic allocator call: a concurrent allocate() (the
        # multi-host streamed-import fetch thread) must not steal a
        # ref-0 hit between the two.
        max_cached = (req.num_prompt_tokens - 1) // self.allocator.page_size
        cached = self.allocator.lookup_and_touch_prefix(
            req.prompt_token_ids, extra=self._hash_extra(req),
            max_pages=max_cached,
        )
        if not cached:
            return
        req.block_ids.extend(cached)
        n = len(cached)
        req.num_cached_tokens = n * self.allocator.page_size
        req.num_computed_tokens = req.num_cached_tokens
        parent = _ROOT_HASH
        for i in range(n):
            parent = hash_page(
                parent,
                req.prompt_token_ids[i * self.allocator.page_size : (i + 1) * self.allocator.page_size],
                extra=self._hash_extra(req),
            )
        self._chain[req.request_id] = (parent, n)

    def _ensure_ring(self, req: Request) -> bool:
        """Allocate the sequence's sliding-window ring (once, at admission).

        The auto-sized ring pool (max_num_seqs x R) covers every RUNNING
        sequence; P/D preloads additionally allocate rings at add_request
        time (outside admission), so a burst of preloaded arrivals can
        transiently exhaust the pool — _reclaim_waiting_ring keeps the
        queue head admissible then. An explicit smaller swa_blocks turns
        shortage into a wait-for-next-step, like the main pool.
        """
        if self.swa_allocator is None or req.swa_block_ids:
            return True
        while True:
            try:
                req.swa_block_ids = self.swa_allocator.allocate(
                    self.swa_ring_pages
                )
                return True
            except NoFreePagesError:
                # Idle retained sections (hybrid APC) yield to live
                # sequences before admission gives up for this step.
                if self.ring_pressure_hook is None or not self.ring_pressure_hook():
                    return False

    def _reclaim_waiting_ring(self, req: Request) -> bool:
        """Downgrade the youngest preloaded WAITING request: free its ring
        (and preloaded pages), resetting it to plain local recompute.

        Without this, preloaded arrivals holding rings behind a ring-less
        queue head would starve admission forever (nothing running, so no
        ring would ever free) — correctness over the transfer savings.
        """
        for victim in reversed(self.waiting):
            if victim is req or not victim.swa_block_ids:
                continue
            if victim.status is not RequestStatus.WAITING:
                continue
            self._release(victim)  # frees pages + ring
            victim.num_computed_tokens = 0
            victim.num_cached_tokens = 0
            return True
        return False

    def _ensure_pages(self, req: Request, new_tokens: int) -> bool:
        # Dispatched position: in-flight tokens already own their slots.
        need_slots = req.num_dispatched_tokens + new_tokens
        need_pages = -(-need_slots // self.allocator.page_size)
        missing = need_pages - len(req.block_ids)
        if missing <= 0:
            return True
        try:
            req.block_ids.extend(self.allocator.allocate(missing))
            return True
        except NoFreePagesError:
            return False

    def _preempt_for(
        self,
        req: Request,
        exclude: set[str] = frozenset(),
        batch_only: bool = False,
    ) -> bool:
        """Evict the youngest other running sequence to recompute later.

        Victim order is (lowest priority, youngest) — batch-band rows
        are therefore always reclaimed before any interactive row.
        ``batch_only`` restricts the victim set to the batch band (the
        interactive-pressure reclaim path: interactive admission must
        never evict interactive work just to make room for itself).

        In-flight sequences (``protected``, async stepping) are never
        victims: the dispatched device programs still read/write their
        pages, and recompute-preemption frees those pages immediately.
        """
        victims = [
            r for r in self.running
            if r is not req
            and r.request_id not in exclude
            and r.request_id not in self.protected
            and (not batch_only or r.is_batch)
        ]
        if not victims:
            return False
        victim = max(victims, key=lambda r: (r.priority * -1, r.arrival_time))
        if victim.is_batch:
            self.num_batch_preemptions += 1
        kept = self.park_hook(victim) if self.park_hook is not None else 0
        if kept:
            # Parked: the pager already hosted the committed KV and
            # released the HBM pages; only queue bookkeeping remains.
            # Resume streams the attention window back instead of
            # recomputing the whole prefix.
            victim.num_pending_tokens = 0
            self.protected.discard(victim.request_id)
        else:
            self._release(victim)
        self.running.remove(victim)
        # Fold generated tokens into the prompt and restart from scratch
        # (or, when parked, from the pager's preserved prefix).
        victim.num_prior_output_tokens += len(victim.output_token_ids)
        victim.prompt_token_ids = victim.all_token_ids
        victim.output_token_ids = []
        self.num_preemptions += 1
        victim.num_computed_tokens = kept
        victim.num_cached_tokens = kept
        victim.status = RequestStatus.PREEMPTED
        # insort keeps the victim FCFS-ordered by its original arrival time
        # within its priority class, so it resumes ahead of newer arrivals.
        bisect.insort(
            self.waiting, victim, key=lambda r: (-r.priority, r.arrival_time)
        )
        return True

    def _release(self, req: Request) -> None:
        req.num_pending_tokens = 0
        self.protected.discard(req.request_id)
        if req.block_ids:
            # Paged-out indexes hold stale ids — the pager freed (and the
            # allocator may have recycled) those pages when it spilled
            # them to the host tier; freeing again would corrupt another
            # sequence's pages.
            ids = [
                b for i, b in enumerate(req.block_ids)
                if i not in req.paged_out
            ]
            if ids:
                self.allocator.free(ids)
            req.block_ids = []
        req.paged_out.clear()
        req.kv_fetch_pending = False
        if req.swa_block_ids:
            self.swa_allocator.free(req.swa_block_ids)
            req.swa_block_ids = []
            req.swa_table_row = None
        self._chain.pop(req.request_id, None)

    # ------------------------------------------------------------------ #
    # post-step bookkeeping

    def note_dispatch(self, batch: ScheduledBatch) -> None:
        """Mark a dispatched batch's tokens as in flight (async stepping).

        Until the readback commits them, scheduling proceeds against the
        dispatched positions and the sequences are protected from
        preemption. ``update_after_step`` is the matching commit (it
        drains the pending counts); sync engines call both back to back,
        so the window is empty there.
        """
        for seq in batch.seqs:
            seq.request.num_pending_tokens += seq.num_tokens
            self.protected.add(seq.request.request_id)

    def _commit_pending(self, seq: ScheduledSeq) -> None:
        req = seq.request
        req.num_pending_tokens = max(0, req.num_pending_tokens - seq.num_tokens)
        self.protected.discard(req.request_id)

    def update_after_step(
        self, batch: ScheduledBatch, sampled: dict[str, list[int]]
    ) -> dict[str, list[int]]:
        """Advance state after the device step.

        ``sampled`` maps request id -> the window of sampled tokens (length 1
        for prefill/single-step rows, K for fused decode windows). Tokens
        past a stop condition are discarded (their speculative KV writes sit
        in pages that are freed with the request and never committed).
        Returns the tokens actually accepted per request.
        """
        accepted: dict[str, list[int]] = {}
        for seq in batch.prefills:
            req = seq.request
            self._commit_pending(seq)
            req.num_computed_tokens += seq.num_tokens
            if req.is_batch:
                self.batch_tokens += seq.num_tokens
            if req.in_decode:  # this chunk completed the prompt -> 1st token
                if self.prefill_complete_hook is not None:
                    # Hybrid-APC capture: the ring still holds the
                    # prompt's trailing window right now.
                    self.prefill_complete_hook(req)
                token = sampled[req.request_id][0]
                req.output_token_ids.append(token)
                accepted[req.request_id] = [token]
                reason = self._check_stop(req, token)
                if reason is not None:
                    self._finish(req, reason)
                    continue
            self._commit_full_pages(req)
        for seq in batch.decodes:
            req = seq.request
            self._commit_pending(seq)
            window = sampled[req.request_id]
            if seq.device_accept is not None:
                # Fused verify window: the accept/reject decision ran ON
                # DEVICE (the whole point — one host round-trip per K
                # verify iterations), so the host only folds the meta
                # into the same counters the one-shot path feeds. The
                # emitted window then runs the SAME stop-check loop
                # below.
                _emitted, drafted, n_acc, iters = seq.device_accept
                self.spec_proposed_tokens += drafted
                self.spec_accepted_tokens += n_acc
                req.spec_drafted_tokens += drafted
                req.spec_accepted_tokens += n_acc
                self.spec_window_iters += iters
                if iters < batch.spec_window:
                    self.spec_window_early_exit += 1
                # Histogram: the per-iteration accept-length breakdown
                # stays on device, so distribute (count += iters,
                # sum += n_acc) across buckets — the mean-emitted
                # reading (1 + sum/count) the panel derives is EXACT;
                # only the shape within a window is approximated.
                if iters > 0:
                    full, part = divmod(n_acc, self.spec_k)
                    self.spec_accept_len_hist[self.spec_k] += full
                    used = full
                    if part:
                        self.spec_accept_len_hist[part] += 1
                        used += 1
                    self.spec_accept_len_hist[0] += max(0, iters - used)
                if drafted and n_acc == 0:
                    req.spec_consec_rejected += 1
                elif n_acc > 0:
                    req.spec_consec_rejected = 0
            elif seq.draft_tokens:
                # Speculative row: resolve the accepted prefix first
                # (sampler.accept_draft_tokens), then run the emitted
                # window through the SAME stop-check loop as a fused
                # decode window — tokens past a stop (or past the first
                # draft mismatch) are discarded and their provisional KV
                # never counts as computed.
                window, n_acc = accept_draft_tokens(seq.draft_tokens, window)
                self.spec_proposed_tokens += len(seq.draft_tokens)
                self.spec_accepted_tokens += n_acc
                self.spec_accept_len_hist[n_acc] += 1
                req.spec_drafted_tokens += len(seq.draft_tokens)
                req.spec_accepted_tokens += n_acc
                # Draft backoff: a fully-rejected draft suggests the
                # n-gram matches are spurious (low-repetition output) —
                # exponentially sparser aligned retries (_spec_eligible)
                # cap the wasted verify columns.
                if n_acc == 0:
                    req.spec_consec_rejected += 1
                else:
                    req.spec_consec_rejected = 0
            elif seq.draft_tokens is not None:
                # Spec row that drafted nothing: plain committed
                # samples, no provisional writes. A windowed fallback
                # step (batch.spec_window > 1 with no row drafting)
                # emitted one committed sample per fused iteration.
                self.spec_accept_len_hist[0] += (
                    len(window) if batch.spec_window > 1 else 1
                )
            acc: list[int] = []
            reason = None
            for token in window:
                req.num_computed_tokens += 1
                req.output_token_ids.append(token)
                acc.append(token)
                reason = self._check_stop(req, token)
                if reason is not None:
                    break
            accepted[req.request_id] = acc
            if req.is_batch:
                self.batch_tokens += len(acc)
            if reason is not None:
                self._finish(req, reason)
            else:
                self._commit_full_pages(req)
                if seq.draft_tokens or (
                    batch.spec_window > 1 and seq.draft_tokens is not None
                ):
                    # Drafting rows made provisional KV writes; windowed
                    # rows additionally planned pages at the full
                    # window x (1 + k) width they may not have emitted.
                    # Plain one-shot draft-less rows hold at most one
                    # page of planned headroom, which the next step
                    # reuses — no truncation walk for them.
                    self._truncate_spec_pages(req)
        return accepted

    def _spec_eligible(self, req: Request) -> bool:
        """Draft-backoff gate: after c consecutive fully-rejected drafts
        a row retries only on steps where the global clock is a multiple
        of 2^min(c+1, 8). The shared clock ALIGNS retries across rows —
        low-repetition traffic converges to plain decode steps with one
        clustered retry wave every few hundred steps, instead of every
        step paying a mixed verify dispatch for one stray row. A single
        accepted token resets the row to drafting every step."""
        c = req.spec_consec_rejected
        return c == 0 or self.spec_step % (1 << min(c + 1, 8)) == 0

    def _truncate_spec_pages(self, req: Request) -> None:
        """Return the pages a speculative row claimed past its accepted
        prefix (the partial-rollback half of the propose/verify/accept
        contract): rejected draft tokens' provisional KV writes sit in
        slots >= num_computed_tokens, which by construction are never
        committed (``_commit_full_pages`` stops at the computed-token
        page floor) — freeing the trailing pages BEFORE any commit_page
        call makes it structurally impossible for rejected content to
        enter the prefix-cache hash chain.

        Async engines keep the slots a staged-but-undispatched next
        batch may already be planned against (its verify writes reach at
        most num_dispatched + the max planned width — 1 + spec_k for
        one-shot verify, window x (1 + k) when fused verify windows are
        on); sync engines have nothing in flight here and keep exactly
        the computed span — the next schedule's _ensure_pages re-extends
        as needed."""
        page = self.allocator.page_size
        slots = req.num_computed_tokens
        if self.config.async_scheduling:
            slots = req.num_dispatched_tokens + self.spec_plan_max
        keep = -(-slots // page)
        if keep < len(req.block_ids):
            self.allocator.free(req.block_ids[keep:])
            del req.block_ids[keep:]

    def _finish(self, req: Request, reason: FinishReason) -> None:
        # Commit computed full pages before release: the KV is valid, so
        # future identical prompts (and P/D exports) can reuse it.
        self._commit_full_pages(req)
        if self.finish_hook is not None:
            # P/D producer export runs here, while block_ids are live.
            self.finish_hook(req)
        self._release(req)
        self.running.remove(req)
        req.finish(reason)

    def _check_stop(self, req: Request, token: int) -> FinishReason | None:
        s = req.sampling
        if not s.ignore_eos and token in s.stop_token_ids:
            return FinishReason.STOP
        if req.total_output_tokens >= s.max_tokens:
            return FinishReason.LENGTH
        if req.num_tokens >= self.max_model_len:
            return FinishReason.LENGTH
        return None

    def seed_commit_chain(self, req: Request, parent: bytes, committed: int) -> None:
        """Mark the request's first ``committed`` pages as already in the
        prefix index with ``parent`` as the chain head — the one
        sanctioned way for admission-side hit paths (hybrid SWA-ring) to
        keep _commit_full_pages from re-hashing and re-committing a
        cached prefix."""
        self._chain[req.request_id] = (parent, committed)

    def hash_extra(self, req: Request) -> bytes:
        """Public cache-identity discriminator (see _hash_extra)."""
        return self._hash_extra(req)

    def commit_chain_state(self, req: Request) -> tuple[bytes, int]:
        """(chain tail hash, committed page count) — the pager consults
        this before seeding past a spilled range so it never regresses a
        prefix-cache-seeded chain."""
        return self._chain.get(req.request_id, (_ROOT_HASH, 0))

    def _commit_full_pages(self, req: Request) -> None:
        """Register newly-completed full pages in the prefix index."""
        if not self.allocator.enable_prefix_caching:
            return  # commit_page would no-op; skip the hashing walk too
        page = self.allocator.page_size
        parent, committed = self._chain.get(req.request_id, (_ROOT_HASH, 0))
        # Only KV already computed counts; the just-sampled token's KV is not
        # yet written (it is written when fed as input next step).
        full = req.num_computed_tokens // page
        tokens = req.all_token_ids
        while committed < full:
            chunk = tokens[committed * page : (committed + 1) * page]
            h = hash_page(parent, chunk, extra=self._hash_extra(req))
            self.allocator.commit_page(req.block_ids[committed], h, chunk, parent)
            parent = h
            committed += 1
        self._chain[req.request_id] = (parent, committed)
