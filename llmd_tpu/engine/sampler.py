"""Token sampling on device: temperature / top-k / top-p / greedy.

One jit-traced function over the whole batch; per-request knobs arrive as
arrays so one compiled program serves any mix of greedy and sampled
sequences (no recompilation per sampling config).

Also hosts the speculative-decoding acceptance rule
(``accept_draft_tokens``): the host-side half of the verify step that
turns per-position target samples plus a draft into the emitted window.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SamplingInputs:
    temperature: jax.Array  # [B] f32; <=1e-5 means greedy
    top_k: jax.Array  # [B] i32; 0 disables
    top_p: jax.Array  # [B] f32; 1.0 disables
    # Per-row PRNG seed: rows with SamplingParams.seed get a deterministic
    # seed derived from (seed, output position); others get engine-RNG draws.
    seeds: jax.Array  # [B] u32


def sample_tokens(
    logits: jax.Array, s: SamplingInputs, all_greedy: bool = False
) -> tuple[jax.Array, jax.Array]:
    """Returns (token_ids [B] i32, logprobs [B] f32 of the chosen token).

    ``all_greedy`` is a trace-time flag (the host knows the batch's sampling
    mix): it elides the sort/top-k/top-p/gumbel pipeline entirely, which
    matters at TPU vocab sizes (two [B, 128k] sorts per decode step).
    """
    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1)
    if all_greedy:
        logp = jax.nn.log_softmax(logits, axis=-1)
        chosen = jnp.take_along_axis(logp, greedy_tok[:, None], axis=-1)[:, 0]
        return greedy_tok.astype(jnp.int32), chosen

    temp = jnp.maximum(s.temperature, 1e-5)[:, None]
    scaled = logits / temp

    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]
    # top-k: keep values >= k-th largest (k=0 -> keep all).
    k = jnp.where(s.top_k > 0, s.top_k, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)

    # top-p (nucleus): smallest prefix of the sorted dist with mass >= top_p.
    probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    keep_sorted = (cum - probs_sorted) < s.top_p[:, None]  # always keeps rank 0
    num_keep = jnp.maximum(jnp.sum(keep_sorted, axis=-1), 1)
    p_thresh = jnp.take_along_axis(sorted_desc, (num_keep - 1)[:, None], axis=-1)
    scaled = jnp.where(scaled >= p_thresh, scaled, -jnp.inf)

    keys = jax.vmap(jax.random.key)(s.seeds)
    gumbel = jax.vmap(lambda k: jax.random.gumbel(k, (V,), jnp.float32))(keys)
    sampled_tok = jnp.argmax(scaled + gumbel, axis=-1)

    tokens = jnp.where(s.temperature <= 1e-5, greedy_tok, sampled_tok)
    logp = jax.nn.log_softmax(logits, axis=-1)
    chosen_logp = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
    return tokens.astype(jnp.int32), chosen_logp


def spec_seed(seed, out_index):
    """The per-(request seed, output index) sampling-seed derivation —
    THE one definition every dispatch path uses. Prefill, fused decode
    windows, and the one-shot verify step derive it on HOST
    (``ModelRunner._overwrite_seeded_rows``, Python ints); the fused
    verify window derives it ON DEVICE (uint32 arrays), because each
    row's output index there depends on its own acceptance, which only
    the device knows mid-window. Multiplication mod 2^32 respects
    residues, so the uint32 array form equals the masked Python-int
    form bit for bit — which is what keeps seeded speculative streams
    byte-identical whichever path samples a given output index."""
    if hasattr(seed, "dtype"):
        # uint32 array path (device or numpy): the dtype's wraparound IS
        # the mod-2^32 mask, and a literal 0xFFFFFFFF would overflow
        # jax's weak-typed int32 promotion.
        return seed * np.uint32(1000003) + out_index
    return (seed * 1000003 + out_index) & 0xFFFFFFFF


def accept_counts(draft, target, draft_len, xp=jnp):
    """Vectorized Leviathan-style acceptance rule, shared by BOTH
    acceptance paths: the host one-shot verify (numpy, via
    ``accept_draft_tokens``) and the fused verify window's on-device
    accept/reject (jnp, inside ``ModelRunner._build_verify_window``'s
    ``fori_loop`` body).

    ``draft [..., k]`` vs ``target [..., >=k]`` (the target model's
    per-position samples), with ``draft_len [...]`` masking each row's
    real draft width. Returns ``(n_emit, n_acc)``: ``n_acc`` is the
    longest accepted prefix (leading run of draft[j] == target[j] with
    j < draft_len) and ``n_emit = n_acc + 1`` — the accepted drafts
    plus the correction/bonus sample that always lands.
    """
    k = draft.shape[-1]
    idx = xp.arange(k)
    matches = (draft == target[..., :k]) & (idx < draft_len[..., None])
    n_acc = xp.sum(xp.cumprod(matches.astype(xp.int32), axis=-1), axis=-1)
    return n_acc + 1, n_acc


def accept_draft_tokens(
    draft: list[int], sampled: list[int]
) -> tuple[list[int], int]:
    """Speculative-decoding acceptance: longest draft prefix consistent
    with the target distribution.

    ``sampled[j]`` is the token the TARGET model samples at drafted
    position j (greedy argmax, or the per-(seed, output-index) PRNG draw
    for seeded rows) — computed in one verify pass whose position-j
    context is ``draft[:j]``. That context is valid exactly while every
    prior draft token matched its target sample, so the emitted window is
    ``sampled[0 .. m]`` where m is the first mismatch (the target's
    correction token lands for free at the mismatch position, and the
    bonus sample at the end when the whole draft holds). Every emitted
    token IS a target sample under a correct context, which is why
    speculative streams are byte-identical to non-speculative ones for
    greedy and seeded rows (Leviathan et al. 2023 specialized to
    deterministic per-position sampling).

    Returns (emitted window, number of draft tokens accepted). A thin
    numpy wrapper over ``accept_counts``, the jittable rule the fused
    verify window applies on device.
    """
    if not sampled:
        return [], 0
    k = min(len(draft), len(sampled))
    d = np.asarray(draft[:k], np.int64).reshape(1, k)
    t = np.asarray(sampled[:k], np.int64).reshape(1, k)
    _, n_acc = accept_counts(d, t, np.asarray([k]), xp=np)
    n_acc = int(n_acc[0])
    n_emit = min(n_acc + 1, len(sampled))
    return [int(tok) for tok in sampled[:n_emit]], n_acc
