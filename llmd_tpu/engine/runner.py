"""Model runner: owns device state (params + KV pool) and the jitted step.

Everything under jit is traced once per shape bucket and cached
(compiler-friendly static shapes -- no data-dependent Python control flow).
The runner pads each step's work to the nearest bucket:

- decode: batch of running seqs padded to a batch bucket, Q=1
- prefill: one seq per call, chunk padded to a token bucket

This is the classic split-step TPU schedule; the ragged Pallas kernel path
(mixed prefill+decode in one launch) plugs in behind the same interface.

KV pool: ONE jax.Array [L, pages, page, K, 2D] sharded over tp on the KV
head axis, donated through the step so XLA updates it in place.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from llmd_tpu.config import EngineConfig
from llmd_tpu.engine.sampler import SamplingInputs, sample_tokens
from llmd_tpu.engine.scheduler import ScheduledSeq
from llmd_tpu.models import llama
from llmd_tpu.models.common import StepInput
from llmd_tpu.parallel.mesh import KV_CACHE_SPEC, MeshContext, shard_params


def _buckets(limit: int, start: int = 8) -> tuple[int, ...]:
    out, b = [], start
    while b < limit:
        out.append(b)
        b *= 2
    out.append(limit)
    return tuple(dict.fromkeys(out))


def pad_to_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclass
class StepResult:
    tokens: np.ndarray  # [B] sampled token per row
    logprobs: np.ndarray  # [B]


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        mesh_ctx: MeshContext,
        params: dict | None = None,
    ) -> None:
        self.config = config
        self.cfg = config.model
        self.ctx = mesh_ctx
        self.max_pages = config.cache.max_pages_per_seq(self.cfg.max_model_len)
        self.page = config.cache.page_size

        if params is None:
            params = llama.init_params(self.cfg, jax.random.key(config.seed))
        self.params = shard_params(params, mesh_ctx)
        self.kv_cache = self._alloc_kv()
        self._np_rng = np.random.default_rng(config.seed ^ 0x5EED)

        sched = config.scheduler
        self.decode_buckets = sched.decode_batch_buckets or _buckets(sched.max_num_seqs)
        self.prefill_buckets = sched.prefill_token_buckets or _buckets(
            sched.max_num_batched_tokens, start=16
        )
        self._step = self._build_step()

    # ------------------------------------------------------------------ #

    def _alloc_kv(self) -> jax.Array:
        c = self.config.cache
        shape = (
            self.cfg.num_layers,
            c.num_blocks,
            c.page_size,
            self.cfg.num_kv_heads,
            2 * self.cfg.head_dim,
        )
        return jnp.zeros(shape, jnp.dtype(c.dtype), device=self.ctx.sharding(*KV_CACHE_SPEC))

    def kv_bytes(self) -> int:
        return self.kv_cache.size * self.kv_cache.dtype.itemsize

    def _build_step(self):
        cfg = self.cfg

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(params, kv_cache, inp: StepInput, s: SamplingInputs):
            hidden, kv_cache = llama.forward_hidden(params, kv_cache, inp, cfg)
            B = hidden.shape[0]
            last = jnp.maximum(inp.query_lens - 1, 0)
            h_last = hidden[jnp.arange(B), last]  # [B, H]
            logits = llama.compute_logits(params, h_last, cfg)
            tokens, logprobs = sample_tokens(logits, s)
            return kv_cache, tokens, logprobs

        return step

    # ------------------------------------------------------------------ #
    # host-side input prep

    def _sampling_inputs(self, seqs: list[ScheduledSeq], B: int) -> SamplingInputs:
        temp = np.zeros(B, np.float32)
        top_k = np.zeros(B, np.int32)
        top_p = np.ones(B, np.float32)
        seeds = self._np_rng.integers(0, 2**32, size=B, dtype=np.uint32)
        for i, s in enumerate(seqs):
            sp = s.request.sampling
            temp[i] = 0.0 if sp.greedy else sp.temperature
            top_k[i] = sp.top_k
            top_p[i] = sp.top_p
            if sp.seed is not None:
                # Deterministic per (request seed, output index): resubmitting
                # the same seeded request reproduces its tokens regardless of
                # batch-mates.
                pos = s.request.total_output_tokens
                seeds[i] = np.uint32((sp.seed * 1000003 + pos) & 0xFFFFFFFF)
        return SamplingInputs(
            temperature=jnp.asarray(temp),
            top_k=jnp.asarray(top_k),
            top_p=jnp.asarray(top_p),
            seeds=jnp.asarray(seeds),
        )

    def _page_table(self, seqs: list[ScheduledSeq], B: int) -> np.ndarray:
        pt = np.zeros((B, self.max_pages), np.int32)
        for i, s in enumerate(seqs):
            ids = s.request.block_ids
            pt[i, : len(ids)] = ids
        return pt

    def run_decode(self, seqs: list[ScheduledSeq]) -> StepResult:
        """One decode token for each running sequence."""
        n = len(seqs)
        B = pad_to_bucket(n, self.decode_buckets)
        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros((B, 1), np.int32)
        qlens = np.zeros(B, np.int32)
        kvlens = np.zeros(B, np.int32)
        for i, s in enumerate(seqs):
            req = s.request
            tokens[i, 0] = req.all_token_ids[req.num_computed_tokens]
            positions[i, 0] = req.num_computed_tokens
            qlens[i] = 1
            kvlens[i] = req.num_computed_tokens + 1
        inp = StepInput(
            token_ids=jnp.asarray(tokens),
            positions=jnp.asarray(positions),
            query_lens=jnp.asarray(qlens),
            kv_lens=jnp.asarray(kvlens),
            page_table=jnp.asarray(self._page_table(seqs, B)),
        )
        self.kv_cache, tok, logp = self._step(
            self.params, self.kv_cache, inp, self._sampling_inputs(seqs, B)
        )
        return StepResult(np.asarray(tok)[:n], np.asarray(logp)[:n])

    def run_prefill(self, seq: ScheduledSeq) -> StepResult:
        """One prompt chunk for one sequence (B=1, Q=bucket)."""
        req = seq.request
        start, n = req.num_computed_tokens, seq.num_tokens
        Q = pad_to_bucket(n, self.prefill_buckets)
        chunk = req.all_token_ids[start : start + n]
        tokens = np.zeros((1, Q), np.int32)
        tokens[0, :n] = chunk
        positions = np.full((1, Q), start + max(n - 1, 0), np.int32)
        positions[0, :n] = np.arange(start, start + n)
        inp = StepInput(
            token_ids=jnp.asarray(tokens),
            positions=jnp.asarray(positions),
            query_lens=jnp.asarray([n], np.int32),
            kv_lens=jnp.asarray([start + n], np.int32),
            page_table=jnp.asarray(self._page_table([seq], 1)),
        )
        self.kv_cache, tok, logp = self._step(
            self.params, self.kv_cache, inp, self._sampling_inputs([seq], 1)
        )
        return StepResult(np.asarray(tok)[:1], np.asarray(logp)[:1])
