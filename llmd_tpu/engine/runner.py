"""Model runner: owns device state (params + KV pool) and the jitted steps.

Multi-host (reference wide-EP LWS shape, docs/infrastructure/
multi-node.md:3-41): when ``jax.distributed`` is initialized with >1
process, ONE runner spans the global mesh. The leader (process 0) runs the
scheduler and broadcasts each step's host inputs (fixed-size header + array
payload via ``multihost_utils.broadcast_one_to_all``); followers sit in
``follower_loop`` mirroring every dispatch so all processes execute the
same XLA programs in lockstep — the property a real LWS deployment relies
on. Sampled tokens come back replicated so every host reads them locally.

TPU-first scheduling shapes (everything static per bucket, traced once):

- **batched prefill**: all scheduled prompt chunks run in ONE call
  [B_bucket, Q_bucket] -- one weight read per step instead of one per
  sequence (HBM bandwidth is the bottleneck; see SURVEY.md section 7
  "hard parts").
- **multi-step decode**: K decode iterations fused into one jit call with a
  ``lax.fori_loop`` that feeds each sampled token back as the next input
  ON DEVICE. The host gets one packed transfer per K tokens, which
  amortizes dispatch/transfer latency (the reference fights the same battle
  with --enable-dbo / DP supervisor batching; on a remote-dispatch TPU
  runtime the roundtrip is the whole game).
- stop conditions are reconciled on host AFTER the window: tokens past a
  stop are discarded and never committed to the prefix cache.

KV pool: ONE jax.Array [L, pages, K, page, 2D] (head-major within a page so
a (page, head) slab is one contiguous DMA), sharded over tp on the KV head
axis, donated through every step so XLA updates it in place.
"""

from __future__ import annotations

import concurrent.futures
import functools
import logging
import os
import threading
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from llmd_tpu import ops
from llmd_tpu.config import EngineConfig, swa_ring_spec
from llmd_tpu.engine.sampler import (
    SamplingInputs,
    accept_counts,
    sample_tokens,
    spec_seed,
)
from llmd_tpu.engine.scheduler import ScheduledSeq
from llmd_tpu.models import llama
from llmd_tpu.models.common import StepInput
from llmd_tpu.parallel import distributed as dist
from llmd_tpu.parallel.mesh import MeshContext, kv_cache_spec, shard_params

# Multi-host dispatch opcodes (fixed-size i32 header broadcast leader ->
# followers before each step's array payload). KV_GATHER/KV_SCATTER are
# the staging legs of P/D transfer + tiered offload over a multi-process
# mesh: every process dispatches the same SPMD gather/scatter program
# (the gather all-gathers the tp-sharded head axis to a replicated
# bundle the leader can stage; the scatter writes broadcast values into
# each process's own pool shards).
_OP_STOP, _OP_PREFILL, _OP_DECODE = 0, 1, 2
_OP_KV_GATHER, _OP_KV_SCATTER = 3, 4
_OP_EMBED, _OP_LORA = 5, 6
_OP_KV_COPY = 7
_OP_VERIFY = 8  # speculative-decoding verify step ([B, 1+k] positions)
# Fused verify window: K verify iterations in one dispatch, accept/reject
# and token feedback ON DEVICE (header QK slot carries the window size).
_OP_VERIFY_WINDOW = 9
# Unified single-dispatch step: an entire window=1 engine step — chunked-
# prefill token runs, plain decode rows, and one-shot [B, 1+k] verify
# rows — packed into ONE ragged program (header QK slot packs
# (Q_bucket << 20) | T_bucket; the payload is a flat token stream plus
# per-row (start, qlen, kind) metadata).
_OP_UNIFIED = 10
# Genuinely ragged flattened-token step (`--ragged-qlens`): the unified
# step's forward runs over the PACKED [T_bucket] token stream itself
# (cu_q_lens row offsets; per-token causality = position + 1) instead of
# gathering into a padded [B, Q] view — a decode row costs ONE token, a
# verify row 1 + its own draft length. Header QK carries T_bucket
# directly (no Q packing: the flat family has no per-row column bucket).
_OP_FLAT = 11
# Lockstep liveness heartbeat: broadcast by an idle leader so followers'
# bounded header wait can distinguish "leader idle" from "leader dead".
# No device work — followers just absorb it and keep waiting.
_OP_HEARTBEAT = 12
# Symmetric int8-wire scatter (the q8 twin of _OP_KV_SCATTER): the leader
# broadcasts (i8 data + f16 K/V-half scales) instead of canonical staging
# bytes — HALF the DCN bytes per imported page, so multi-host streamed
# imports ride the same wire saving the q8 gather already gives exports.
# Int8 pools scatter the wire form directly; float pools dequantize on
# device.
_OP_KV_SCATTER_Q8 = 13

# Row kinds of the unified step's (start, qlen, kind) metadata. Only
# verify-ness reaches the device (it selects the sample positions: verify
# rows sample every position, the rest sample the last); the full kind is
# broadcast anyway so followers and debugging tools see the same step
# structure the leader staged.
_KIND_PREFILL, _KIND_DECODE, _KIND_VERIFY = 0, 1, 2

# Max tokens one unified row carries: prefill chunks longer than this are
# split into consecutive sub-rows of the SAME sequence (each layer writes
# the whole step's KV before attention reads, so a later sub-row attends
# the earlier sub-rows' fresh KV — the chunked-prefill invariant, just
# within one program). Bounds the [B, Q] padding a mixed step pays: a
# decode row pads to the Q bucket, so Q must stay small relative to the
# token stream, not grow to the largest chunk.
_UNIFIED_ROW_TOKENS = 64

log = logging.getLogger(__name__)


def _buckets(limit: int, start: int = 8) -> tuple[int, ...]:
    out, b = [], start
    while b < limit:
        out.append(b)
        b *= 2
    out.append(limit)
    return tuple(dict.fromkeys(out))


def pad_to_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _padded_ids(page_ids, pad_to: int) -> np.ndarray:
    """[n] i32 ids padded to ``pad_to`` by repeating the last id (a
    duplicate gather/scatter of the same page is idempotent)."""
    ids = np.asarray(page_ids, np.int32)
    if pad_to > len(ids):
        ids = np.concatenate([ids, np.full(pad_to - len(ids), ids[-1], np.int32)])
    return ids


@jax.jit
def _quantize_rows_q8(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 with SEPARATE scales for the K and V halves of each
    (token, head) row (the row packs K|V along the last 2D axis, and
    RoPE'd keys are routinely an order of magnitude larger than values —
    one shared amax would crush the value half to a few int8 levels).
    Returns (q [..., 2D] i8, scales [..., 2] f16). Module-level jit: one
    compile per shape, NOT per call."""
    *lead, D2 = x.shape
    xf = x.astype(jnp.float32).reshape(*lead, 2, D2 // 2)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    # Quantize against the f16-ROUNDED scale — the value the consumer
    # will actually dequantize with (avoids a systematic per-row bias of
    # up to ~2^-11 from the f32->f16 scale rounding).
    scale = scale.astype(jnp.float16).astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q.reshape(*lead, D2), scale[..., 0].astype(jnp.float16)


@functools.partial(jax.jit, static_argnames=("dtype_name",))
def _dequantize_rows_q8(
    q: jax.Array, s: jax.Array, dtype_name: str
) -> jax.Array:
    *lead, D2 = q.shape
    qf = q.astype(jnp.float32).reshape(*lead, 2, D2 // 2)
    out = qf * s.astype(jnp.float32)[..., None]
    return out.reshape(*lead, D2).astype(jnp.dtype(dtype_name))


def _fuse_projection_tree(params: dict) -> dict:
    """Pure tree transform behind ModelRunner._maybe_fuse (jitted there)."""

    def fuse(d: dict, names: list[str], out_name: str) -> None:
        if not all(n in d for n in names):
            return
        d[out_name] = jnp.concatenate([d[n] for n in names], axis=-1)
        if all(f"{n}_scale" in d for n in names):
            d[f"{out_name}_scale"] = jnp.concatenate(
                [d[f"{n}_scale"] for n in names], axis=-1
            )
        for n in names:
            d.pop(n, None)
            d.pop(f"{n}_scale", None)

    out = dict(params)
    for key in ("layers", "dense_layers"):
        if key not in out:
            continue
        d = dict(out[key])
        fuse(d, ["wq", "wk", "wv"], "wqkv")
        fuse(d, ["w_gate", "w_up"], "w_gu")
        out[key] = d
    return out


@dataclass
class StepResult:
    """Sampled tokens for each row; [B, K] (K=1 for single-shot calls).

    ``meta`` is set only by fused verify-window programs: per-row
    ``[emitted count, draft tokens scored, draft tokens accepted,
    iterations active]`` i32 — the device-resolved acceptance the host
    would otherwise have to recompute (and could not, mid-window)."""

    tokens: np.ndarray
    logprobs: np.ndarray
    meta: np.ndarray | None = None


@dataclass
class PendingPrefill:
    """Dispatched-but-unread prefill programs of one engine step: the
    packed [B, 2] device outputs per Q-bucket group plus each group's
    source row indices. ``wait_step`` folds every group into one
    coalesced host transfer."""

    entries: list[tuple[jax.Array, list[int]]]
    n: int


@dataclass
class PendingDecode:
    """Dispatched-but-unread decode-side programs of one engine step,
    awaiting the coalesced readback: (packed device output, source row
    indices, K, meta_cols) per program — the packed layout is
    [B, meta_cols + 2K], with meta_cols == 0 for plain decode/verify
    programs and 4 for fused verify windows (count/drafted/accepted/
    iters leading columns). Plain steps carry ONE entry; a speculative
    one-shot step may SPLIT its rows between the verify program (rows
    that drafted) and the plain one-token decode program (the rest), so
    low-repetition traffic pays verify columns only for rows that
    actually drafted."""

    entries: list[tuple[jax.Array, list[int], int, int]]
    n: int
    k: int  # widest K across entries == the StepResult window width


@dataclass
class StagedVerify:
    """Host arrays for a speculative verify dispatch built AHEAD of the
    tokens (and drafts) they depend on: page/ring tables and sampling
    knobs are final at staging time; tokens, positions, qlens, kvlens
    and seeds are filled by ``dispatch_staged_verify`` once the previous
    step's readback has committed and the drafts are proposed."""

    seqs: list[ScheduledSeq]
    arrays: dict
    B: int
    q: int  # 1 + spec_ngram_k (the verify shape family's static Q)
    all_greedy: bool


@dataclass
class StagedVerifyWindow:
    """Host arrays for a fused verify-window dispatch built AHEAD of the
    tokens/drafts they depend on (async stepping): page/ring tables,
    sampling knobs, the active mask and per-row emission limits are
    final at staging; ``first``/``start``, the pre-drafted token block,
    seeds, and the seeded-row derivation inputs are filled by
    ``dispatch_staged_verify_window`` once the previous step's readback
    has committed and the window's drafts are proposed."""

    seqs: list[ScheduledSeq]
    arrays: dict
    B: int
    window: int  # verify iterations fused into this dispatch
    q: int  # 1 + spec_ngram_k (columns per iteration)
    all_greedy: bool


@dataclass
class StagedDecode:
    """Host arrays for a decode dispatch built AHEAD of the tokens they
    feed on (async stepping): everything shape- and page-dependent is
    final at staging time; only ``first``/``start`` (and seeded rows'
    seeds) depend on the previous step's readback and are filled by
    ``dispatch_staged_decode`` right before dispatch."""

    seqs: list[ScheduledSeq]
    arrays: dict
    B: int
    k: int
    all_greedy: bool


@dataclass
class StagedUnified:
    """Host arrays for a unified single-dispatch step built AHEAD of the
    tokens/drafts they depend on (async prestaging): the ROW STRUCTURE
    (prefill chunks split into <= _UNIFIED_ROW_TOKENS sub-rows, one row
    per decode seq at its planned width) and everything row-dependent
    but token-independent — page/ring tables, sampling knobs, lora
    slots — are final at staging; the packed token stream, per-row
    (start, qlen, kind) metadata and seeds are filled by
    ``dispatch_staged_unified`` once the previous step's readback has
    committed and any drafts are proposed."""

    prefills: list[ScheduledSeq]
    decodes: list[ScheduledSeq]
    row_seqs: list[ScheduledSeq]  # one entry per unified row
    row_off: list[int]  # prefill sub-row token offset within its chunk
    row_plan: list[int]  # planned row width (actual qlen <= plan)
    prefill_rows: list[int]  # row index of each prefill seq's LAST sub-row
    decode_rows: list[int]  # row index of each decode seq
    arrays: dict
    B: int
    Q: int  # static per-row column count (bucketed max row width)
    T: int  # token-stream bucket (bucketed sum of planned widths)
    S: int  # sample columns per row (spec_q on speculative engines, 1)
    all_greedy: bool
    # Flattened-token staging (`--ragged-qlens`): dispatch rides the
    # _OP_FLAT program over the packed stream (B is the FIXED row-
    # metadata width, T a fine-grained flat bucket) instead of the
    # bucketed [B, Q] gather.
    flat: bool = False


@dataclass
class PendingUnified:
    """One dispatched-but-unread unified step: the packed [B, 2S] device
    output plus the row maps that split it back into prefill first-token
    results and decode/verify windows at ``wait_step``'s single
    coalesced readback."""

    packed: jax.Array
    S: int
    prefill_rows: list[int]
    decode_rows: list[int]
    n_prefills: int
    n_decodes: int


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        mesh_ctx: MeshContext,
        params: dict | None = None,
        swa_spec=None,
    ) -> None:
        self.config = config
        self.cfg = config.model
        self.ctx = mesh_ctx
        self.max_pages = config.cache.max_pages_per_seq(self.cfg.max_model_len)
        self.page = config.cache.page_size
        # SWA ring geometry (CacheConfig.swa_ring). The ENGINE passes its
        # resolved spec so allocator/scheduler and pool/table geometry
        # share one source of truth; a standalone runner resolves its own.
        self._swa_spec_arg = swa_spec

        if params is None:
            if config.weights_path:
                from llmd_tpu.models.loader import load_params

                params = load_params(self.cfg, config.weights_path)
            else:
                params = llama.init_params(self.cfg, jax.random.key(config.seed))
        params = self._maybe_fuse(params)
        self.params = shard_params(params, mesh_ctx)
        # Wide-EP MoE live state. ep_capacity is the LIVE capacity factor
        # (the adaptive controller may move it; every change rebuilds the
        # jitted programs so each compiled family sees exactly one static
        # capacity). The census buffer is the [E+2] accumulator
        # (moe_ep.CENSUS layout: per-expert routed tokens, dropped slots,
        # max dispatch demand) threaded through every forward and drained
        # by the engine's stats refresh — no extra per-step host
        # transfer beyond the read the stats path already does.
        pc = config.parallel
        self.ep_capacity = float(pc.ep_capacity_factor)
        self._ep_active = bool(self.cfg.is_moe) and pc.moe_backend == "ep"
        self.moe_overlap = int(pc.moe_overlap) if self._ep_active else 0
        self._moe_census = None
        if self._ep_active:
            from llmd_tpu.parallel.moe_ep import census_size

            self._moe_census = jax.device_put(
                np.zeros(census_size(self.cfg), np.float32),
                mesh_ctx.replicated,
            )
        # Pristine logical [L, E, ...] expert leaves, stashed on first
        # EPLB remap so later placements regather from the un-replicated
        # originals; the host-side Placement mirrors params["moe_placement"].
        self._logical_experts: dict | None = None
        self.moe_placement = None
        # SWA ring (CacheConfig.swa_ring): sliding-window layers live in a
        # second, smaller pool indexed through a ring-view page table.
        self.swa = self._swa_spec_arg or swa_ring_spec(
            self.cfg, config.cache, config.scheduler
        )
        self.kv_cache = self._alloc_kv()
        self.kv_swa = self._alloc_swa()
        self._multihost = dist.is_multihost()
        # Serializes lockstep broadcast+dispatch pairs so NON-engine
        # threads (P/D fetch staging, embeds, adapter installs) can
        # originate ops: followers mirror in receive order, so each
        # leader op must be broadcast AND dispatched atomically.
        self._dispatch_lock = threading.RLock()
        # Set by stop_followers: once _OP_STOP is broadcast the followers
        # are gone, and any later lockstep broadcast (e.g. from an
        # orphaned streamed-fetch thread) would block forever in a
        # collective nobody answers — refuse loudly instead.
        self._stopped = False  # llmd: guarded_by(_dispatch_lock)
        # Lockstep liveness: every collective leg runs under a bounded
        # wait (LLMD_LOCKSTEP_TIMEOUT_S; 0 disables) so a dead peer is a
        # loud RuntimeError within the budget instead of an infinite
        # hang, and an idle leader heartbeats (_OP_HEARTBEAT) so the
        # followers' bounded header wait can tell "idle leader" from
        # "dead leader".
        try:
            self.lockstep_timeout_s = float(
                os.environ.get("LLMD_LOCKSTEP_TIMEOUT_S", "300") or 0
            )
        except ValueError:
            self.lockstep_timeout_s = 300.0
        self._lockstep_pool = None
        self._last_broadcast = 0.0
        # The FIRST collective round carries cold-cache jit compiles and
        # weight-load skew across hosts (the deploy startupProbe budgets
        # hours for it) — bounding it would declare a healthy group dead
        # mid-startup. The wait arms after one successful collective,
        # mirroring the serving watchdog's first-step exemption.
        self._lockstep_warmed = False
        # Mid-serving compile grace: the first dispatch of each
        # (op, B, QK) shape family jit-compiles on every host, and
        # per-host persistent-cache skew (one host hits the cache,
        # another compiles for minutes) can legitimately exceed the
        # liveness budget long after startup. After a first-of-family
        # dispatch each side grants its NEXT bounded wait one unbounded
        # pass — the peer is compiling, not dead. Both sides see every
        # header, so the seen-sets stay in sync.
        self._lockstep_seen_shapes: set = set()
        self._lockstep_compile_grace = False
        self._hb_stop = threading.Event()
        if self._multihost and dist.is_leader() and self.lockstep_timeout_s:
            threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name="llmd-lockstep-hb",
            ).start()
        self._np_rng = np.random.default_rng(config.seed ^ 0x5EED)

        if config.parallel.enable_dbo and not ops._on_tpu():
            # Never a silent regression: see ParallelConfig.enable_dbo
            # for the full substrate condition.
            log.warning(
                "enable_dbo is ON without a TPU backend: profiled on the "
                "CPU mesh, the half-batch split MULTIPLIES all-to-all ops "
                "~3.8x (2.4x collective device-time) with nothing to hide "
                "them behind — steps run ~1.9x slower. EXPERIMENTAL: "
                "enable only on a real multi-chip slice and trust the "
                "bench delta (docs/architecture/dbo.md)"
            )
        if self.moe_overlap > 1 and not ops._on_tpu():
            # Same substrate condition as DBO: see ParallelConfig.
            # moe_overlap and the bench moe_ep part's on/off delta.
            log.warning(
                "moe_overlap=%d without a TPU backend: the microbatched "
                "EP dispatch only pays where the all-to-all runs "
                "asynchronously on a real ICI fabric; on the CPU mesh the "
                "extra collective launches are pure overhead. EXPERIMENTAL: "
                "graduate via the bench moe_ep part on a real slice "
                "(docs/architecture/wide-ep.md)", self.moe_overlap,
            )
        # Context-parallel ring prefill (ParallelConfig.cp_prefill): armed
        # only for non-MLA models on a mesh whose dp axis matches the cp
        # degree (the config validates cp == data_parallel_size). The
        # dedicated _forward_cp family serves chunk widths divisible by
        # cp and at least cp_prefill_min_tokens; everything else keeps
        # the monolithic program.
        self.cp_prefill = (
            int(pc.cp_prefill)
            if pc.cp_prefill > 1 and not self.cfg.is_mla
            else 0
        )
        self.cp_min_tokens = int(pc.cp_prefill_min_tokens)
        # Ring collective steps dispatched (cp per cp-prefill call);
        # drained into EngineStats.cp_ring_steps_total.
        self.cp_ring_steps_total = 0
        sched = config.scheduler
        self.batch_buckets = sched.decode_batch_buckets or _buckets(sched.max_num_seqs)
        self.prefill_batch_buckets = (
            sched.prefill_batch_buckets or _buckets(sched.max_num_seqs, start=1)
        )
        self.prefill_buckets = sched.prefill_token_buckets or _buckets(
            sched.max_num_batched_tokens, start=16
        )
        self._build_programs()
        # Padding-efficiency accounting (EngineStats padded/live tokens):
        # every dispatch path adds its live token count and the padded
        # compute width the traced shape actually paid for.
        self.live_tokens_total = 0
        self.padded_tokens_total = 0

    def _build_programs(self) -> None:
        """(Re)build every jitted forward program. Called at init and
        whenever a trace-time MoE static changes — an adaptive
        ep_capacity step or an EPLB remap (the we_* leaves change shape)
        — so no compiled family ever runs a stale capacity/placement."""
        sched = self.config.scheduler
        self._forward = self._build_forward()
        self._forward_cp = (
            self._build_forward(cp=self.cp_prefill) if self.cp_prefill else None
        )
        self._multi = self._build_multi()
        # Speculative decoding (SchedulerConfig.speculative_ngram): the
        # verify step scores [B, 1 + spec_ngram_k] positions per decode
        # row in one forward — its own traced shape family (Q static per
        # engine, B over the decode batch buckets).
        self.spec_q = (
            1 + sched.spec_ngram_k if sched.speculative_ngram else 0
        )
        self._verify = self._build_verify() if self.spec_q else None
        # Fused verify window (spec x decode_window composition): the
        # window sizes the scheduler may pick (SchedulerConfig.
        # spec_window_set); one traced family, window a static argument.
        self.spec_windows = sched.spec_window_set
        self._verify_window = (
            self._build_verify_window() if self.spec_windows else None
        )
        # Decode depths warmup precompiles — and the ONLY depths the
        # engine's no-draft degrade path may dispatch at (an unwarmed K
        # would block serving on a fresh XLA compile mid-step). Includes
        # every fused-verify-window candidate: a degraded window step
        # runs the plain decode program at the window's depth. On
        # speculative engines the scheduler never takes the PLAIN fused-
        # window branch, so decode_window itself is reachable only
        # through the resolved spec window — warming it directly would
        # be dead compile time when --spec-verify-window decouples them.
        self.decode_windows = tuple(sorted({
            1,
            sched.spec_window if sched.speculative_ngram
            else sched.decode_window,
            *self.spec_windows,
        }))
        # Unified single-dispatch step (SchedulerConfig.unified_step): one
        # ragged program packs a whole window=1 step — prefill chunk
        # runs, plain decode rows, one-shot verify rows. Sample columns
        # per row: verify rows need spec_q, everything else 1.
        self.unified_s = max(self.spec_q, 1)
        # Per-row width cap (long chunks split into sub-rows); must cover
        # the verify family's 1 + k columns.
        self.unified_row_cap = max(_UNIFIED_ROW_TOKENS, self.unified_s)
        self.unified_q_buckets = _buckets(self.unified_row_cap, start=8)
        # Row-count bound: every scheduled seq is one row, plus at most
        # budget // cap extra sub-rows from chunk splitting.
        self.unified_row_buckets = _buckets(
            sched.max_num_seqs
            + sched.max_num_batched_tokens // self.unified_row_cap,
            start=1,
        )
        self._unified = (
            self._build_unified() if sched.unified_step else None
        )
        # Genuinely ragged flattened-token step (SchedulerConfig.
        # ragged_qlens): the unified step's forward runs over the packed
        # [T] token stream with cu_q_lens row offsets — no [B, Q]
        # padding. ONE T-bucket dimension (16-token granules, so padding
        # waste is bounded by 15 tokens/step) replaces the bucketed
        # unified family's (rows x Q x T) cross-product; the row-
        # metadata width is FIXED at the largest row bucket (metadata is
        # O(rows), not O(tokens) — a few KB). MLA keeps the bucketed
        # layout (latent writes have their own addressing).
        self._flat = None
        self.flat_rows = 0
        self.flat_t_buckets: tuple[int, ...] = ()
        if sched.unified_step and sched.ragged_qlens and not self.cfg.is_mla:
            limit = sched.max_num_batched_tokens + max(self.unified_s, 1)
            limit = -(-limit // 16) * 16
            self.flat_t_buckets = tuple(range(16, limit + 1, 16))
            self.flat_rows = self.unified_row_buckets[-1]
            self._flat = self._build_flat()

    # ------------------------------------------------------------------ #
    # Wide-EP MoE control plane (census drain, adaptive capacity, EPLB)

    # Expert param leaves remapped by an EPLB placement (present subset
    # only: bf16 weights, int8 channel scales, gpt-oss biases).
    _EXPERT_LEAVES = (
        "we_gate", "we_up", "we_down",
        "we_gate_scale", "we_up_scale", "we_down_scale",
        "we_gate_b", "we_up_b", "we_down_b",
    )

    def drain_moe_census(self) -> np.ndarray | None:
        """Read-and-reset the MoE census accumulator ([E+2] f32: routed
        tokens per logical expert, dropped slots, max dispatch demand as
        a capacity-factor multiple). Called by the engine's stats refresh
        once per step — the read rides the sync the stats path already
        does."""
        if self._moe_census is None:
            return None
        from llmd_tpu.parallel.distributed import replicated_to_host

        out = np.asarray(replicated_to_host(self._moe_census))
        self._moe_census = jax.device_put(
            np.zeros_like(out), self.ctx.replicated
        )
        return out

    def set_ep_capacity(self, factor: float) -> None:
        """Move the live EP capacity factor (adaptive controller step).
        Rebuilds the jitted programs: capacity is a trace-time static, so
        every compiled family must re-trace at the new value."""
        if float(factor) == self.ep_capacity:
            return
        self.ep_capacity = float(factor)
        self._build_programs()

    def apply_expert_placement(self, placement) -> None:
        """Install an EPLB placement (parallel.eplb.Placement) at a step
        boundary: regather the ``we_*`` leaves from the pristine logical
        layout into the physical one ([L, E_phys, ...], hot experts
        replicated), publish the routing tables into
        ``params["moe_placement"]`` (the router maps logical ids through
        them inside moe_block_ep), and rebuild the programs — the leaf
        shapes changed, so every family re-traces exactly once per
        placement epoch."""
        if not self._ep_active:
            raise RuntimeError("EPLB requires moe_backend='ep'")
        self._require_single_host("apply_expert_placement (EPLB)")
        from llmd_tpu.parallel.mesh import param_specs

        layers = dict(self.params["layers"])
        names = [k for k in self._EXPERT_LEAVES if k in layers]
        if self._logical_experts is None:
            self._logical_experts = {k: layers[k] for k in names}
        idx = jnp.asarray(placement.phys_to_logical, jnp.int32)
        specs = param_specs({k: self._logical_experts[k] for k in names})
        with self._dispatch_lock:
            for k in names:
                # llmd: allow(trace-discipline) -- control-plane only: runs once per EPLB placement epoch (eplb_interval_steps), never on the step path; out_shardings is per-leaf so the gather lands sharded without a host roundtrip
                gather = jax.jit(
                    lambda w, i: jnp.take(w, i, axis=1),
                    out_shardings=self.ctx.sharding(*specs[k]),
                )
                layers[k] = gather(self._logical_experts[k], idx)
            tables = {
                "phys_to_logical": placement.phys_to_logical,
                "replicas": placement.replicas,
                "n_replicas": placement.n_replicas,
            }
            self.params = {
                **self.params,
                "layers": layers,
                "moe_placement": {
                    k: jax.device_put(
                        np.asarray(v, np.int32), self.ctx.replicated
                    )
                    for k, v in tables.items()
                },
            }
            self.moe_placement = placement
            self._build_programs()

    # ------------------------------------------------------------------ #

    def _maybe_fuse(self, params: dict) -> dict:
        """Fuse q|k|v and gate|up projections into single matmuls (one
        activation quantization + one bigger MXU dot instead of three).

        Lossless by construction: per-output-channel int8 scales (and
        bf16 weights) concatenate exactly, so the fused dot equals the
        separate dots bit-for-bit. Only when the layout allows: tp == 1
        (the fused output axis cannot ride the per-projection TP shard),
        no LoRA (adapters add to q/v, fine — but kept simple), non-MLA.

        Runs as ONE jitted call with the unfused tree donated — eager
        per-tensor concats would transiently double the projection
        weights on device and fragment the arena (the same init-OOM
        pattern the jitted quantize call avoids, models/llama.py).
        """
        cfg = self.cfg
        if (
            not self.config.parallel.fuse_projections
            or self.ctx.tp > 1
            or cfg.is_mla
            or cfg.num_lora_adapters
        ):
            return params
        # Jitted only so the donated tree fuses in-place instead of
        # transiently doubling HBM (see docstring).
        # llmd: allow(trace-discipline) -- one-shot at __init__ weight load, never on the step path
        return jax.jit(_fuse_projection_tree, donate_argnums=0)(
            jax.tree.map(jnp.asarray, params)
        )

    @functools.cached_property
    def kv_rep(self) -> int:
        """KV-head replication factor for the pool's head axis.

        When tp exceeds (but is a multiple of) the KV head count, each kv
        head is stored tp/K times consecutively so the head axis shards
        over tp: per-chip KV becomes pool/K instead of the full replicated
        pool the plain spec degrades to (the reference's FlashInfer-under-
        TP layouts make the same trade). GQA stays exact — q head h reads
        expanded head h // (Nq / (K*rep)), which holds h's kv head."""
        K, tp, Nq = self.cfg.kv_cache_heads, self.ctx.tp, self.cfg.num_heads
        if (
            not self.cfg.is_mla
            and tp > 1
            and K % tp != 0
            and tp % K == 0
            and Nq % tp == 0
        ):
            return tp // K
        return 1

    def _alloc_kv(self):
        c = self.config.cache
        layers = (
            len(self.swa.full_layers) if self.swa is not None
            else self.cfg.num_layers
        )
        return self._alloc_pool(layers, c.num_blocks)

    def _alloc_swa(self):
        """The sliding-window ring pool (None unless swa_ring resolves)."""
        if self.swa is None:
            return None
        return self._alloc_pool(len(self.swa.swa_layers), self.swa.num_swa_blocks)

    def _alloc_pool(self, num_layers: int, num_blocks: int):
        c = self.config.cache
        shape = (
            num_layers,
            num_blocks,
            self.cfg.kv_cache_heads * self.kv_rep,  # MLA: one latent "head"
            c.page_size,
            self.cfg.kv_cache_entry_dim,
        )
        if c.quantized and self.cfg.is_mla:
            # Latent rows ([rank | rope] padded to lanes) need their own
            # scale layout; the K|V midpoint split is wrong for them —
            # refuse rather than silently degrade accuracy (same policy
            # as the int8 transfer encoding).
            raise ValueError(
                "kv cache dtype 'int8' is not supported for MLA models"
            )
        if self.cfg.is_mla:
            # The latent pool replicates across tp BY DESIGN: rows are a
            # few hundred bytes and every head reads the same latent —
            # not the GQA mis-configuration kv_cache_spec warns about.
            spec = jax.sharding.PartitionSpec()
        else:
            spec = kv_cache_spec(shape[2], self.ctx.tp)
        sharding = self.ctx.sharding(*spec)
        if c.quantized:
            # Int8 pool: (data i8, per-row K/V-half scales f32 in the
            # pool layout [L, P, K, page, 2]) — see ops/quant_kv.py for
            # the layout contract. Scales share the data pool's head
            # sharding (same axis position).
            sshape = (shape[0], shape[1], shape[2], shape[3], 2)
            if dist.is_multihost():
                return jax.jit(
                    lambda: (
                        jnp.zeros(shape, jnp.int8),
                        jnp.ones(sshape, jnp.float32),
                    ),
                    out_shardings=(sharding, sharding),
                )()
            return (
                jnp.zeros(shape, jnp.int8, device=sharding),
                jnp.ones(sshape, jnp.float32, device=sharding),
            )
        if dist.is_multihost():
            # Global pool spanning processes: allocate via a jitted zeros
            # so no host ever materializes (or addresses) the full array.
            dt = jnp.dtype(c.dtype)
            return jax.jit(
                lambda: jnp.zeros(shape, dt), out_shardings=sharding
            )()
        return jnp.zeros(shape, jnp.dtype(c.dtype), device=sharding)

    @property
    def kv_quantized(self) -> bool:
        return isinstance(self.kv_cache, tuple)

    @property
    def _kv_data(self) -> jax.Array:
        return self.kv_cache[0] if self.kv_quantized else self.kv_cache

    @property
    def staging_dtype(self) -> np.dtype:
        """Canonical dtype of dequantized staging bundles (transfer wire
        'exact' form, offload host pages): the model compute dtype for
        int8 pools, the pool dtype otherwise."""
        if self.kv_quantized:
            return np.dtype(jnp.dtype(self.cfg.dtype))
        return np.dtype(self.kv_cache.dtype)

    @property
    def staging_dtype_name(self) -> str:
        return self.staging_dtype.name

    def kv_bytes(self) -> int:
        return sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves((self.kv_cache, self.kv_swa))
        )

    def set_lora_weights(self, lora_id: int, weights: dict) -> None:
        """Install adapter weights into slot ``lora_id`` (1-based).

        ``weights`` maps la_q/lb_q/la_v/lb_v to stacked
        ``[num_layers, ...]`` arrays matching the slot's shape; A and B
        must be installed together per projection (setting only B would
        silently compose with whatever A the slot holds — zeros on
        checkpoint-loaded models, i.e. an identity adapter). Slots
        initialize with B == 0 (adapter == base model), so serving an
        adapter name before its weights load is safe; this is the hook
        checkpoint loading and dynamic adapter registration use.
        """
        if not (0 < lora_id <= self.cfg.num_lora_adapters):
            raise ValueError(f"lora_id {lora_id} out of range")
        for a, b in (("la_q", "lb_q"), ("la_v", "lb_v")):
            if (a in weights) != (b in weights):
                raise ValueError(
                    f"LoRA install must pair {a} with {b}: partial updates "
                    "compose with stale/zero factors and silently serve the "
                    "wrong adapter"
                )
        for k in weights:
            if k not in ("la_q", "lb_q", "la_v", "lb_v"):
                raise KeyError(f"unknown LoRA tensor {k!r}")
        # Multi-host: the per-slot update is a plain SPMD program over
        # the sharded params — broadcast the factors (header: B carries
        # a pair-presence bitmask, QK the slot id) and apply everywhere.
        mask = (1 if "la_q" in weights else 0) | (2 if "la_v" in weights else 0)
        layers = self.params["layers"]
        arrays = {
            # Normalized to the slot's (L, *factor) shape so the payload
            # spec is derivable from the shared params structure.
            k: np.ascontiguousarray(np.asarray(v, np.float32)).reshape(
                layers[k].shape[0], *layers[k].shape[2:]
            )
            for k, v in weights.items()
        }
        with self._dispatch_lock:
            arrays = self._sync_locked(_OP_LORA, mask, lora_id, False, arrays)
            self._exec_lora(arrays, lora_id)

    def _exec_lora(self, arrays: dict, lora_id: int) -> None:
        layers = dict(self.params["layers"])
        for k, v in arrays.items():
            arr = layers[k]
            layers[k] = arr.at[:, lora_id].set(
                jnp.asarray(v, arr.dtype).reshape(arr.shape[0], *arr.shape[2:])
            )
        self.params = {**self.params, "layers": layers}

    def _replicate_out(self, packed: jax.Array) -> jax.Array:
        """Multi-host: pin the packed host transfer to full replication so
        every process can read it locally (single-host: no-op)."""
        if not dist.is_multihost():
            return packed
        return jax.lax.with_sharding_constraint(packed, self.ctx.replicated)

    def _fwd_hidden(self, params, kv_cache, kv_swa, inp, census, dbo=False,
                    cp=0):
        """llama.forward_hidden under this runner's trace-time MoE/EP
        statics (live ep_capacity, moe_overlap, the EPLB placement riding
        in ``params["moe_placement"]``), threading the census accumulator
        when armed. Returns (hidden, kv_cache, kv_swa, census) uniformly
        so every builder shares one call shape. Builders are recreated by
        _build_programs whenever a static here changes, so each compiled
        family sees exactly one value."""
        cfg = self.cfg
        moe_backend = (
            self.config.parallel.moe_backend if cfg.is_moe else "dense"
        )
        kw = {}
        ring = self.swa is not None
        if ring:
            kw["kv_swa"] = kv_swa
        if census is not None:
            kw["moe_census"] = census
        out = llama.forward_hidden(
            params, kv_cache, inp, cfg, self.ctx.world,
            mesh=self.ctx.mesh, moe_backend=moe_backend,
            ep_capacity_factor=self.ep_capacity, kv_rep=self.kv_rep,
            dbo=dbo, moe_overlap=self.moe_overlap,
            moe_placement=params.get("moe_placement"),
            cp_prefill=cp,
            **kw,
        )
        if census is not None:
            census = out[-1]
            out = out[:-1]
        hidden, kv_cache = out[0], out[1]
        if ring:
            kv_swa = out[2]
        return hidden, kv_cache, kv_swa, census

    def _build_forward(self, cp: int = 0):
        """The prefill/one-shot-step program. ``cp`` > 1 builds the
        context-parallel ring variant (ops/ring_attention.py): same call
        shape, attention sharded over the mesh dp axis — a separate
        compiled family the dispatcher selects by chunk width."""
        cfg = self.cfg
        dbo = self.config.parallel.enable_dbo
        replicate = self._replicate_out
        ring = self.swa is not None

        @functools.partial(
            jax.jit,
            donate_argnums=(1, 2) if ring else (1,),
            static_argnames=("all_greedy",),
        )
        def fwd(params, kv_cache, kv_swa, inp: StepInput, s: SamplingInputs,
                census=None, all_greedy=False):
            hidden, kv_cache, kv_swa, census = self._fwd_hidden(
                params, kv_cache, kv_swa, inp, census, dbo=dbo, cp=cp
            )
            B = hidden.shape[0]
            last = jnp.maximum(inp.query_lens - 1, 0)
            h_last = hidden[jnp.arange(B), last]
            logits = llama.compute_logits(params, h_last, cfg)
            tokens, logprobs = sample_tokens(logits, s, all_greedy)
            # Pack into one array => one host transfer for the whole step.
            packed = jnp.concatenate(
                [tokens.astype(jnp.float32)[:, None], logprobs[:, None]], axis=1
            )
            return kv_cache, kv_swa, replicate(packed), census

        return fwd

    def _build_verify(self):
        """Speculative verify: the prefill forward over [B, 1+k] rows
        (chunked-prefill/ragged-paged-attention path — no new kernel,
        just a new traced shape family), sampling at EVERY position
        instead of only the last. Row i feeds [last committed token,
        draft_0..draft_{m-1}] with per-row draft-length masks
        (query_lens); position j's sample is the target token for output
        index j, computed under the draft's context. KV for all 1+k
        positions is written provisionally — the scheduler truncates
        past the accepted prefix before any page commit."""
        cfg = self.cfg
        dbo = self.config.parallel.enable_dbo
        replicate = self._replicate_out
        ring = self.swa is not None

        @functools.partial(
            jax.jit,
            donate_argnums=(1, 2) if ring else (1,),
            static_argnames=("all_greedy",),
        )
        def verify(params, kv_cache, kv_swa, inp: StepInput, s: SamplingInputs,
                   census=None, all_greedy=False):
            hidden, kv_cache, kv_swa, census = self._fwd_hidden(
                params, kv_cache, kv_swa, inp, census, dbo=dbo
            )
            B, Q, H = hidden.shape
            logits = llama.compute_logits(params, hidden.reshape(B * Q, H), cfg)
            flat = SamplingInputs(
                temperature=jnp.repeat(s.temperature, Q),
                top_k=jnp.repeat(s.top_k, Q),
                top_p=jnp.repeat(s.top_p, Q),
                seeds=s.seeds.reshape(B * Q),
            )
            tokens, logprobs = sample_tokens(logits, flat, all_greedy)
            # Same packed [B, 2Q] layout as the fused decode window, so
            # wait_step's coalesced readback handles both identically.
            packed = jnp.concatenate(
                [
                    tokens.reshape(B, Q).astype(jnp.float32),
                    logprobs.reshape(B, Q),
                ],
                axis=1,
            )
            return kv_cache, kv_swa, replicate(packed), census

        return verify

    def _build_unified(self):
        """Unified single-dispatch step: ONE ragged program for an entire
        window=1 engine step. The host ships a packed token stream
        ``[T]`` plus per-row (start, qlen, kind) metadata; the device
        gathers it into the bucketed ``[B, Q]`` view and runs the SAME
        prefill/ragged-paged-attention forward every other shape family
        uses — chunked-prefill rows, plain decode rows (qlen 1), and
        one-shot verify rows (qlen 1 + draft) side by side, masked by
        ``query_lens`` exactly like the verify family's padding. Long
        prefill chunks arrive pre-split into consecutive sub-rows of the
        same sequence (each layer writes the whole step's KV before
        attention reads, so later sub-rows attend earlier sub-rows'
        fresh KV — the cross-step chunked-prefill invariant, inside one
        program). Sampling gathers an ``[B, S]`` plane of positions
        (verify rows: every draft position; all other rows: the last
        valid position) so prefill-chunk first-tokens and decode/verify
        tokens sample ON DEVICE in the same call, and the whole step
        comes back as one packed ``[B, 2S]`` transfer — one dispatch,
        one coalesced readback, where the split engine pays one per
        program."""
        cfg = self.cfg
        dbo = self.config.parallel.enable_dbo
        replicate = self._replicate_out
        ring = self.swa is not None
        S = self.unified_s

        @functools.partial(
            jax.jit,
            donate_argnums=(1, 2) if ring else (1,),
            static_argnames=("Q", "all_greedy"),
        )
        def unified(
            params,
            kv_cache,
            kv_swa,  # ring pool (None unless swa_ring)
            stream: jax.Array,  # [T] packed token stream
            row_start: jax.Array,  # [B] row's offset into the stream
            pos0: jax.Array,  # [B] absolute position of the row's first token
            qlens: jax.Array,  # [B] valid token count per row
            kvlens: jax.Array,  # [B] kv length after this row's writes
            verify_row: jax.Array,  # [B] bool (kind == verify)
            page_table: jax.Array,  # [B, max_pages]
            swa_table,  # [B, max_pages] ring view, or None
            lora_ids,  # [B] i32 adapter slots, or None
            temperature: jax.Array,
            top_k: jax.Array,
            top_p: jax.Array,
            seeds: jax.Array,  # [B, S]
            census=None,  # [E+2] MoE census accumulator, or None
            Q: int = 0,
            all_greedy: bool = False,
        ):
            B = row_start.shape[0]
            cols = jnp.arange(Q)
            gidx = jnp.clip(
                row_start[:, None] + cols[None, :], 0, stream.shape[0] - 1
            )
            tokens = jnp.where(
                cols[None, :] < qlens[:, None], stream[gidx], 0
            )
            last = jnp.maximum(qlens - 1, 0)
            # Pad columns repeat the last real position (the prefill
            # convention every family shares).
            positions = pos0[:, None] + jnp.minimum(
                cols[None, :], last[:, None]
            )
            inp = StepInput(
                token_ids=tokens,
                positions=positions,
                query_lens=qlens.astype(jnp.int32),
                kv_lens=kvlens.astype(jnp.int32),
                page_table=page_table,
                lora_ids=lora_ids,
                swa_page_table=swa_table,
            )
            hidden, kv_cache, kv_swa, census = self._fwd_hidden(
                params, kv_cache, kv_swa, inp, census, dbo=dbo
            )
            H = hidden.shape[-1]
            scols = jnp.arange(S)
            # Verify rows sample every scored position (the one-shot
            # verify layout); everything else samples its last valid
            # position in column 0 (duplicate pad samples are dropped
            # host-side).
            samp = jnp.where(
                verify_row[:, None],
                jnp.minimum(scols[None, :], last[:, None]),
                last[:, None],
            )
            h = hidden[jnp.arange(B)[:, None], samp]  # [B, S, H]
            logits = llama.compute_logits(params, h.reshape(B * S, H), cfg)
            flat = SamplingInputs(
                temperature=jnp.repeat(temperature, S),
                top_k=jnp.repeat(top_k, S),
                top_p=jnp.repeat(top_p, S),
                seeds=seeds.reshape(B * S),
            )
            tok, logp = sample_tokens(logits, flat, all_greedy)
            packed = jnp.concatenate(
                [
                    tok.reshape(B, S).astype(jnp.float32),
                    logp.reshape(B, S),
                ],
                axis=1,
            )  # [B, 2S]
            return kv_cache, kv_swa, replicate(packed), census

        return unified

    def _build_flat(self):
        """Genuinely ragged flattened-token step (`cu_q_lens`): the SAME
        engine step the bucketed unified program runs, but the forward
        iterates the packed ``[T]`` token stream itself. The device
        derives the per-token view from the per-row metadata — token t
        belongs to the row whose ``[row_start, row_start + qlen)`` span
        holds it (``searchsorted`` over the cu_q_lens ends; pad rows
        carry ``row_start = total`` so the boundary array stays
        monotonic), its position is ``pos0[row] + (t - row_start[row])``
        and its causal horizon is ``position + 1`` — so a decode row
        costs ONE token of the stream, a verify row ``1 + its own draft
        length`` (per-row adaptive verify depth: hot-draft rows run deep
        windows while backed-off rows run depth 1 in the same program),
        and nothing pads to a per-row column bucket. KV lands through
        the run-addressed flat write plan (same-page-safe Pallas writes
        on TPU); sampling gathers each row's positions out of the packed
        hidden stream and the step still comes back as ONE ``[B, 2S]``
        transfer."""
        cfg = self.cfg
        replicate = self._replicate_out
        ring = self.swa is not None
        S = self.unified_s

        @functools.partial(
            jax.jit,
            donate_argnums=(1, 2) if ring else (1,),
            static_argnames=("all_greedy",),
        )
        def flat(
            params,
            kv_cache,
            kv_swa,  # ring pool (None unless swa_ring)
            stream: jax.Array,  # [T] packed token stream
            row_start: jax.Array,  # [B] cu_q_lens offsets (pad rows: total)
            pos0: jax.Array,  # [B] absolute position of the row's first token
            qlens: jax.Array,  # [B] valid token count per row
            verify_row: jax.Array,  # [B] bool (kind == verify)
            page_table: jax.Array,  # [B, max_pages] COMPACT per-row table
            swa_table,  # [B, max_pages] ring view, or None
            lora_ids,  # [B] i32 adapter slots, or None
            temperature: jax.Array,
            top_k: jax.Array,
            top_p: jax.Array,
            seeds: jax.Array,  # [B, S]
            wsrc: jax.Array,  # [R] flat-write run slab starts
            woff: jax.Array,  # [R] first in-page slot per run
            wcnt: jax.Array,  # [R] token count per run (0 = pad)
            wphys: jax.Array,  # [R] physical page per run (main pool)
            wphys_swa,  # [R] physical page per run (ring pool), or None
            census=None,  # [E+2] MoE census accumulator, or None
            all_greedy: bool = False,
        ):
            T = stream.shape[0]
            B = row_start.shape[0]
            t = jnp.arange(T)
            ends = row_start + qlens  # non-decreasing (pad rows = total)
            row_of = jnp.clip(
                jnp.searchsorted(ends, t, side="right"), 0, B - 1
            ).astype(jnp.int32)
            live = t < ends[-1]
            local = t - row_start[row_of]
            positions_t = jnp.where(live, pos0[row_of] + local, 0)
            inp = StepInput(
                token_ids=jnp.where(live, stream, 0)[:, None],
                positions=positions_t[:, None],
                query_lens=live.astype(jnp.int32),
                # Per-token causal horizon derived from the packing:
                # position + 1 — the whole causal mask the bucketed
                # layout needed [B, Q] positions for.
                kv_lens=jnp.where(live, positions_t + 1, 0).astype(jnp.int32),
                page_table=page_table,
                lora_ids=(
                    lora_ids[row_of] if lora_ids is not None else None
                ),
                swa_page_table=swa_table,
                token_rows=row_of,
                flat_runs=((wsrc, woff, wcnt), wphys, wphys_swa),
            )
            hidden, kv_cache, kv_swa, census = self._fwd_hidden(
                params, kv_cache, kv_swa, inp, census
            )
            H = hidden.shape[-1]
            scols = jnp.arange(S)
            last = jnp.maximum(qlens - 1, 0)
            samp_local = jnp.where(
                verify_row[:, None],
                jnp.minimum(scols[None, :], last[:, None]),
                last[:, None],
            )  # [B, S] offsets within each row
            flat_idx = jnp.clip(row_start[:, None] + samp_local, 0, T - 1)
            h = hidden[flat_idx, 0]  # [B, S, H]
            logits = llama.compute_logits(params, h.reshape(B * S, H), cfg)
            flat_s = SamplingInputs(
                temperature=jnp.repeat(temperature, S),
                top_k=jnp.repeat(top_k, S),
                top_p=jnp.repeat(top_p, S),
                seeds=seeds.reshape(B * S),
            )
            tok, logp = sample_tokens(logits, flat_s, all_greedy)
            packed = jnp.concatenate(
                [
                    tok.reshape(B, S).astype(jnp.float32),
                    logp.reshape(B, S),
                ],
                axis=1,
            )  # [B, 2S]
            return kv_cache, kv_swa, replicate(packed), census

        return flat

    def _build_verify_window(self):
        """Fused verify window: ``window`` verify iterations in ONE jit
        call — a ``lax.fori_loop`` whose body runs the [B, 1+k] verify
        forward, applies the acceptance rule ON DEVICE
        (``sampler.accept_counts`` — the same rule the host one-shot
        path uses — with the per-(seed, output-index) PRNG derivation
        for seeded rows, ``sampler.spec_seed``), advances each row's
        position by its accepted length, and feeds the device-side next
        token back for the following iteration. The host pre-drafts up
        to window x (1+k) - 1 tokens per row (``predraft``/
        ``draft_len`` — each fully-accepted iteration consumes k scored
        columns plus the bonus slot); a
        row whose draft diverges (mismatch among scored columns) or
        exhausts degrades to plain one-token decode iterations inside
        the same loop via the query-length mask, and a row that reaches
        its ``limit`` (planned emission cap: budget/pages/max_model_len)
        goes fully inactive (qlen 0, the prefill pad-row convention).
        One packed output per window: 4 meta columns (emitted/drafted/
        accepted/iters-active) + window x (1+k) token and logprob
        columns — ONE host round-trip per K verify iterations."""
        cfg = self.cfg
        dbo = self.config.parallel.enable_dbo
        replicate = self._replicate_out
        ring = self.swa is not None
        Q = self.spec_q
        k = Q - 1

        @functools.partial(
            jax.jit,
            donate_argnums=(1, 2) if ring else (1,),
            static_argnames=("window", "all_greedy"),
        )
        def verify_window(
            params,
            kv_cache,
            kv_swa,  # ring pool (None unless swa_ring)
            first_token: jax.Array,  # [B] next input token
            start_pos: jax.Array,  # [B] position of first_token
            predraft: jax.Array,  # [B, window*k] pre-drafted tokens
            draft_len: jax.Array,  # [B] valid predraft width
            limit: jax.Array,  # [B] max emissions this window
            page_table: jax.Array,  # [B, max_pages]
            swa_table,  # [B, max_pages] ring view, or None
            active: jax.Array,  # [B] bool (pad rows False)
            lora_ids,  # [B] i32 adapter slots, or None
            temperature: jax.Array,
            top_k: jax.Array,
            top_p: jax.Array,
            seeds: jax.Array,  # [B, window, Q] engine-RNG draws
            seed_base: jax.Array,  # [B] u32 request seed (seeded rows)
            seeded: jax.Array,  # [B] bool
            out0: jax.Array,  # [B] output index of the first emission
            census=None,  # [E+2] MoE census accumulator, or None
            window: int = 1,
            all_greedy: bool = False,
        ):
            B = first_token.shape[0]
            Wmax = window * Q
            qcols = jnp.arange(Q)
            dcols = jnp.arange(k)

            def body(t, carry):
                (kv_cache, kv_swa, census, tok, pos, emitted, dptr, alive,
                 drafted, accepted, iters, out_t, out_l) = carry
                rem = limit - emitted
                row_on = active & (rem > 0)
                avail = jnp.where(
                    alive & row_on, jnp.clip(draft_len - dptr, 0, k), 0
                )
                qlen = jnp.minimum(1 + avail, jnp.maximum(rem, 1))
                qlen = jnp.where(row_on, qlen, 0)
                dlen = jnp.maximum(qlen - 1, 0)  # draft columns scored
                # Each row reads its next k pre-drafted tokens at its
                # OWN pointer; columns past dlen are zeroed like the
                # one-shot verify's padding.
                gcols = jnp.clip(
                    dptr[:, None] + dcols[None, :], 0, predraft.shape[1] - 1
                )
                draft = jnp.take_along_axis(predraft, gcols, axis=1)
                tokens = jnp.concatenate([tok[:, None], draft], axis=1)
                tokens = jnp.where(qcols[None, :] < qlen[:, None], tokens, 0)
                positions = pos[:, None] + qcols[None, :]
                last_real = pos + jnp.maximum(qlen - 1, 0)
                positions = jnp.where(
                    qcols[None, :] < qlen[:, None],
                    positions,
                    last_real[:, None],
                )
                inp = StepInput(
                    token_ids=tokens,
                    positions=positions,
                    query_lens=qlen.astype(jnp.int32),
                    kv_lens=jnp.where(row_on, pos + qlen, 0).astype(jnp.int32),
                    page_table=page_table,
                    lora_ids=lora_ids,
                    swa_page_table=swa_table,
                )
                hidden, kv_cache, kv_swa, census = self._fwd_hidden(
                    params, kv_cache, kv_swa, inp, census, dbo=dbo
                )
                H = hidden.shape[-1]
                logits = llama.compute_logits(
                    params, hidden.reshape(B * Q, H), cfg
                )
                s_t = jax.lax.dynamic_index_in_dim(
                    seeds, t, axis=1, keepdims=False
                )  # [B, Q]
                out_idx = (out0 + emitted)[:, None] + qcols[None, :]
                derived = spec_seed(
                    seed_base[:, None], out_idx.astype(jnp.uint32)
                )
                s_t = jnp.where(seeded[:, None], derived, s_t)
                flat = SamplingInputs(
                    temperature=jnp.repeat(temperature, Q),
                    top_k=jnp.repeat(top_k, Q),
                    top_p=jnp.repeat(top_p, Q),
                    seeds=s_t.reshape(B * Q),
                )
                tgt, logp = sample_tokens(logits, flat, all_greedy)
                tgt = tgt.reshape(B, Q)
                logp = logp.reshape(B, Q)
                n_emit, n_acc = accept_counts(draft, tgt, dlen)
                n_emit = jnp.where(row_on, jnp.minimum(n_emit, qlen), 0)
                # Scatter the emitted prefix at each row's output
                # offset; rejected/pad columns route out of range and
                # drop.
                col = emitted[:, None] + qcols[None, :]
                col = jnp.where(qcols[None, :] < n_emit[:, None], col, Wmax)
                rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, Q))
                out_t = out_t.at[rows, col].set(tgt, mode="drop")
                out_l = out_l.at[rows, col].set(logp, mode="drop")
                last = jnp.take_along_axis(
                    tgt, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
                )[:, 0]
                tok = jnp.where(row_on, last, tok)
                pos = pos + n_emit
                # The iteration consumed n_emit slots of the prediction
                # stream: n_acc scored draft columns PLUS the
                # correction/bonus sample, whose slot
                # (predraft[dptr + n_acc]) the verify had no input
                # column for. The remaining pre-draft stays valid only
                # when nothing mismatched among the scored columns AND
                # the bonus token equals its predicted slot — advancing
                # by n_acc alone would re-verify the bonus slot next
                # iteration and spuriously reject every later column.
                bonus_idx = dptr + n_acc
                bonus_pred = jnp.take_along_axis(
                    predraft,
                    jnp.clip(bonus_idx, 0, predraft.shape[1] - 1)[:, None],
                    axis=1,
                )[:, 0]
                bonus_ok = (bonus_idx >= draft_len) | (bonus_pred == last)
                alive = alive & jnp.where(
                    row_on, (n_acc >= dlen) & bonus_ok, True
                )
                dptr = dptr + n_emit
                emitted = emitted + n_emit
                drafted = drafted + dlen
                accepted = accepted + n_acc
                iters = iters + row_on.astype(jnp.int32)
                return (kv_cache, kv_swa, census, tok, pos, emitted, dptr,
                        alive, drafted, accepted, iters, out_t, out_l)

            zeros = jnp.zeros(B, jnp.int32)
            carry = (
                kv_cache, kv_swa, census, first_token, start_pos, zeros,
                zeros, jnp.ones(B, bool), zeros, zeros, zeros,
                jnp.zeros((B, Wmax), jnp.int32),
                jnp.zeros((B, Wmax), jnp.float32),
            )
            (kv_cache, kv_swa, census, _, _, emitted, _, _, drafted,
             accepted, iters, out_t, out_l) = jax.lax.fori_loop(
                 0, window, body, carry)
            meta = jnp.stack(
                [emitted, drafted, accepted, iters], axis=1
            ).astype(jnp.float32)
            packed = jnp.concatenate(
                [meta, out_t.astype(jnp.float32), out_l], axis=1
            )  # [B, 4 + 2*Wmax]
            return kv_cache, kv_swa, replicate(packed), census

        return verify_window

    def _build_multi(self):
        cfg = self.cfg
        dbo = self.config.parallel.enable_dbo
        replicate = self._replicate_out
        ring = self.swa is not None

        @functools.partial(
            jax.jit,
            donate_argnums=(1, 2) if ring else (1,),
            static_argnames=("k_steps", "all_greedy"),
        )
        def multi(
            params,
            kv_cache,
            kv_swa,  # ring pool (None unless swa_ring)
            first_token: jax.Array,  # [B]
            start_pos: jax.Array,  # [B] position of first_token
            page_table: jax.Array,  # [B, max_pages]
            swa_table,  # [B, max_pages] ring view, or None
            active: jax.Array,  # [B] bool (pad rows False)
            lora_ids,  # [B] i32 adapter slots, or None
            temperature: jax.Array,
            top_k: jax.Array,
            top_p: jax.Array,
            seeds: jax.Array,  # [B, K]
            census=None,  # [E+2] MoE census accumulator, or None
            k_steps: int = 1,
            all_greedy: bool = False,
        ):
            B = first_token.shape[0]

            def body(i, carry):
                kv_cache, kv_swa, census, tok, out_t, out_l = carry
                pos = start_pos + i
                inp = StepInput(
                    token_ids=tok[:, None],
                    positions=pos[:, None],
                    query_lens=jnp.where(active, 1, 0).astype(jnp.int32),
                    kv_lens=jnp.where(active, pos + 1, 0).astype(jnp.int32),
                    page_table=page_table,
                    lora_ids=lora_ids,
                    swa_page_table=swa_table,
                )
                hidden, kv_cache, kv_swa, census = self._fwd_hidden(
                    params, kv_cache, kv_swa, inp, census, dbo=dbo
                )
                logits = llama.compute_logits(params, hidden[:, 0, :], cfg)
                s = SamplingInputs(
                    temperature=temperature,
                    top_k=top_k,
                    top_p=top_p,
                    seeds=jax.lax.dynamic_index_in_dim(
                        seeds, i, axis=1, keepdims=False
                    ),
                )
                nxt, logp = sample_tokens(logits, s, all_greedy)
                out_t = jax.lax.dynamic_update_index_in_dim(out_t, nxt, i, axis=1)
                out_l = jax.lax.dynamic_update_index_in_dim(out_l, logp, i, axis=1)
                return kv_cache, kv_swa, census, nxt, out_t, out_l

            out_t = jnp.zeros((B, k_steps), jnp.int32)
            out_l = jnp.zeros((B, k_steps), jnp.float32)
            kv_cache, kv_swa, census, _, out_t, out_l = jax.lax.fori_loop(
                0, k_steps, body,
                (kv_cache, kv_swa, census, first_token, out_t, out_l),
            )
            packed = jnp.concatenate(
                [out_t.astype(jnp.float32), out_l], axis=1
            )  # [B, 2K]
            return kv_cache, kv_swa, replicate(packed), census

        return multi

    # ------------------------------------------------------------------ #
    # multi-host KV staging programs (lockstep-dispatched on all procs)

    @functools.cached_property
    def _replicated_gather(self):
        """Gather pages -> CANONICAL heads, output fully replicated: the
        all-gather of the tp-sharded head axis rides ICI, after which the
        leader's host download is a local replica read. Int8 pools
        dequantize in-program to the staging dtype."""
        rep = self.kv_rep
        dt = jnp.dtype(self.staging_dtype) if self.kv_quantized else None

        def gather(kv, ids):
            if isinstance(kv, tuple):
                from llmd_tpu.ops.quant_kv import dequantize_pages

                d, s = kv[0][:, ids], kv[1][:, ids]
                if rep > 1:
                    d, s = d[:, :, ::rep], s[:, :, ::rep]
                return dequantize_pages(d, s, dt)
            out = kv[:, ids]
            if rep > 1:
                out = out[:, :, ::rep]
            return out

        return jax.jit(gather, out_shardings=self.ctx.replicated)

    @functools.cached_property
    def _replicated_gather_q8(self):
        """Q8-wire gather: float pools quantize in-program; int8 pools
        ship their bytes directly (lossless wrt the pool, half the
        staging bytes, zero quantize work)."""
        rep = self.kv_rep

        def gather(kv, ids):
            if isinstance(kv, tuple):
                from llmd_tpu.ops.quant_kv import pool_scales_to_wire

                d, s = kv[0][:, ids], kv[1][:, ids]
                if rep > 1:
                    d, s = d[:, :, ::rep], s[:, :, ::rep]
                # Pool scales are f32 ON the f16 grid — the wire's f16
                # form is a lossless cast.
                return d, pool_scales_to_wire(s).astype(jnp.float16)
            out = kv[:, ids]
            if rep > 1:
                out = out[:, :, ::rep]
            return _quantize_rows_q8(out)

        return jax.jit(gather, out_shardings=self.ctx.replicated)

    @functools.cached_property
    def _scatter_canonical(self):
        """Scatter canonical-head bundles into the pool (head expansion
        on device); every process writes its own shards of the result.
        Int8 pools quantize the incoming float rows in-program."""
        rep = self.kv_rep

        def scatter(kv, ids, vals):
            if rep > 1:
                vals = jnp.repeat(vals, rep, axis=2)
            if isinstance(kv, tuple):
                from llmd_tpu.ops.quant_kv import quantize_pages

                d, s = quantize_pages(vals)
                return (
                    kv[0].at[:, ids].set(d),
                    kv[1].at[:, ids].set(s),
                )
            # Heterogeneous-pool local claims (e.g. bf16 producer -> f32
            # consumer) cast at the write.
            return kv.at[:, ids].set(vals.astype(kv.dtype))

        return jax.jit(scatter, donate_argnums=(0,))

    @functools.cached_property
    def _scatter_q8_direct(self):
        """Scatter a q8-wire bundle (q8 data + wire-layout scales)
        straight into an int8 pool — no dequant/requant round trip."""
        rep = self.kv_rep

        def scatter(kv, ids, d, s_wire):
            from llmd_tpu.ops.quant_kv import wire_scales_to_pool

            s = wire_scales_to_pool(s_wire)  # [L, n, K, page, 2]
            if rep > 1:
                d = jnp.repeat(d, rep, axis=2)
                s = jnp.repeat(s, rep, axis=2)
            return (
                kv[0].at[:, ids].set(d),
                kv[1].at[:, ids].set(s.astype(kv[1].dtype)),
            )

        return jax.jit(scatter, donate_argnums=(0,))

    # ------------------------------------------------------------------ #
    # layer-group staging programs (the v3 group-framed KV transfer:
    # docs/architecture/kv-cache.md "layer-streamed import"). The layer
    # index rides as a TRACED [Lg] array, so one program per (Lg, page
    # count) shape family serves every group offset — not one per l0.

    @functools.cached_property
    def _replicated_gather_group(self):
        """Layer-sliced gather -> canonical heads, fully replicated:
        [Lg, n, K, page, 2D] of layers ``l_ids``."""
        rep = self.kv_rep
        dt = jnp.dtype(self.staging_dtype) if self.kv_quantized else None

        def gather(kv, l_ids, ids):
            li = l_ids[:, None]
            if isinstance(kv, tuple):
                from llmd_tpu.ops.quant_kv import dequantize_pages

                d, s = kv[0][li, ids[None, :]], kv[1][li, ids[None, :]]
                if rep > 1:
                    d, s = d[:, :, ::rep], s[:, :, ::rep]
                return dequantize_pages(d, s, dt)
            out = kv[li, ids[None, :]]
            if rep > 1:
                out = out[:, :, ::rep]
            return out

        return jax.jit(gather, out_shardings=self.ctx.replicated)

    @functools.cached_property
    def _replicated_gather_group_q8(self):
        """Layer-sliced q8-wire gather (the grouped twin of
        :attr:`_replicated_gather_q8`)."""
        rep = self.kv_rep

        def gather(kv, l_ids, ids):
            li = l_ids[:, None]
            if isinstance(kv, tuple):
                from llmd_tpu.ops.quant_kv import pool_scales_to_wire

                d, s = kv[0][li, ids[None, :]], kv[1][li, ids[None, :]]
                if rep > 1:
                    d, s = d[:, :, ::rep], s[:, :, ::rep]
                return d, pool_scales_to_wire(s).astype(jnp.float16)
            out = kv[li, ids[None, :]]
            if rep > 1:
                out = out[:, :, ::rep]
            return _quantize_rows_q8(out)

        return jax.jit(gather, out_shardings=self.ctx.replicated)

    @functools.cached_property
    def _scatter_canonical_group(self):
        """Layer-sliced scatter of a canonical [Lg, n, ...] bundle into
        pool layers ``l_ids`` (the grouped twin of
        :attr:`_scatter_canonical`). Int8 pools quantize in-program."""
        rep = self.kv_rep

        def scatter(kv, l_ids, ids, vals):
            li = l_ids[:, None]
            if rep > 1:
                vals = jnp.repeat(vals, rep, axis=2)
            if isinstance(kv, tuple):
                from llmd_tpu.ops.quant_kv import quantize_pages

                d, s = quantize_pages(vals)
                return (
                    kv[0].at[li, ids[None, :]].set(d),
                    kv[1].at[li, ids[None, :]].set(s),
                )
            return kv.at[li, ids[None, :]].set(vals.astype(kv.dtype))

        return jax.jit(scatter, donate_argnums=(0,))

    @functools.cached_property
    def _scatter_q8_direct_group(self):
        """Layer-sliced q8-wire scatter into an int8 pool (the grouped
        twin of :attr:`_scatter_q8_direct`)."""
        rep = self.kv_rep

        def scatter(kv, l_ids, ids, d, s_wire):
            from llmd_tpu.ops.quant_kv import wire_scales_to_pool

            li = l_ids[:, None]
            s = wire_scales_to_pool(s_wire)  # [Lg, n, K, page, 2]
            if rep > 1:
                d = jnp.repeat(d, rep, axis=2)
                s = jnp.repeat(s, rep, axis=2)
            return (
                kv[0].at[li, ids[None, :]].set(d),
                kv[1].at[li, ids[None, :]].set(s.astype(kv[1].dtype)),
            )

        return jax.jit(scatter, donate_argnums=(0,))

    def _pool(self, swa: bool):
        """Select the staging target: the main pool or the SWA ring pool.
        The staging programs themselves are pool-agnostic (the pool is an
        argument), so both pools share them."""
        return self.kv_swa if swa else self.kv_cache

    def _pool_data(self, swa: bool) -> jax.Array:
        kv = self._pool(swa)
        return kv[0] if isinstance(kv, tuple) else kv

    @functools.cached_property
    def _copy_pool_pages(self):
        """Device-to-device page copy within one pool (hybrid-APC
        sliding-section capture/seed; no host bytes move)."""

        def copy(kv, src, dst):
            if isinstance(kv, tuple):
                return (
                    kv[0].at[:, dst].set(kv[0][:, src]),
                    kv[1].at[:, dst].set(kv[1][:, src]),
                )
            return kv.at[:, dst].set(kv[:, src])

        return jax.jit(copy, donate_argnums=(0,))

    def copy_pages_on_device(
        self, src_ids: list[int], dst_ids: list[int], swa: bool = False
    ) -> None:
        """Copy pool pages src -> dst on device (lockstep in multi-host:
        a plain SPMD program every process mirrors)."""
        arrays = {
            "src": np.asarray(src_ids, np.int32),
            "dst": np.asarray(dst_ids, np.int32),
        }
        if self._multihost:
            with self._dispatch_lock:
                arrays = self._sync_locked(
                    _OP_KV_COPY, len(src_ids), int(swa), False, arrays
                )
                self._exec_kv_copy(arrays, swa)
            return
        self._exec_kv_copy(arrays, swa)

    def _exec_kv_copy(self, arrays: dict, swa: bool) -> None:
        out = self._copy_pool_pages(
            self._pool(swa), jnp.asarray(arrays["src"]),
            jnp.asarray(arrays["dst"]),
        )
        if swa:
            self.kv_swa = out
        else:
            self.kv_cache = out

    def _exec_kv_gather(self, arrays: dict, q8: bool, swa: bool = False):
        fn = self._replicated_gather_q8 if q8 else self._replicated_gather
        return fn(self._pool(swa), jnp.asarray(arrays["ids"]))

    def _exec_kv_scatter(self, arrays: dict, n: int, swa: bool = False) -> None:
        data = self._pool_data(swa)
        Kc = data.shape[2] // self.kv_rep
        shape = (data.shape[0], n, Kc, self.page, data.shape[4])
        vals = np.frombuffer(
            np.ascontiguousarray(arrays["vals_u8"]).data,
            dtype=self.staging_dtype,
        ).reshape(shape)
        out = self._scatter_canonical(
            self._pool(swa), jnp.asarray(arrays["ids"]), jnp.asarray(vals)
        )
        if swa:
            self.kv_swa = out
        else:
            self.kv_cache = out

    def _exec_kv_scatter_q8(self, arrays: dict, swa: bool = False) -> None:
        ids = jnp.asarray(arrays["ids"])
        q8 = jnp.asarray(arrays["q8"])
        scales = jnp.asarray(arrays["scales"])
        if self.kv_quantized:
            out = self._scatter_q8_direct(self._pool(swa), ids, q8, scales)
        else:
            vals = _dequantize_rows_q8(q8, scales, self.staging_dtype_name)
            out = self._scatter_canonical(self._pool(swa), ids, vals)
        if swa:
            self.kv_swa = out
        else:
            self.kv_cache = out

    def _kv_gather_lockstep(self, ids: np.ndarray, q8: bool, swa: bool = False):
        """Leader leg of a multi-host page gather: broadcast the op so
        every process dispatches the same program; return the (replicated)
        result. Any leader thread may call — the dispatch lock keeps each
        broadcast+dispatch pair atomic in the totally ordered op stream.
        The header's 4th slot carries the pool selector (main vs SWA
        ring) for KV ops."""
        assert dist.is_leader(), "KV staging ops originate on the leader"
        with self._dispatch_lock:
            arrays = self._sync_locked(
                _OP_KV_GATHER, len(ids), int(q8), bool(swa), {"ids": ids}
            )
            return self._exec_kv_gather(arrays, q8, swa)

    # ------------------------------------------------------------------ #
    # host-side input prep

    @staticmethod
    def _overwrite_seeded_rows(
        seeds: np.ndarray, seqs: list[ScheduledSeq], K: int
    ) -> None:
        """Deterministic per (request seed, output index): resubmitting
        the same seeded request reproduces its tokens regardless of
        batch-mates or window size. ``sampler.spec_seed`` is the ONE
        derivation every dispatch path uses — prefill, fused decode
        windows, and the one-shot speculative verify step apply it here
        on host; the fused verify window applies the same function on
        device (its output indices depend on device-side acceptance) —
        or seeded speculative acceptance silently loses its byte-parity
        guarantee."""
        for i, s in enumerate(seqs):
            sp = s.request.sampling
            if sp.seed is not None:
                pos = s.request.total_output_tokens
                for j in range(K):
                    seeds[i, j] = np.uint32(spec_seed(sp.seed, pos + j))

    @staticmethod
    def _sampling_knobs(seqs: list[ScheduledSeq], B: int):
        """(temp, top_k, top_p) rows for a dispatch — shared by every
        path that stages sampling inputs. Seeds are deliberately NOT
        here: they come from the stateful rng, which must advance in
        dispatch order only (see stage_decode)."""
        temp = np.zeros(B, np.float32)
        top_k = np.zeros(B, np.int32)
        top_p = np.ones(B, np.float32)
        for i, s in enumerate(seqs):
            sp = s.request.sampling
            temp[i] = 0.0 if sp.greedy else sp.temperature
            top_k[i] = sp.top_k
            top_p[i] = sp.top_p
        return temp, top_k, top_p

    def _sampling_arrays(self, seqs: list[ScheduledSeq], B: int, K: int = 1):
        temp, top_k, top_p = self._sampling_knobs(seqs, B)
        seeds = self._np_rng.integers(0, 2**32, size=(B, K), dtype=np.uint32)
        self._overwrite_seeded_rows(seeds, seqs, K)
        return temp, top_k, top_p, seeds

    def _lora_array(self, seqs: list[ScheduledSeq], B: int) -> np.ndarray:
        """[B] adapter slots (pad rows 0 = base model) for the payload."""
        ids = np.zeros(B, np.int32)
        for i, s in enumerate(seqs):
            ids[i] = s.request.lora_id
        return ids

    def _require_single_host(self, what: str) -> None:
        """Paths not mirrored to followers must refuse loudly in a
        multi-host world: a leader-only device program whose shardings
        span follower-owned devices would deadlock the whole group."""
        if self._multihost:
            raise NotImplementedError(
                f"{what} is not supported in multi-host mode (the "
                "prefill/decode serving steps and the KV staging ops are "
                "broadcast to follower processes; see deploy/guides/"
                "wide-ep-lws/README.md scope notes)"
            )

    def _page_table(self, seqs: list[ScheduledSeq], B: int) -> np.ndarray:
        pt = np.zeros((B, self.max_pages), np.int32)
        for i, s in enumerate(seqs):
            ids = s.request.block_ids
            pt[i, : len(ids)] = ids
        return pt

    def _swa_table(self, seqs: list[ScheduledSeq], B: int) -> np.ndarray:
        """Ring-view table for sliding layers: logical page l of sequence
        i maps to ring[l % R]. Same [B, max_pages] shape as the main table
        so every kernel path is unchanged; the repeats past the window are
        exactly the pages the window-skip never reads. Rows are immutable
        once a sequence's ring is allocated, so they memoize on the
        request (scheduler._release invalidates)."""
        pt = np.zeros((B, self.max_pages), np.int32)
        for i, s in enumerate(seqs):
            req = s.request
            ring = req.swa_block_ids
            if not ring:
                continue
            row = req.swa_table_row
            if row is None or len(row) != self.max_pages:
                row = np.asarray(ring, np.int32)[
                    np.arange(self.max_pages) % len(ring)
                ]
                req.swa_table_row = row
            pt[i] = row
        return pt

    # ------------------------------------------------------------------ #
    # multi-host lockstep dispatch (leader broadcasts, followers mirror)

    def _payload_spec(self, op: int, B: int, QK: int):
        """(name, shape, dtype) tuple layout for one op's array payload —
        the contract both sides of the broadcast derive independently.

        KV ops reuse the header slots: B carries the page count and QK the
        q8 flag (gather). Scatter payload geometry derives from the pool
        config both sides share."""
        if op == _OP_HEARTBEAT:
            # Liveness tick only; a 1-slot dummy keeps the payload leg's
            # pytree non-empty (both sides derive the same shape).
            return [("hb", (1,), np.int32)]
        if op == _OP_KV_GATHER:
            return [("ids", (B,), np.int32)]
        if op == _OP_KV_COPY:
            return [("src", (B,), np.int32), ("dst", (B,), np.int32)]
        if op == _OP_EMBED:
            return [
                ("tokens", (B, QK), np.int32),
                ("positions", (B, QK), np.int32),
                ("qlens", (B,), np.int32),
            ]
        if op == _OP_LORA:
            # B slot = pair-presence bitmask (1: q pair, 2: v pair); the
            # factor shapes derive from the shared params structure.
            layers = self.params["layers"]
            spec = []
            for bit, a, b in ((1, "la_q", "lb_q"), (2, "la_v", "lb_v")):
                if B & bit:
                    for k in (a, b):
                        s = layers[k].shape
                        spec.append((k, (s[0], *s[2:]), np.float32))
            return spec
        if op == _OP_KV_SCATTER:
            # QK carries the pool selector (main vs SWA ring): the two
            # pools have different layer counts, so the payload geometry
            # both sides derive depends on it.
            data = self._pool_data(bool(QK))
            Kc = data.shape[2] // self.kv_rep
            nbytes = (
                data.shape[0] * B * Kc * self.page
                * data.shape[4] * self.staging_dtype.itemsize
            )
            return [("ids", (B,), np.int32), ("vals_u8", (nbytes,), np.uint8)]
        if op == _OP_KV_SCATTER_Q8:
            # Same header contract as _OP_KV_SCATTER (B = padded page
            # count, QK = pool selector); the payload is the q8 wire
            # form — i8 rows + f16 per-(token, head) K/V-half scales.
            data = self._pool_data(bool(QK))
            Kc = data.shape[2] // self.kv_rep
            L, D2 = data.shape[0], data.shape[4]
            return [
                ("ids", (B,), np.int32),
                ("q8", (L, B, Kc, self.page, D2), np.int8),
                ("scales", (L, B, Kc, self.page, 2), np.float16),
            ]
        mp = self.max_pages
        if op in (_OP_PREFILL, _OP_VERIFY):
            spec = [
                ("tokens", (B, QK), np.int32),
                ("positions", (B, QK), np.int32),
                ("qlens", (B,), np.int32),
                ("kvlens", (B,), np.int32),
                ("page_table", (B, mp), np.int32),
                ("temp", (B,), np.float32),
                ("top_k", (B,), np.int32),
                ("top_p", (B,), np.float32),
                # Verify samples at every position, so its seeds are
                # per (row, position) — the one payload difference from
                # the prefill family.
                ("seeds", (B, QK) if op == _OP_VERIFY else (B,), np.uint32),
            ]
        elif op == _OP_VERIFY_WINDOW:
            # QK carries the WINDOW size (verify iterations fused);
            # the per-iteration column count Q derives from the shared
            # engine config (1 + spec_ngram_k) on both sides.
            q = self.spec_q
            spec = [
                ("first", (B,), np.int32),
                ("start", (B,), np.int32),
                # window x q - 1 slots: each fully-accepted iteration
                # consumes q (= k scored columns + the bonus slot), and
                # the last iteration's bonus needs no prediction.
                ("predraft", (B, QK * q - 1), np.int32),
                ("dlen", (B,), np.int32),
                ("limit", (B,), np.int32),
                ("page_table", (B, mp), np.int32),
                ("active", (B,), np.uint8),
                ("temp", (B,), np.float32),
                ("top_k", (B,), np.int32),
                ("top_p", (B,), np.float32),
                # One engine-RNG seed block per (iteration, row,
                # position); seeded rows are overridden ON DEVICE by
                # the per-(seed, output-index) derivation, because
                # their output indices depend on device-side
                # acceptance.
                ("seeds", (B, QK, q), np.uint32),
                ("seed_base", (B,), np.uint32),
                ("seeded", (B,), np.uint8),
                ("out0", (B,), np.int32),
            ]
        elif op == _OP_UNIFIED:
            # QK packs (Q_bucket << 20) | T_bucket: the follower needs
            # BOTH the per-row column count and the token-stream length
            # to derive the payload geometry; the sample width S derives
            # from the shared engine config (spec_q or 1) on both sides.
            t = QK & 0xFFFFF
            spec = [
                ("stream", (t,), np.int32),
                ("row_start", (B,), np.int32),
                ("pos0", (B,), np.int32),
                ("qlens", (B,), np.int32),
                ("kvlens", (B,), np.int32),
                ("kind", (B,), np.uint8),
                ("page_table", (B, mp), np.int32),
                ("temp", (B,), np.float32),
                ("top_k", (B,), np.int32),
                ("top_p", (B,), np.float32),
                ("seeds", (B, self.unified_s), np.uint32),
            ]
        elif op == _OP_FLAT:
            # Flattened-token step: QK carries T_bucket directly (the
            # flat family has no per-row column bucket). The run-plan
            # width derives from (B, T, page) identically on both sides:
            # a row touching p pages emits p runs, and p <= (w-1)//page
            # + 2 (the +2 covers the first page AND a mid-page start's
            # extra straddle — a 2-token row starting at slot page-1
            # already touches two pages), so the total is bounded by
            # 2*B + ceil(T / page).
            t = QK
            rn = 2 * B + -(-t // self.page)
            spec = [
                ("stream", (t,), np.int32),
                ("row_start", (B,), np.int32),
                ("pos0", (B,), np.int32),
                ("qlens", (B,), np.int32),
                ("kvlens", (B,), np.int32),
                ("kind", (B,), np.uint8),
                ("page_table", (B, mp), np.int32),
                ("temp", (B,), np.float32),
                ("top_k", (B,), np.int32),
                ("top_p", (B,), np.float32),
                ("seeds", (B, self.unified_s), np.uint32),
                ("wsrc", (rn,), np.int32),
                ("woff", (rn,), np.int32),
                ("wcnt", (rn,), np.int32),
                ("wphys", (rn,), np.int32),
            ]
            if self.swa is not None:
                spec.append(("wphys_swa", (rn,), np.int32))
        else:
            spec = [
                ("first", (B,), np.int32),
                ("start", (B,), np.int32),
                ("page_table", (B, mp), np.int32),
                ("active", (B,), np.uint8),
                ("temp", (B,), np.float32),
                ("top_k", (B,), np.int32),
                ("top_p", (B,), np.float32),
                ("seeds", (B, QK), np.uint32),
            ]
        if self.swa is not None:
            # Ring-view table for sliding layers; followers derive its
            # presence from the shared engine config.
            spec.append(("swa_table", (B, mp), np.int32))
        if self.cfg.num_lora_adapters:
            spec.append(("lora", (B,), np.int32))
        return spec

    def _bounded(self, fn, what: str):
        """Run one lockstep collective leg with a bounded wait.

        ``broadcast_one_to_all`` blocks until EVERY process participates;
        a dead/wedged peer turns that into an infinite hang that no
        watchdog above can attribute. The collective runs on a dedicated
        single worker thread and the caller waits at most
        ``lockstep_timeout_s`` — on expiry the group is declared dead
        with a loud RuntimeError (the step fails fast; the serving
        watchdog then 503s /health and terminates streams). The worker
        thread stays parked in the dead collective, which is fine: the
        process is about to be restarted by the platform anyway.

        Startup exemption: the first collective of a process runs
        UNBOUNDED (cold-compile/weight-load skew legitimately exceeds
        any liveness budget; the startup probe owns that phase), and the
        wait arms once one collective has completed."""
        timeout = self.lockstep_timeout_s
        if not timeout or timeout <= 0:
            return fn()
        if not self._lockstep_warmed:
            out = fn()
            self._lockstep_warmed = True
            return out
        if self._lockstep_compile_grace:
            # The previous dispatch opened a new shape family: the peer
            # may be inside a legitimately-long jit compile of it, not
            # dead. One unbounded pass, then the bound re-arms.
            self._lockstep_compile_grace = False
            return fn()
        if self._lockstep_pool is None:
            self._lockstep_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="llmd-lockstep"
            )
        fut = self._lockstep_pool.submit(fn)
        try:
            return fut.result(timeout)
        except concurrent.futures.TimeoutError:
            # llmd: allow(concurrency) -- one-way latch (False->True only): leader legs hold the dispatch lock already; the follower mirror loop is its process's sole lockstep thread
            self._stopped = True  # no further broadcasts into a dead group
            raise RuntimeError(
                f"lockstep {what} did not complete within {timeout:.0f}s: "
                "a peer process is dead or wedged (set "
                "LLMD_LOCKSTEP_TIMEOUT_S to tune; 0 disables)"
            ) from None

    def _heartbeat_loop(self) -> None:
        """Leader-side liveness ticks: when no real op has been broadcast
        for a third of the lockstep budget, send _OP_HEARTBEAT so idle
        followers' bounded header wait keeps getting fed."""
        period = max(self.lockstep_timeout_s / 3.0, 1.0)
        while not self._hb_stop.wait(period / 2):
            # llmd: allow(concurrency) -- double-checked peek: re-read under the dispatch lock below before broadcasting; a stale False only costs one loop turn
            if self._stopped:
                return
            if not self._lockstep_warmed:
                continue  # startup phase: followers wait unbounded anyway
            if time.monotonic() - self._last_broadcast < period:
                continue
            try:
                with self._dispatch_lock:
                    if self._stopped:
                        return
                    self._sync_locked(
                        _OP_HEARTBEAT, 0, 0, False,
                        {"hb": np.zeros(1, np.int32)},
                    )
            except RuntimeError:
                log.exception("lockstep heartbeat failed; group is dead")
                return

    def _sync_locked(self, op: int, B: int, QK: int, greedy: bool, arrays: dict) -> dict:
        """Leader leg: broadcast header + payload; identity single-host."""
        if not self._multihost:
            return arrays
        if self._stopped:
            raise RuntimeError(
                "lockstep dispatch after stop_followers: the follower "
                "processes have exited and a broadcast would hang"
            )
        from jax.experimental import multihost_utils as mhu

        spec = self._payload_spec(op, B, QK)
        staged = tuple(
            np.ascontiguousarray(arrays[name]).astype(dt, copy=False)
            for name, _, dt in spec
        )

        def _broadcast():
            # Injection site: a stalled collective is indistinguishable
            # from a dead peer — exactly what the bounded wait bounds.
            from llmd_tpu import faults as _faults

            _faults.delay("lockstep.sync.stall")
            mhu.broadcast_one_to_all(
                np.asarray([op, B, QK, int(greedy)], np.int32),
                is_source=True,
            )
            return mhu.broadcast_one_to_all(staged, is_source=True)

        payload = self._bounded(_broadcast, f"broadcast of op {op}")
        self._last_broadcast = time.monotonic()
        if op != _OP_HEARTBEAT:
            shape_key = (op, B, QK, bool(greedy))
            if shape_key not in self._lockstep_seen_shapes:
                self._lockstep_seen_shapes.add(shape_key)
                # Followers compile this family during their exec of
                # this dispatch; the next broadcast must not bound it.
                self._lockstep_compile_grace = True
        return {name: arr for (name, _, _), arr in zip(spec, payload)}

    def follower_loop(self) -> None:
        """Run on every non-leader process: mirror the leader's dispatches
        until a stop is broadcast. Blocks for the life of the deployment."""
        from jax.experimental import multihost_utils as mhu

        assert self._multihost and not dist.is_leader(), (
            "follower_loop is for non-leader processes of a multi-host world"
        )
        # With the leader heartbeating every timeout/3 when idle, a
        # header wait past the full budget means the leader is dead —
        # the follower raises loudly instead of hanging forever. The
        # payload leg after a header is bounded the same way (a leader
        # dying mid-broadcast must not wedge the group).
        while True:
            hdr = self._bounded(
                lambda: mhu.broadcast_one_to_all(
                    np.zeros(4, np.int32), is_source=False
                ),
                "header wait (leader liveness)",
            )
            op, B, QK, greedy = (int(v) for v in np.asarray(hdr))
            if op == _OP_STOP:
                return
            spec = self._payload_spec(op, B, QK)
            zeros = tuple(np.zeros(shp, dt) for _, shp, dt in spec)
            payload = self._bounded(
                lambda: mhu.broadcast_one_to_all(zeros, is_source=False),
                f"payload wait for op {op}",
            )
            arrays = {name: arr for (name, _, _), arr in zip(spec, payload)}
            if op == _OP_HEARTBEAT:
                continue  # liveness tick only; nothing to dispatch
            shape_key = (op, B, QK, bool(greedy))
            if shape_key not in self._lockstep_seen_shapes:
                self._lockstep_seen_shapes.add(shape_key)
                # The leader compiles this family during its own exec;
                # the next header wait must not bound that compile.
                self._lockstep_compile_grace = True
            if op == _OP_PREFILL:
                self._exec_prefill(arrays, bool(greedy))
            elif op == _OP_VERIFY:
                self._exec_verify(arrays, bool(greedy))
            elif op == _OP_VERIFY_WINDOW:
                self._exec_verify_window(arrays, QK, bool(greedy))
            elif op == _OP_UNIFIED:
                # QK packs (Q_bucket << 20) | T_bucket; the exec only
                # needs the static per-row column count.
                self._exec_unified(arrays, QK >> 20, bool(greedy))
            elif op == _OP_FLAT:
                self._exec_flat(arrays, bool(greedy))
            elif op == _OP_KV_GATHER:
                # Participate in the SPMD gather (the all-gather collective
                # needs every process); the replicated result is dropped —
                # only the leader stages it to the network. ``greedy``
                # carries the pool selector for KV ops.
                self._exec_kv_gather(arrays, bool(QK), bool(greedy))
            elif op == _OP_KV_SCATTER:
                self._exec_kv_scatter(arrays, B, bool(QK))
            elif op == _OP_KV_SCATTER_Q8:
                self._exec_kv_scatter_q8(arrays, bool(QK))
            elif op == _OP_KV_COPY:
                self._exec_kv_copy(arrays, bool(QK))
            elif op == _OP_EMBED:
                # greedy slot carries the lora id; the replicated pooled
                # output is only read on the leader.
                self._exec_embed(arrays, greedy)
            elif op == _OP_LORA:
                self._exec_lora(arrays, QK)
            elif op == _OP_DECODE:
                self._exec_decode(arrays, QK, bool(greedy))
            else:
                # An unknown opcode means leader and follower disagree on
                # the dispatch protocol (e.g. an opcode added without a
                # follower arm): the follower would mirror the WRONG
                # program and desynchronize the SPMD collective stream.
                # Crash loudly instead of hanging the whole group.
                raise RuntimeError(
                    f"follower received unknown lockstep opcode {op}; "
                    "leader and follower builds disagree on the dispatch "
                    "protocol"
                )

    def stop_followers(self) -> None:
        if self._multihost and dist.is_leader():
            from jax.experimental import multihost_utils as mhu

            with self._dispatch_lock:
                if self._stopped:
                    return
                self._stopped = True
                self._hb_stop.set()
                mhu.broadcast_one_to_all(
                    np.asarray([_OP_STOP, 0, 0, 0], np.int32), is_source=True
                )

    def _exec_prefill(self, arrays: dict, all_greedy: bool) -> jax.Array:
        inp = StepInput(
            token_ids=jnp.asarray(arrays["tokens"]),
            positions=jnp.asarray(arrays["positions"]),
            query_lens=jnp.asarray(arrays["qlens"]),
            kv_lens=jnp.asarray(arrays["kvlens"]),
            page_table=jnp.asarray(arrays["page_table"]),
            lora_ids=(
                jnp.asarray(arrays["lora"]) if "lora" in arrays else None
            ),
            swa_page_table=(
                jnp.asarray(arrays["swa_table"])
                if "swa_table" in arrays else None
            ),
        )
        s = SamplingInputs(
            temperature=jnp.asarray(arrays["temp"]),
            top_k=jnp.asarray(arrays["top_k"]),
            top_p=jnp.asarray(arrays["top_p"]),
            seeds=jnp.asarray(arrays["seeds"]),
        )
        # Program selection is shape-deterministic (Q rides the lockstep
        # broadcast), so leader and followers always pick the same family.
        Q = arrays["tokens"].shape[1]
        fwd = self._forward
        if (
            self._forward_cp is not None
            and Q % self.cp_prefill == 0
            and Q >= max(self.cp_min_tokens, self.cp_prefill)
        ):
            fwd = self._forward_cp
            self.cp_ring_steps_total += self.cp_prefill
        self.kv_cache, self.kv_swa, packed, self._moe_census = fwd(
            self.params, self.kv_cache, self.kv_swa, inp, s,
            census=self._moe_census, all_greedy=all_greedy,
        )
        return packed

    def _exec_verify(self, arrays: dict, all_greedy: bool) -> jax.Array:
        inp = StepInput(
            token_ids=jnp.asarray(arrays["tokens"]),
            positions=jnp.asarray(arrays["positions"]),
            query_lens=jnp.asarray(arrays["qlens"]),
            kv_lens=jnp.asarray(arrays["kvlens"]),
            page_table=jnp.asarray(arrays["page_table"]),
            lora_ids=(
                jnp.asarray(arrays["lora"]) if "lora" in arrays else None
            ),
            swa_page_table=(
                jnp.asarray(arrays["swa_table"])
                if "swa_table" in arrays else None
            ),
        )
        s = SamplingInputs(
            temperature=jnp.asarray(arrays["temp"]),
            top_k=jnp.asarray(arrays["top_k"]),
            top_p=jnp.asarray(arrays["top_p"]),
            seeds=jnp.asarray(arrays["seeds"]),
        )
        self.kv_cache, self.kv_swa, packed, self._moe_census = self._verify(
            self.params, self.kv_cache, self.kv_swa, inp, s,
            census=self._moe_census, all_greedy=all_greedy,
        )
        return packed

    def _exec_unified(self, arrays: dict, Q: int, all_greedy: bool) -> jax.Array:
        self.kv_cache, self.kv_swa, packed, self._moe_census = self._unified(
            self.params,
            self.kv_cache,
            self.kv_swa,
            jnp.asarray(arrays["stream"]),
            jnp.asarray(arrays["row_start"]),
            jnp.asarray(arrays["pos0"]),
            jnp.asarray(arrays["qlens"]),
            jnp.asarray(arrays["kvlens"]),
            jnp.asarray(arrays["kind"] == _KIND_VERIFY),
            jnp.asarray(arrays["page_table"]),
            (
                jnp.asarray(arrays["swa_table"])
                if "swa_table" in arrays else None
            ),
            jnp.asarray(arrays["lora"]) if "lora" in arrays else None,
            jnp.asarray(arrays["temp"]),
            jnp.asarray(arrays["top_k"]),
            jnp.asarray(arrays["top_p"]),
            jnp.asarray(arrays["seeds"]),
            census=self._moe_census,
            Q=Q,
            all_greedy=all_greedy,
        )
        return packed

    def _exec_flat(self, arrays: dict, all_greedy: bool) -> jax.Array:
        self.kv_cache, self.kv_swa, packed, self._moe_census = self._flat(
            self.params,
            self.kv_cache,
            self.kv_swa,
            jnp.asarray(arrays["stream"]),
            jnp.asarray(arrays["row_start"]),
            jnp.asarray(arrays["pos0"]),
            jnp.asarray(arrays["qlens"]),
            jnp.asarray(arrays["kind"] == _KIND_VERIFY),
            jnp.asarray(arrays["page_table"]),
            (
                jnp.asarray(arrays["swa_table"])
                if "swa_table" in arrays else None
            ),
            jnp.asarray(arrays["lora"]) if "lora" in arrays else None,
            jnp.asarray(arrays["temp"]),
            jnp.asarray(arrays["top_k"]),
            jnp.asarray(arrays["top_p"]),
            jnp.asarray(arrays["seeds"]),
            jnp.asarray(arrays["wsrc"]),
            jnp.asarray(arrays["woff"]),
            jnp.asarray(arrays["wcnt"]),
            jnp.asarray(arrays["wphys"]),
            (
                jnp.asarray(arrays["wphys_swa"])
                if "wphys_swa" in arrays else None
            ),
            census=self._moe_census,
            all_greedy=all_greedy,
        )
        return packed

    def _exec_verify_window(
        self, arrays: dict, window: int, all_greedy: bool
    ) -> jax.Array:
        (self.kv_cache, self.kv_swa, packed,
         self._moe_census) = self._verify_window(
            self.params,
            self.kv_cache,
            self.kv_swa,
            jnp.asarray(arrays["first"]),
            jnp.asarray(arrays["start"]),
            jnp.asarray(arrays["predraft"]),
            jnp.asarray(arrays["dlen"]),
            jnp.asarray(arrays["limit"]),
            jnp.asarray(arrays["page_table"]),
            (
                jnp.asarray(arrays["swa_table"])
                if "swa_table" in arrays else None
            ),
            jnp.asarray(arrays["active"].astype(bool)),
            jnp.asarray(arrays["lora"]) if "lora" in arrays else None,
            jnp.asarray(arrays["temp"]),
            jnp.asarray(arrays["top_k"]),
            jnp.asarray(arrays["top_p"]),
            jnp.asarray(arrays["seeds"]),
            jnp.asarray(arrays["seed_base"]),
            jnp.asarray(arrays["seeded"].astype(bool)),
            jnp.asarray(arrays["out0"]),
            census=self._moe_census,
            window=window,
            all_greedy=all_greedy,
        )
        return packed

    def _exec_decode(self, arrays: dict, K: int, all_greedy: bool) -> jax.Array:
        self.kv_cache, self.kv_swa, packed, self._moe_census = self._multi(
            self.params,
            self.kv_cache,
            self.kv_swa,
            jnp.asarray(arrays["first"]),
            jnp.asarray(arrays["start"]),
            jnp.asarray(arrays["page_table"]),
            (
                jnp.asarray(arrays["swa_table"])
                if "swa_table" in arrays else None
            ),
            jnp.asarray(arrays["active"].astype(bool)),
            jnp.asarray(arrays["lora"]) if "lora" in arrays else None,
            jnp.asarray(arrays["temp"]),
            jnp.asarray(arrays["top_k"]),
            jnp.asarray(arrays["top_p"]),
            jnp.asarray(arrays["seeds"]),
            census=self._moe_census,
            k_steps=K,
            all_greedy=all_greedy,
        )
        return packed

    # ------------------------------------------------------------------ #
    # KV page staging (the HBM<->host leg of the P/D transfer path;
    # reference TPUConnectorHMA host-memory-assisted pattern)

    def snapshot_pages_device(
        self,
        page_ids: list[int],
        pad_to: int,
        layers: tuple[int, int] | None = None,
    ) -> jax.Array:
        """On-device snapshot of pages (padded to ``pad_to`` by repeating
        the last id): [L, pad_to, K, page, 2D] in CANONICAL heads.

        Returns immediately (async dispatch) with an INDEPENDENT device
        buffer — the engine may donate/mutate the pool right after; jax
        sequences the enqueued gather before any later pool write. The
        blocking host download happens later via ``download_pages`` on a
        staging thread, off the engine thread and off the TTFT path.

        ``layers=(l0, Lg)`` snapshots only that layer slice ([Lg, ...]) —
        the v3 group-framed transfer's per-layer-group export unit
        (single-host only; multi-host producers stay on the monolithic
        lockstep gather).

        Multi-host: the gather is lockstep-broadcast so every process
        dispatches the same SPMD program; the output is fully replicated
        (head-axis all-gather over ICI), so the later download is a local
        replica read on the leader.
        """
        ids = _padded_ids(page_ids, pad_to)
        if self._multihost:
            assert layers is None, "layer-group staging is single-host only"
            return self._kv_gather_lockstep(ids, q8=False)
        # Canonical transfer format keeps the ORIGINAL heads (peers with
        # different tp interoperate byte-exact); int8 pools dequantize
        # in-program to the staging dtype.
        if layers is not None:
            l0, lg = layers
            return self._replicated_gather_group(
                self.kv_cache,
                jnp.arange(l0, l0 + lg, dtype=jnp.int32),
                jnp.asarray(ids),
            )
        return self._replicated_gather(self.kv_cache, jnp.asarray(ids))

    def snapshot_swa_pages_device(self, page_ids: list[int], pad_to: int) -> jax.Array:
        """On-device snapshot of SWA RING pages (sliding-layer pool):
        [L_swa, pad_to, K, page, 2D] canonical heads, dequantized to the
        staging dtype for int8 pools. Same async-dispatch contract as
        snapshot_pages_device; the P/D export of a ring engine ships the
        trailing in-window ring pages through this."""
        assert self.swa is not None, "no SWA ring pool on this runner"
        ids = _padded_ids(page_ids, pad_to)
        if self._multihost:
            return self._kv_gather_lockstep(ids, q8=False, swa=True)
        return self._replicated_gather(self.kv_swa, jnp.asarray(ids))

    def snapshot_pages_device_q8(
        self,
        page_ids: list[int],
        pad_to: int,
        layers: tuple[int, int] | None = None,
    ) -> tuple[jax.Array, jax.Array]:
        """INT8-quantized snapshot for the transfer plane: per-(token,
        head)-row symmetric int8 + f16 scales, computed ON DEVICE so the
        HBM -> host staging moves HALF the bytes. Returns (q8, scales)
        with q8 [L, pad_to, K, page, 2D] i8 and scales
        [L, pad_to, K, page, 2] f16 (separate K/V half scales). Opt-in
        and lossy (~0.4% per-half rel-err) for FLOAT pools; for int8
        pools the pool bytes ship directly (lossless wrt the pool, no
        quantize work). The default transfer dtype stays pool-exact."""
        ids = _padded_ids(page_ids, pad_to)
        if self._multihost:
            assert layers is None, "layer-group staging is single-host only"
            return self._kv_gather_lockstep(ids, q8=True)
        if layers is not None:
            l0, lg = layers
            return self._replicated_gather_group_q8(
                self.kv_cache,
                jnp.arange(l0, l0 + lg, dtype=jnp.int32),
                jnp.asarray(ids),
            )
        return self._replicated_gather_q8(self.kv_cache, jnp.asarray(ids))

    @staticmethod
    def download_pages(snapshot: jax.Array) -> np.ndarray:
        """Blocking HBM -> host download of a snapshot (staging thread).

        Multi-host snapshots are fully replicated global arrays: the read
        is a local replica fetch (no collective, safe off-thread)."""
        if isinstance(snapshot, jax.Array) and not snapshot.is_fully_addressable:
            return np.ascontiguousarray(snapshot.addressable_shards[0].data)
        return np.ascontiguousarray(jax.device_get(snapshot))

    def upload_pages_device(self, pages: np.ndarray) -> jax.Array:
        """Async host -> HBM upload of a canonical bundle (fetch thread:
        creates an independent device array, touches no engine state, so
        the upload overlaps later pulls and the producer's own staging)."""
        return jnp.asarray(pages, dtype=self.staging_dtype)

    def upload_pages_device_q8(self, q8: np.ndarray, scales: np.ndarray):
        """Upload an int8-quantized bundle (half the host -> HBM bytes).

        Float pools dequantize ON DEVICE into the pool dtype; int8 pools
        keep the wire form — (q8, wire scales) scatter straight into the
        pool with no dequant/requant round trip."""
        if self.kv_quantized:
            return (jnp.asarray(q8), jnp.asarray(scales))
        return _dequantize_rows_q8(
            jnp.asarray(q8), jnp.asarray(scales), self.staging_dtype_name
        )

    def scatter_pages_from_device(
        self,
        page_ids: list[int],
        vals,
        swa: bool = False,
        layers: tuple[int, int] | None = None,
    ) -> None:
        """Device -> pool scatter of an already-uploaded chunk (head
        expansion device-side). ``vals`` is a float bundle, or a
        (q8, wire scales) pair — int8 pools scatter the pair directly;
        float pools dequantize on device first (the local fast path hands
        q8 device snapshots to any consumer pool dtype). ``swa`` targets
        the SWA ring pool; ``layers=(l0, Lg)`` writes only that layer
        slice (the v3 group-streamed import).

        Thread-safe: the whole pool read-modify-write runs under the
        dispatch lock, so the streamed import's FETCH-thread scatters
        interleave with (never tear) the engine thread's step dispatches
        — the same discipline the multi-host streamed path rides."""
        self._require_single_host("scatter_pages_from_device (P/D staging)")
        # Device chunks may come from ANOTHER engine's mesh (the local
        # fast path claims the producer's snapshots; e.g. a tp=1
        # producer feeding a tp=8 consumer): re-place them replicated on
        # THIS runner's mesh so the donated-pool scatter sees consistent
        # devices.
        place = lambda x: jax.device_put(x, self.ctx.replicated)  # noqa: E731
        ids = place(np.asarray(page_ids, np.int32))
        l_ids = (
            None if layers is None
            else place(
                np.arange(layers[0], layers[0] + layers[1], dtype=np.int32)
            )
        )
        with self._dispatch_lock:
            if isinstance(vals, tuple):
                if self.kv_quantized:
                    if l_ids is not None:
                        out = self._scatter_q8_direct_group(
                            self._pool(swa), l_ids, ids,
                            place(vals[0]), place(vals[1]),
                        )
                    else:
                        out = self._scatter_q8_direct(
                            self._pool(swa), ids, place(vals[0]), place(vals[1])
                        )
                    if swa:
                        self.kv_swa = out
                    else:
                        self.kv_cache = out
                    return
                vals = _dequantize_rows_q8(
                    vals[0], vals[1], self.staging_dtype_name
                )
            if l_ids is not None:
                out = self._scatter_canonical_group(
                    self._pool(swa), l_ids, ids, place(vals)
                )
            else:
                out = self._scatter_canonical(self._pool(swa), ids, place(vals))
            if swa:
                self.kv_swa = out
            else:
                self.kv_cache = out

    def gather_pages(self, page_ids: list[int]) -> np.ndarray:
        """Stage pages HBM -> host: returns [L, n, K, page, 2D] ndarray.

        Page count is padded to a bucket (ids repeat the last page) so XLA
        compiles one gather per bucket, not per transfer size.
        """
        n = len(page_ids)
        bucket = pad_to_bucket(n, _buckets(max(self.config.cache.num_blocks, n)))
        ids = _padded_ids(page_ids, bucket)
        # Canonical (original-heads, dequantized) bundle either way:
        # replicated copies are a local layout detail, and peers with
        # different tp/pool-dtype configs must interoperate.
        if self._multihost:
            snap = self._kv_gather_lockstep(ids, q8=False)
        else:
            snap = self._replicated_gather(self.kv_cache, jnp.asarray(ids))
        return np.ascontiguousarray(self.download_pages(snap)[:, :n])

    def scatter_pages(
        self,
        page_ids: list[int],
        pages: np.ndarray,
        swa: bool = False,
        layers: tuple[int, int] | None = None,
    ) -> None:
        """Stage pages host -> HBM into the given physical page slots
        (``swa`` targets the SWA ring pool; ``layers=(l0, Lg)`` writes
        only that layer slice of the pool — the v3 group-streamed
        import's per-cell write, single-host only).

        Pads the page count up to a bucket by repeating the last (id, value)
        pair — a duplicate scatter of identical values is idempotent — so
        XLA compiles one scatter program per bucket, not per transfer size.

        Thread-safe on the single-host path: the pool read-modify-write
        holds the dispatch lock, so streamed-import fetch threads and the
        engine step thread interleave safely (multi-host already
        serialized through the lockstep dispatch).
        """
        n = len(page_ids)
        if n == 0:
            return
        bucket = pad_to_bucket(n, _buckets(max(self.config.cache.num_blocks, n)))
        ids = np.asarray(page_ids, np.int32)
        if bucket > n:
            ids = np.concatenate([ids, np.full(bucket - n, ids[-1], np.int32)])
            pages = np.concatenate(
                [pages, np.repeat(pages[:, -1:], bucket - n, axis=1)], axis=1
            )
        if self._multihost:
            assert layers is None, "layer-group staging is single-host only"
            # Lockstep scatter: canonical-head values broadcast to every
            # process (one collective), head expansion (and int8-pool
            # quantization) on device. QK slot = pool selector.
            assert dist.is_leader(), "KV staging ops originate on the leader"
            vals = np.ascontiguousarray(
                np.asarray(pages).astype(self.staging_dtype, copy=False)
            )
            with self._dispatch_lock:
                arrays = self._sync_locked(
                    _OP_KV_SCATTER, bucket, int(swa), False,
                    {"ids": ids, "vals_u8": vals.view(np.uint8).reshape(-1)},
                )
                self._exec_kv_scatter(arrays, bucket, swa)
            return
        vals = jnp.asarray(np.asarray(pages), dtype=self.staging_dtype)
        with self._dispatch_lock:
            if layers is not None:
                l0, lg = layers
                out = self._scatter_canonical_group(
                    self._pool(swa),
                    jnp.arange(l0, l0 + lg, dtype=jnp.int32),
                    jnp.asarray(ids),
                    vals,
                )
            else:
                out = self._scatter_canonical(
                    self._pool(swa), jnp.asarray(ids), vals
                )
            if swa:
                self.kv_swa = out
            else:
                self.kv_cache = out

    def scatter_pages_q8(
        self,
        page_ids: list[int],
        q8: np.ndarray,
        scales: np.ndarray,
        swa: bool = False,
    ) -> None:
        """Stage an int8-wire bundle host -> HBM (the symmetric twin of
        the q8 gather): (q8 [L, n, K, page, 2D] i8, scales
        [L, n, K, page, 2] f16) canonical heads. Multi-host broadcasts
        the wire form — HALF the DCN bytes of the canonical
        _OP_KV_SCATTER leg — with head expansion (and for float pools
        the dequant) on every process's device. Same bucket-padding and
        locking discipline as :meth:`scatter_pages`."""
        n = len(page_ids)
        if n == 0:
            return
        bucket = pad_to_bucket(n, _buckets(max(self.config.cache.num_blocks, n)))
        ids = np.asarray(page_ids, np.int32)
        q8 = np.asarray(q8)
        scales = np.asarray(scales)
        if bucket > n:
            ids = np.concatenate([ids, np.full(bucket - n, ids[-1], np.int32)])
            q8 = np.concatenate(
                [q8, np.repeat(q8[:, -1:], bucket - n, axis=1)], axis=1
            )
            scales = np.concatenate(
                [scales, np.repeat(scales[:, -1:], bucket - n, axis=1)], axis=1
            )
        arrays = {
            "ids": ids,
            "q8": np.ascontiguousarray(q8, np.int8),
            "scales": np.ascontiguousarray(scales, np.float16),
        }
        if self._multihost:
            assert dist.is_leader(), "KV staging ops originate on the leader"
            with self._dispatch_lock:
                arrays = self._sync_locked(
                    _OP_KV_SCATTER_Q8, bucket, int(swa), False, arrays
                )
                self._exec_kv_scatter_q8(arrays, swa)
            return
        with self._dispatch_lock:
            self._exec_kv_scatter_q8(arrays, swa)

    # ------------------------------------------------------------------ #

    def run_embed(
        self, prompts: list[list[int]], lora_id: int = 0
    ) -> np.ndarray:
        """Mean-pooled, L2-normalized final hidden states: [n, H] f32.

        The /v1/embeddings surface (OpenAI API; the reference's vllmgrpc
        Embed verb, request-handling.md:50-86). Runs the decoder stack
        over a throwaway KV scratch pool — embeddings never touch the
        serving cache, so this is safe to run concurrently with the step
        loop (params are read-only)."""
        if not prompts:
            return np.zeros((0, self.cfg.hidden_size), np.float32)
        maxlen = max(len(p) for p in prompts)
        limit = min(self.cfg.max_model_len, self.prefill_buckets[-1])
        if maxlen > limit:
            raise ValueError(
                f"embedding input length {maxlen} exceeds the embed limit "
                f"{limit} (min of max_model_len and max_num_batched_tokens)"
            )
        # Requests larger than one device batch run in slices.
        max_b = self.batch_buckets[-1]
        if len(prompts) > max_b:
            return np.concatenate([
                self.run_embed(prompts[i : i + max_b], lora_id)
                for i in range(0, len(prompts), max_b)
            ])
        n = len(prompts)
        Q = pad_to_bucket(maxlen, self.prefill_buckets)
        B = pad_to_bucket(n, self.batch_buckets)
        tokens = np.zeros((B, Q), np.int32)
        positions = np.zeros((B, Q), np.int32)
        qlens = np.zeros(B, np.int32)
        for i, p in enumerate(prompts):
            m = len(p)
            tokens[i, :m] = p
            positions[i, :m] = np.arange(m)
            positions[i, m:] = max(m - 1, 0)
            qlens[i] = m
        arrays = {"tokens": tokens, "positions": positions, "qlens": qlens}
        if self._multihost:
            # A plain SPMD program like any step — broadcast the host
            # inputs (lora_id rides the header's 4th slot) and dispatch
            # on every process; the replicated output is read locally.
            # The lock covers broadcast ordering only; single-host embeds
            # run lock-free so an embed compile never stalls the step
            # loop (params are read-only, scratch is program-internal).
            with self._dispatch_lock:
                arrays = self._sync_locked(_OP_EMBED, B, Q, lora_id, arrays)
                pooled = self._exec_embed(arrays, lora_id)
        else:
            pooled = self._exec_embed(arrays, lora_id)
        return np.asarray(pooled[:n])

    def _exec_embed(self, arrays: dict, lora_id: int) -> jax.Array:
        B, Q = arrays["tokens"].shape
        page = self.page
        pages_per_seq = -(-Q // page)
        # Page table / lora ids derive from (B, Q, lora_id) identically
        # on every process — not broadcast.
        pt = jnp.asarray(
            np.arange(B * pages_per_seq, dtype=np.int32).reshape(
                B, pages_per_seq
            )
        )
        qlens = jnp.asarray(arrays["qlens"])
        inp = StepInput(
            token_ids=jnp.asarray(arrays["tokens"]),
            positions=jnp.asarray(arrays["positions"]),
            query_lens=qlens,
            kv_lens=qlens,
            page_table=pt,
            lora_ids=(
                jnp.full(B, lora_id, jnp.int32)
                if self.cfg.num_lora_adapters
                else None
            ),
            # Embeds are one-shot: the sliding group can use a full-length
            # identity view of its own scratch (no ring needed — the ring
            # is just a table pattern).
            swa_page_table=pt if self.swa is not None else None,
        )
        return self._embed_fn(self.params, inp)

    @functools.cached_property
    def _embed_fn(self):
        cfg, world, mesh = self.cfg, self.ctx.world, self.ctx.mesh
        kv_rep = self.kv_rep
        moe_backend = self.config.parallel.moe_backend if cfg.is_moe else "dense"
        ep_capacity = self.config.parallel.ep_capacity_factor
        ring = self.swa is not None
        data_shape = self._kv_data.shape
        data_dtype = self._kv_data.dtype
        quantized = self.kv_quantized
        page = self.page
        swa = self.swa
        num_layers = self.cfg.num_layers
        replicate = self._replicate_out

        @jax.jit
        def embed(params, inp: StepInput):
            # Scratch pools are created INSIDE the jit (SPMD-consistent
            # on a multi-host mesh; XLA also frees them at program end
            # instead of holding host-side references).
            B, Q = inp.token_ids.shape
            pages_per_seq = -(-Q // page)

            def scratch_pool(n_layers: int):
                shape = (
                    n_layers, B * pages_per_seq, data_shape[2], page,
                    data_shape[4],
                )
                if quantized:
                    return (
                        jnp.zeros(shape, jnp.int8),
                        jnp.ones((*shape[:3], page, 2), jnp.float32),
                    )
                return jnp.zeros(shape, data_dtype)

            if ring:
                scratch_kv = scratch_pool(len(swa.full_layers))
                scratch_swa = scratch_pool(len(swa.swa_layers))
            else:
                scratch_kv = scratch_pool(num_layers)
                scratch_swa = None
            if ring:
                hidden, _, _ = llama.forward_hidden(
                    params, scratch_kv, inp, cfg, world, mesh=mesh,
                    moe_backend=moe_backend, ep_capacity_factor=ep_capacity,
                    kv_rep=kv_rep, kv_swa=scratch_swa,
                )
            else:
                hidden, _ = llama.forward_hidden(
                    params, scratch_kv, inp, cfg, world, mesh=mesh,
                    moe_backend=moe_backend, ep_capacity_factor=ep_capacity,
                    kv_rep=kv_rep,
                )
            valid = inp.valid[..., None].astype(jnp.float32)  # [B, Q, 1]
            summed = jnp.sum(hidden.astype(jnp.float32) * valid, axis=1)
            denom = jnp.maximum(jnp.sum(valid, axis=1), 1.0)
            mean = summed / denom
            out = mean / jnp.maximum(
                jnp.linalg.norm(mean, axis=-1, keepdims=True), 1e-12
            )
            return replicate(out)

        return embed

    def run_prefill(
        self, seqs: list[ScheduledSeq], sync: bool = True
    ) -> StepResult:
        """Dispatch all scheduled prompt chunks and read the tokens back.

        ``sync=False`` is the P/D eager-ACK path: the forward is ENQUEUED
        but the sampled token is never read back (zeros returned). Valid
        only when no caller consumes the tokens — export-only prefills,
        whose response the routing sidecar discards. Device program order
        keeps the subsequently enqueued KV snapshots correct without any
        host synchronization; a forward fault surfaces on the snapshot
        consumers (staging download / consumer scatter) instead of here.
        """
        pending = self.dispatch_prefill(seqs)
        if not sync:
            return StepResult(
                np.zeros((len(seqs), 1), np.int32),
                np.zeros((len(seqs), 1), np.float32),
            )
        res, _ = self.wait_step(pending, None)
        return res

    def dispatch_prefill(self, seqs: list[ScheduledSeq]) -> PendingPrefill:
        """Enqueue all scheduled prompt chunks, batched by Q bucket; no
        host readback (that is ``wait_step``'s single coalesced fetch).

        Rows are grouped so a single long chunk doesn't pad every short
        chunk up to its bucket (padded compute stays ~sum of real tokens,
        not B_bucket x max_chunk).
        """
        groups: dict[int, list[int]] = {}
        for i, s in enumerate(seqs):
            groups.setdefault(
                pad_to_bucket(s.num_tokens, self.prefill_buckets), []
            ).append(i)
        entries = []
        for q_bucket, idxs in sorted(groups.items()):
            packed = self._dispatch_prefill_group(
                [seqs[i] for i in idxs], q_bucket
            )
            entries.append((packed, idxs))
        return PendingPrefill(entries, len(seqs))

    def _dispatch_prefill_group(
        self, seqs: list[ScheduledSeq], Q: int
    ) -> jax.Array:
        n = len(seqs)
        B = pad_to_bucket(n, self.prefill_batch_buckets)
        tokens = np.zeros((B, Q), np.int32)
        positions = np.zeros((B, Q), np.int32)
        qlens = np.zeros(B, np.int32)
        kvlens = np.zeros(B, np.int32)
        for i, s in enumerate(seqs):
            req, start, m = s.request, s.start_pos, s.num_tokens
            tokens[i, :m] = req.all_token_ids[start : start + m]
            positions[i, :m] = np.arange(start, start + m)
            positions[i, m:] = start + max(m - 1, 0)
            qlens[i] = m
            kvlens[i] = start + m
        temp, top_k, top_p, seeds = self._sampling_arrays(seqs, B, 1)
        arrays = {
            "tokens": tokens, "positions": positions, "qlens": qlens,
            "kvlens": kvlens, "page_table": self._page_table(seqs, B),
            "temp": temp, "top_k": top_k, "top_p": top_p,
            "seeds": seeds[:, 0],
        }
        if self.swa is not None:
            arrays["swa_table"] = self._swa_table(seqs, B)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = self._lora_array(seqs, B)
        live = int(qlens.sum())
        self.live_tokens_total += live
        self.padded_tokens_total += B * Q - live
        all_greedy = all(s.request.sampling.greedy for s in seqs)
        with self._dispatch_lock:
            arrays = self._sync_locked(_OP_PREFILL, B, Q, all_greedy, arrays)
            return self._exec_prefill(arrays, all_greedy)

    def run_decode(self, seqs: list[ScheduledSeq], k_steps: int = 1) -> StepResult:
        """K fused decode iterations for the running batch (K=1 = one token)."""
        pending = self.dispatch_decode(seqs, k_steps)
        _, res = self.wait_step(None, pending)
        return res

    def dispatch_decode(
        self, seqs: list[ScheduledSeq], k_steps: int = 1
    ) -> PendingDecode:
        """Stage + enqueue the decode program; no host readback."""
        return self.dispatch_staged_decode(self.stage_decode(seqs, k_steps))

    def stage_decode(
        self, seqs: list[ScheduledSeq], k_steps: int = 1
    ) -> StagedDecode:
        """Build the decode dispatch's host arrays AHEAD of the previous
        step's readback (async stepping overlaps this with device
        execution). The page/ring tables — the O(B x max_pages) cost —
        are final here because the scheduler already allocated every page
        the speculated tokens need; ``first``/``start`` and seeded rows'
        seeds are filled at dispatch, once the tokens they depend on are
        committed."""
        n = len(seqs)
        B = pad_to_bucket(n, self.batch_buckets)
        active = np.zeros(B, np.uint8)
        active[:n] = 1
        # Seeds are NOT drawn here: the stateful rng must be consumed at
        # dispatch time in dispatch order, or async staging (which runs
        # a step early and re-runs on a rollback restage) would shift
        # the draw stream relative to a synchronous engine and break
        # unseeded-sampling parity.
        temp, top_k, top_p = self._sampling_knobs(seqs, B)
        arrays = {
            "first": np.zeros(B, np.int32), "start": np.zeros(B, np.int32),
            "page_table": self._page_table(seqs, B), "active": active,
            "temp": temp, "top_k": top_k, "top_p": top_p,
            "seeds": np.zeros((B, k_steps), np.uint32),
        }
        if self.swa is not None:
            arrays["swa_table"] = self._swa_table(seqs, B)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = self._lora_array(seqs, B)
        all_greedy = all(s.request.sampling.greedy for s in seqs)
        return StagedDecode(list(seqs), arrays, B, k_steps, all_greedy)

    def dispatch_staged_decode(self, staged: StagedDecode) -> PendingDecode:
        """Fill the readback-dependent slots of a staged decode and
        enqueue it. By dispatch time the previous step has committed, so
        ``num_computed_tokens``/``all_token_ids`` hold exactly what a
        synchronous engine would see here — async staging never changes
        the dispatched bytes, only when the host work happened."""
        first = staged.arrays["first"]
        start = staged.arrays["start"]
        # ONE [B, K] rng block per decode dispatch, drawn here so the
        # stateful stream advances in dispatch order (byte-parity with a
        # synchronous engine for unseeded sampling); explicitly seeded
        # rows then overwrite theirs per (request seed, output index).
        seeds = self._np_rng.integers(
            0, 2**32, size=(staged.B, staged.k), dtype=np.uint32
        )
        staged.arrays["seeds"] = seeds
        for i, s in enumerate(staged.seqs):
            req = s.request
            first[i] = req.all_token_ids[req.num_computed_tokens]
            start[i] = req.num_computed_tokens
        self._overwrite_seeded_rows(seeds, staged.seqs, staged.k)
        n = len(staged.seqs)
        self.live_tokens_total += n * staged.k
        self.padded_tokens_total += (staged.B - n) * staged.k
        with self._dispatch_lock:
            arrays = self._sync_locked(
                _OP_DECODE, staged.B, staged.k, staged.all_greedy,
                staged.arrays,
            )
            packed = self._exec_decode(arrays, staged.k, staged.all_greedy)
        return PendingDecode(
            [(packed, list(range(n)), staged.k, 0)], n, staged.k
        )

    def stage_spec_verify(self, seqs: list[ScheduledSeq]) -> StagedVerify:
        """Build the verify dispatch's host arrays AHEAD of the previous
        step's readback (async stepping). The page/ring tables are final
        here — the scheduler already allocated pages for the
        max-acceptance position of every row; tokens/positions/qlens/
        kvlens (which depend on the committed position and the drafts
        proposed from committed history) and seeds are filled at
        dispatch."""
        n = len(seqs)
        # Prefill-style row buckets (powers of two from 1): a mixed step
        # verifies only its drafting rows, often just one or two — padding
        # those up to the decode batch buckets (from 8) would waste more
        # verify columns than the drafts save.
        B = pad_to_bucket(n, self.prefill_batch_buckets)
        Q = self.spec_q
        temp, top_k, top_p = self._sampling_knobs(seqs, B)
        arrays = {
            "tokens": np.zeros((B, Q), np.int32),
            "positions": np.zeros((B, Q), np.int32),
            "qlens": np.zeros(B, np.int32),
            "kvlens": np.zeros(B, np.int32),
            "page_table": self._page_table(seqs, B),
            "temp": temp, "top_k": top_k, "top_p": top_p,
            "seeds": np.zeros((B, Q), np.uint32),
        }
        if self.swa is not None:
            arrays["swa_table"] = self._swa_table(seqs, B)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = self._lora_array(seqs, B)
        all_greedy = all(s.request.sampling.greedy for s in seqs)
        return StagedVerify(list(seqs), arrays, B, Q, all_greedy)

    def dispatch_staged_verify(self, staged: StagedVerify) -> PendingDecode:
        """Fill the readback/draft-dependent slots of a staged verify and
        enqueue it. Each row feeds [next input token, draft...]; pad
        positions repeat the last real position and are masked from KV
        writes by query_lens (the prefill convention), so a short draft
        can never deposit KV past its own columns."""
        tokens = staged.arrays["tokens"]
        positions = staged.arrays["positions"]
        qlens = staged.arrays["qlens"]
        kvlens = staged.arrays["kvlens"]
        # ONE [B, Q] rng block per verify dispatch, drawn in dispatch
        # order (see dispatch_staged_decode's seed-parity note); seeded
        # rows overwrite theirs with the shared per-(request seed,
        # output-index) derivation, which is what makes seeded
        # acceptance exact.
        seeds = self._np_rng.integers(
            0, 2**32, size=(staged.B, staged.q), dtype=np.uint32
        )
        staged.arrays["seeds"] = seeds
        for i, s in enumerate(staged.seqs):
            req = s.request
            nc = req.num_computed_tokens
            draft = s.draft_tokens or []
            m = 1 + len(draft)
            tokens[i, :m] = [req.all_token_ids[nc], *draft]
            tokens[i, m:] = 0
            positions[i, :m] = np.arange(nc, nc + m)
            positions[i, m:] = nc + m - 1
            qlens[i] = m
            kvlens[i] = nc + m
        self._overwrite_seeded_rows(seeds, staged.seqs, staged.q)
        live = int(qlens.sum())
        self.live_tokens_total += live
        self.padded_tokens_total += staged.B * staged.q - live
        with self._dispatch_lock:
            arrays = self._sync_locked(
                _OP_VERIFY, staged.B, staged.q, staged.all_greedy,
                staged.arrays,
            )
            packed = self._exec_verify(arrays, staged.all_greedy)
        n = len(staged.seqs)
        return PendingDecode(
            [(packed, list(range(n)), staged.q, 0)], n, staged.q
        )

    @staticmethod
    def _slice_staged_rows(
        arrays: dict, idxs: list[int], B: int, names: tuple[str, ...]
    ) -> dict:
        """Re-bucket the row-independent staged arrays (page/ring
        tables, sampling knobs, lora slots) for a subset of rows: one
        vectorized gather per array instead of re-walking the requests'
        block lists inside the blocking host region (the async+spec
        mixed-step restage cost this avoids is the dominant part of
        ``step_host_gap_ms`` on mixed traffic)."""
        rows = np.asarray(idxs, np.int64)
        out = {}
        for name in names:
            if name not in arrays:
                continue
            src = arrays[name]
            dst = np.zeros((B, *src.shape[1:]), src.dtype)
            if name == "top_p":
                dst[:] = 1.0  # pad rows keep the neutral knob
            dst[: len(rows)] = src[rows]
            out[name] = dst
        return out

    _ROW_SLICE_NAMES = (
        "page_table", "swa_table", "temp", "top_k", "top_p", "lora",
    )

    def _subset_staged_verify(
        self, staged: StagedVerify, seqs: list[ScheduledSeq],
        idxs: list[int],
    ) -> StagedVerify:
        """Derive a subset StagedVerify from prestaged full-batch verify
        arrays (async+spec mixed steps): the row-independent arrays are
        sliced by the subset index set; the dispatch-filled arrays
        (tokens/positions/qlens/kvlens/seeds) are fresh zeros as
        ``stage_spec_verify`` would build them."""
        n = len(idxs)
        B = pad_to_bucket(n, self.prefill_batch_buckets)
        Q = self.spec_q
        arrays = self._slice_staged_rows(
            staged.arrays, idxs, B, self._ROW_SLICE_NAMES
        )
        arrays.update({
            "tokens": np.zeros((B, Q), np.int32),
            "positions": np.zeros((B, Q), np.int32),
            "qlens": np.zeros(B, np.int32),
            "kvlens": np.zeros(B, np.int32),
            "seeds": np.zeros((B, Q), np.uint32),
        })
        sub = [seqs[i] for i in idxs]
        all_greedy = all(s.request.sampling.greedy for s in sub)
        return StagedVerify(sub, arrays, B, Q, all_greedy)

    def _subset_staged_decode(
        self, staged: StagedVerify | StagedVerifyWindow,
        seqs: list[ScheduledSeq], idxs: list[int], k_steps: int,
    ) -> StagedDecode:
        """Derive a subset StagedDecode from prestaged verify(-window)
        arrays — the degrade path when staged drafting rows turned out
        not to draft at dispatch time."""
        n = len(idxs)
        B = pad_to_bucket(n, self.batch_buckets)
        arrays = self._slice_staged_rows(
            staged.arrays, idxs, B, self._ROW_SLICE_NAMES
        )
        active = np.zeros(B, np.uint8)
        active[:n] = 1
        arrays.update({
            "first": np.zeros(B, np.int32),
            "start": np.zeros(B, np.int32),
            "active": active,
            "seeds": np.zeros((B, k_steps), np.uint32),
        })
        sub = [seqs[i] for i in idxs]
        all_greedy = all(s.request.sampling.greedy for s in sub)
        return StagedDecode(sub, arrays, B, k_steps, all_greedy)

    def degrade_staged_window(
        self, staged: StagedVerifyWindow, k_steps: int
    ) -> StagedDecode:
        """Reuse a prestaged verify window's row-independent arrays as a
        plain fused-decode staging — the degrade path when no staged
        row turned out to draft at dispatch time (fully backed-off
        traffic keeps the window's dispatch amortization without paying
        idle verify columns)."""
        return self._subset_staged_decode(
            staged, staged.seqs, list(range(len(staged.seqs))), k_steps
        )

    def dispatch_spec_split(
        self,
        seqs: list[ScheduledSeq],
        staged: StagedVerify | None = None,
    ) -> PendingDecode:
        """Mixed speculative step: rows that drafted ride the verify
        program, the rest ride the plain one-token decode program — two
        enqueues, still ONE coalesced readback (both packed outputs join
        wait_step's single transfer). Keeps non-drafting rows from
        paying 1 + k verify columns for nothing. ``staged`` reuses the
        async pipeline's prestaged full-batch verify arrays: the
        row-independent page-table/knob rows are SLICED by the subset
        index sets instead of being rebuilt inside the blocking host
        region."""
        drafted = [i for i, s in enumerate(seqs) if s.draft_tokens]
        plain = [i for i, s in enumerate(seqs) if not s.draft_tokens]
        entries: list[tuple[jax.Array, list[int], int, int]] = []
        reuse = (
            staged is not None
            and len(staged.seqs) == len(seqs)
            and all(a is b for a, b in zip(staged.seqs, seqs))
        )
        if reuse:
            sub_v = self._subset_staged_verify(staged, seqs, drafted)
        else:
            sub_v = self.stage_spec_verify([seqs[i] for i in drafted])
        pv = self.dispatch_staged_verify(sub_v)
        entries.append((pv.entries[0][0], drafted, self.spec_q, 0))
        if plain:
            if reuse:
                sub_d = self._subset_staged_decode(staged, seqs, plain, 1)
            else:
                sub_d = self.stage_decode([seqs[i] for i in plain], k_steps=1)
            pd = self.dispatch_staged_decode(sub_d)
            entries.append((pd.entries[0][0], plain, 1, 0))
        return PendingDecode(entries, len(seqs), self.spec_q)

    def stage_unified(
        self, prefills: list[ScheduledSeq], decodes: list[ScheduledSeq]
    ) -> StagedUnified:
        """Build a unified step's host arrays AHEAD of the tokens/drafts
        they depend on (async prestaging). The row structure — prefill
        chunks split into <= ``unified_row_cap`` sub-rows, one row per
        decode seq at its PLANNED width — is fixed by the schedule, so
        the page/ring tables and sampling knobs (the O(rows x max_pages)
        cost) are final here; the packed stream, per-row (start, qlen,
        kind) metadata and seeds fill at dispatch."""
        cap = self.unified_row_cap
        row_seqs: list[ScheduledSeq] = []
        row_off: list[int] = []
        row_plan: list[int] = []
        prefill_rows: list[int] = []
        decode_rows: list[int] = []
        for s in prefills:
            off = 0
            while True:
                w = min(cap, s.num_tokens - off)
                row_seqs.append(s)
                row_off.append(off)
                row_plan.append(w)
                off += w
                if off >= s.num_tokens:
                    break
            prefill_rows.append(len(row_seqs) - 1)
        for s in decodes:
            decode_rows.append(len(row_seqs))
            row_seqs.append(s)
            row_off.append(0)
            row_plan.append(s.num_tokens)
        n = len(row_seqs)
        flat = self._flat is not None
        if flat:
            # Flattened-token staging: the row-metadata width is FIXED
            # (one traced B — metadata is O(rows), a few KB) and the
            # stream buckets over the fine-grained flat T set, so the
            # shape family is the T axis alone.
            B = self.flat_rows
            T = pad_to_bucket(sum(row_plan), self.flat_t_buckets)
        else:
            B = pad_to_bucket(n, self.unified_row_buckets)
            T = pad_to_bucket(sum(row_plan), self.prefill_buckets)
        Q = pad_to_bucket(max(row_plan), self.unified_q_buckets)
        S = self.unified_s
        temp, top_k, top_p = self._sampling_knobs(row_seqs, B)
        arrays = {
            "stream": np.zeros(T, np.int32),
            "row_start": np.zeros(B, np.int32),
            "pos0": np.zeros(B, np.int32),
            "qlens": np.zeros(B, np.int32),
            "kvlens": np.zeros(B, np.int32),
            "kind": np.zeros(B, np.uint8),
            "page_table": self._page_table(row_seqs, B),
            "temp": temp, "top_k": top_k, "top_p": top_p,
            "seeds": np.zeros((B, S), np.uint32),
        }
        if self.swa is not None:
            arrays["swa_table"] = self._swa_table(row_seqs, B)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = self._lora_array(row_seqs, B)
        all_greedy = all(s.request.sampling.greedy for s in row_seqs)
        return StagedUnified(
            list(prefills), list(decodes), row_seqs, row_off, row_plan,
            prefill_rows, decode_rows, arrays, B, Q, T, S, all_greedy,
            flat=flat,
        )

    def dispatch_unified(
        self, prefills: list[ScheduledSeq], decodes: list[ScheduledSeq]
    ) -> PendingUnified:
        """Stage + enqueue the whole window=1 step as ONE program."""
        return self.dispatch_staged_unified(self.stage_unified(prefills, decodes))

    def dispatch_staged_unified(self, staged: StagedUnified) -> PendingUnified:
        """Fill the readback/draft-dependent slots of a staged unified
        step and enqueue it: pack every row's actual tokens into the
        flat stream (prefill sub-rows read their chunk slice; decode
        rows feed [next committed token]; drafting rows feed
        [next, draft...] and become verify-kind rows), then dispatch one
        program. ONE [B, S] rng block per dispatch, drawn here so the
        stateful stream advances in dispatch order; SEEDED rows
        overwrite theirs per (request seed, output index), so column 0
        of a seeded non-verify row equals the split engine's one-sample
        seed exactly — greedy and seeded streams stay byte-identical to
        the split engine. (Unseeded sampled rows draw from a
        differently-shaped rng block than the split dispatches would,
        so hot sampling is reproducible within a mode, not across the
        unified/split switch — the same contract as spec on/off.)"""
        a = staged.arrays
        stream, row_start = a["stream"], a["row_start"]
        pos0, qlens, kvlens = a["pos0"], a["qlens"], a["kvlens"]
        kind = a["kind"]
        a["seeds"] = self._np_rng.integers(
            0, 2**32, size=(staged.B, staged.S), dtype=np.uint32
        )
        n_pre_rows = (
            staged.prefill_rows[-1] + 1 if staged.prefill_rows else 0
        )
        t = 0
        for r, (seq, off, _plan) in enumerate(
            zip(staged.row_seqs, staged.row_off, staged.row_plan)
        ):
            req = seq.request
            if r < n_pre_rows:
                start = seq.start_pos + off
                w = min(staged.row_plan[r], seq.num_tokens - off)
                toks = req.all_token_ids[start : start + w]
                kind[r] = _KIND_PREFILL
            else:
                nc = req.num_computed_tokens
                start = nc
                draft = seq.draft_tokens or []
                if draft:
                    toks = [req.all_token_ids[nc], *draft]
                    kind[r] = _KIND_VERIFY
                else:
                    toks = [req.all_token_ids[nc]]
                    kind[r] = _KIND_DECODE
                w = len(toks)
            stream[t : t + w] = toks
            row_start[r] = t
            pos0[r] = start
            qlens[r] = w
            kvlens[r] = start + w
            t += w
        self._overwrite_seeded_rows(a["seeds"], staged.row_seqs, staged.S)
        self.live_tokens_total += t
        if staged.flat:
            # Pad rows carry row_start = total so the cu_q_lens boundary
            # array the device searchsorts stays monotonic.
            row_start[len(staged.row_seqs):] = t
            self._fill_flat_runs(staged, a)
            self.padded_tokens_total += staged.T - t
            with self._dispatch_lock:
                arrays = self._sync_locked(
                    _OP_FLAT, staged.B, staged.T, staged.all_greedy, a
                )
                packed = self._exec_flat(arrays, staged.all_greedy)
        else:
            self.padded_tokens_total += staged.B * staged.Q - t
            with self._dispatch_lock:
                arrays = self._sync_locked(
                    _OP_UNIFIED, staged.B, (staged.Q << 20) | staged.T,
                    staged.all_greedy, a,
                )
                packed = self._exec_unified(
                    arrays, staged.Q, staged.all_greedy
                )
        return PendingUnified(
            packed, staged.S, list(staged.prefill_rows),
            list(staged.decode_rows), len(staged.prefills),
            len(staged.decodes),
        )

    def _fill_flat_runs(self, staged: StagedUnified, a: dict) -> None:
        """Host half of the flat KV-write plan: walk each row's token
        span page by page and emit one run per (row, physical page) —
        maximal spans of consecutive stream tokens landing in one page,
        so runs target distinct pages (the Pallas write pipeline's
        precondition). ``src`` is pre-shifted (page + t0 - off) so the
        kernel's fixed-size slab DMA lands token t0+j at page row off+j.
        The run width derives from (B, T, page) on both lockstep sides;
        see the _OP_FLAT payload spec for the bound's derivation.
        """
        page = self.page
        rn = 2 * staged.B + -(-staged.T // page)
        wsrc = np.zeros(rn, np.int32)
        woff = np.zeros(rn, np.int32)
        wcnt = np.zeros(rn, np.int32)
        wphys = np.zeros(rn, np.int32)
        pt = a["page_table"]
        st = a.get("swa_table")
        wphys_swa = np.zeros(rn, np.int32) if st is not None else None
        i = 0
        for r in range(len(staged.row_seqs)):
            t0 = int(a["row_start"][r])
            p0 = int(a["pos0"][r])
            w = int(a["qlens"][r])
            consumed = 0
            while consumed < w:
                p = p0 + consumed
                pg, o = p // page, p % page
                take = min(page - o, w - consumed)
                wsrc[i] = page + t0 + consumed - o
                woff[i] = o
                wcnt[i] = take
                wphys[i] = pt[r, pg]
                if wphys_swa is not None:
                    wphys_swa[i] = st[r, pg]
                i += 1
                consumed += take
        assert i <= rn, (i, rn)
        a["wsrc"], a["woff"], a["wcnt"], a["wphys"] = wsrc, woff, wcnt, wphys
        if wphys_swa is not None:
            a["wphys_swa"] = wphys_swa

    def subset_staged_unified(
        self,
        staged: StagedUnified,
        live_p: list[ScheduledSeq],
        live_d: list[ScheduledSeq],
    ) -> StagedUnified:
        """Derive a subset StagedUnified after an async rollback dropped
        rows: the surviving rows' row-independent arrays (page/ring
        tables, knobs, lora slots) are SLICED out of the prestaged
        full-batch arrays via ``_slice_staged_rows`` — one vectorized
        gather each — instead of re-walking the requests' block lists
        inside the blocking host region; the dispatch-filled arrays
        come back as fresh zeros."""
        keep_of: dict[int, list[int]] = {}
        for r, s in enumerate(staged.row_seqs):
            keep_of.setdefault(id(s), []).append(r)
        rows: list[int] = []
        row_seqs: list[ScheduledSeq] = []
        row_off: list[int] = []
        row_plan: list[int] = []
        prefill_rows: list[int] = []
        decode_rows: list[int] = []
        for s in live_p:
            for r in keep_of[id(s)]:
                rows.append(r)
                row_seqs.append(s)
                row_off.append(staged.row_off[r])
                row_plan.append(staged.row_plan[r])
            prefill_rows.append(len(rows) - 1)
        for s in live_d:
            r = keep_of[id(s)][0]
            decode_rows.append(len(rows))
            rows.append(r)
            row_seqs.append(s)
            row_off.append(0)
            row_plan.append(staged.row_plan[r])
        if staged.flat:
            B = self.flat_rows
            T = pad_to_bucket(sum(row_plan), self.flat_t_buckets)
        else:
            B = pad_to_bucket(len(rows), self.unified_row_buckets)
            T = pad_to_bucket(sum(row_plan), self.prefill_buckets)
        Q = pad_to_bucket(max(row_plan), self.unified_q_buckets)
        S = staged.S
        arrays = self._slice_staged_rows(
            staged.arrays, rows, B, self._ROW_SLICE_NAMES
        )
        arrays.update({
            "stream": np.zeros(T, np.int32),
            "row_start": np.zeros(B, np.int32),
            "pos0": np.zeros(B, np.int32),
            "qlens": np.zeros(B, np.int32),
            "kvlens": np.zeros(B, np.int32),
            "kind": np.zeros(B, np.uint8),
            "seeds": np.zeros((B, S), np.uint32),
        })
        all_greedy = all(s.request.sampling.greedy for s in row_seqs)
        return StagedUnified(
            list(live_p), list(live_d), row_seqs, row_off, row_plan,
            prefill_rows, decode_rows, arrays, B, Q, T, S, all_greedy,
            flat=staged.flat,
        )

    def prefill_group_count(self, seqs: list[ScheduledSeq]) -> int:
        """How many Q-bucket programs ``dispatch_prefill`` would enqueue
        for these chunks — the engine's unified-step eligibility probe
        (a single-group prefill-only step is already one dispatch)."""
        return len({
            pad_to_bucket(s.num_tokens, self.prefill_buckets) for s in seqs
        })

    def stage_spec_verify_window(
        self, seqs: list[ScheduledSeq], window: int
    ) -> StagedVerifyWindow:
        """Build the fused verify window's host arrays AHEAD of the
        tokens/drafts they depend on (async stepping). The window
        engages only in the saturated all-decode regime, so rows bucket
        over the DECODE batch buckets; page/ring tables, knobs, the
        active mask and the per-row emission limits (the scheduler's
        planned widths) are final here."""
        n = len(seqs)
        B = pad_to_bucket(n, self.batch_buckets)
        Q = self.spec_q
        temp, top_k, top_p = self._sampling_knobs(seqs, B)
        active = np.zeros(B, np.uint8)
        active[:n] = 1
        limit = np.ones(B, np.int32)
        for i, s in enumerate(seqs):
            limit[i] = s.num_tokens
        arrays = {
            "first": np.zeros(B, np.int32),
            "start": np.zeros(B, np.int32),
            "predraft": np.zeros((B, window * Q - 1), np.int32),
            "dlen": np.zeros(B, np.int32),
            "limit": limit,
            "page_table": self._page_table(seqs, B),
            "active": active,
            "temp": temp, "top_k": top_k, "top_p": top_p,
            "seeds": np.zeros((B, window, Q), np.uint32),
            "seed_base": np.zeros(B, np.uint32),
            "seeded": np.zeros(B, np.uint8),
            "out0": np.zeros(B, np.int32),
        }
        if self.swa is not None:
            arrays["swa_table"] = self._swa_table(seqs, B)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = self._lora_array(seqs, B)
        all_greedy = all(s.request.sampling.greedy for s in seqs)
        return StagedVerifyWindow(list(seqs), arrays, B, window, Q, all_greedy)

    def dispatch_staged_verify_window(
        self, staged: StagedVerifyWindow
    ) -> PendingDecode:
        """Fill the readback/draft-dependent slots of a staged verify
        window and enqueue it. ONE [B, window, Q] rng block per
        dispatch, drawn in dispatch order (the seed-parity rule of
        dispatch_staged_decode); seeded rows are NOT overwritten on
        host — the device derives their per-(seed, output-index) seeds,
        because a row's output indices past the first iteration depend
        on its own on-device acceptance."""
        arrays = staged.arrays
        first, start = arrays["first"], arrays["start"]
        predraft, dlen = arrays["predraft"], arrays["dlen"]
        seed_base, seeded = arrays["seed_base"], arrays["seeded"]
        out0 = arrays["out0"]
        arrays["seeds"] = self._np_rng.integers(
            0, 2**32, size=(staged.B, staged.window, staged.q),
            dtype=np.uint32,
        )
        for i, s in enumerate(staged.seqs):
            req = s.request
            nc = req.num_computed_tokens
            first[i] = req.all_token_ids[nc]
            start[i] = nc
            draft = s.draft_tokens or []
            predraft[i, : len(draft)] = draft
            dlen[i] = len(draft)
            out0[i] = req.total_output_tokens
            sp = req.sampling
            if sp.seed is not None:
                seed_base[i] = np.uint32(sp.seed & 0xFFFFFFFF)
                seeded[i] = 1
        n = len(staged.seqs)
        # Planned widths: actual emission is resolved on device, so the
        # padding gauge charges the pad ROWS only (live rows' idle
        # iterations are the window's own accounting).
        self.live_tokens_total += n * staged.window * staged.q
        self.padded_tokens_total += (
            (staged.B - n) * staged.window * staged.q
        )
        with self._dispatch_lock:
            arrays = self._sync_locked(
                _OP_VERIFY_WINDOW, staged.B, staged.window,
                staged.all_greedy, arrays,
            )
            packed = self._exec_verify_window(
                arrays, staged.window, staged.all_greedy
            )
        wmax = staged.window * staged.q
        return PendingDecode([(packed, list(range(n)), wmax, 4)], n, wmax)

    def wait_step(
        self,
        prefill: PendingPrefill | None,
        decode: PendingDecode | None,
        unified: PendingUnified | None = None,
    ) -> tuple[StepResult | None, StepResult | None]:
        """Block on one engine step's token readback: every dispatched
        program's packed output comes back in a SINGLE coalesced
        transfer (one host round-trip per step, however many prefill
        bucket groups and decode windows the step dispatched — or ONE
        packed array for a unified single-dispatch step, split back into
        prefill/decode results by its row maps)."""
        packs: list[jax.Array] = []
        if prefill is not None:
            packs.extend(p for p, _ in prefill.entries)
        if decode is not None:
            packs.extend(p for p, _, _, _ in decode.entries)
        if unified is not None:
            packs.append(unified.packed)
        if not packs:
            return None, None
        if dist.is_multihost():
            hosts = [dist.replicated_to_host(p) for p in packs]
        else:
            hosts = [np.asarray(a) for a in jax.device_get(packs)]
        pres = dres = None
        base = 0
        if prefill is not None:
            tokens = np.zeros((prefill.n, 1), np.int32)
            logprobs = np.zeros((prefill.n, 1), np.float32)
            for gi, (_, idxs) in enumerate(prefill.entries):
                arr = hosts[gi]
                for row, i in enumerate(idxs):
                    tokens[i] = arr[row, :1].astype(np.int32)
                    logprobs[i] = arr[row, 1:2]
            pres = StepResult(tokens, logprobs)
            base = len(prefill.entries)
        if decode is not None:
            K = decode.k
            tokens = np.zeros((decode.n, K), np.int32)
            logprobs = np.zeros((decode.n, K), np.float32)
            meta = None
            for gi, (_, idxs, k, mc) in enumerate(decode.entries):
                arr = hosts[base + gi]
                m = len(idxs)
                if mc:
                    # Fused verify window: leading meta columns carry
                    # the device-resolved acceptance per row.
                    if meta is None:
                        meta = np.zeros((decode.n, mc), np.int32)
                    meta[np.asarray(idxs, np.int64)] = arr[:m, :mc].astype(
                        np.int32
                    )
                if idxs == list(range(decode.n)):
                    # Single whole-batch entry (the common, spec-off
                    # case): one vectorized block copy.
                    tokens[:, :k] = arr[:m, mc : mc + k].astype(np.int32)
                    logprobs[:, :k] = arr[:m, mc + k : mc + 2 * k]
                else:
                    rows = np.asarray(idxs, np.int64)
                    tokens[rows, :k] = arr[:m, mc : mc + k].astype(np.int32)
                    logprobs[rows, :k] = arr[:m, mc + k : mc + 2 * k]
            dres = StepResult(tokens, logprobs, meta)
        if unified is not None:
            arr = hosts[-1]
            S = unified.S
            if unified.n_prefills:
                # A prefill seq's first-token sample sits in column 0 of
                # its LAST sub-row (every sample column of a non-verify
                # row is the last-position sample).
                rows = np.asarray(unified.prefill_rows, np.int64)
                pres = StepResult(
                    arr[rows, :1].astype(np.int32), arr[rows, S : S + 1]
                )
            if unified.n_decodes:
                rows = np.asarray(unified.decode_rows, np.int64)
                dres = StepResult(
                    arr[rows, :S].astype(np.int32), arr[rows, S : 2 * S]
                )
        return pres, dres

    # ------------------------------------------------------------------ #

    def warmup(
        self,
        prefill_shapes: list[tuple[int, int]] | None = None,
        decode_shapes: list[tuple[int, int]] | None = None,
    ) -> int:
        """Precompile the (bucketed) shapes the scheduler will produce.

        The reference faces the same startup-compile problem on TPU
        (SKIP_JAX_PRECOMPILE + 240x30s startup probes, SURVEY.md 3.4); here
        warmup is explicit. Defaults compile the largest prefill shape and
        the largest decode batch at windows {1, decode_window}. Returns the
        number of programs compiled.
        """
        sched = self.config.scheduler
        flat = self._flat is not None
        if prefill_shapes is None:
            # With the flattened step on, EVERY window=1 step kind —
            # prefill-only, pure-decode, mixed, one-shot verify — rides
            # the ONE flat program, so the split prefill/verify families
            # are reachable only through the P/D eager-ACK producer path
            # (which keeps its own dispatch) and explicit API calls:
            # warm them only where a producer role makes them hot.
            if flat and not self.config.kv_role:
                prefill_shapes = []
            else:
                # The lone-prefill shape (B=1) is the P/D TTFT-critical
                # one; compile it alongside the largest so the first
                # single request never eats a compile.
                prefill_shapes = [
                    (self.prefill_batch_buckets[-1], self.prefill_buckets[-1])
                ]
                if self.prefill_batch_buckets[0] == 1:
                    prefill_shapes.append((1, self.prefill_buckets[-1]))
        if decode_shapes is None:
            decode_shapes = [
                (self.batch_buckets[-1], k) for k in self.decode_windows
            ]
            if flat and len(self.decode_windows) == 1:
                # Window=1 decode steps ride the flat program; the plain
                # decode family stays reachable only via explicit
                # run_decode calls and the windowed degrade paths, which
                # this engine (decode_windows == {1}) never takes.
                decode_shapes = []
        count = 0
        for B, Q in prefill_shapes:
            for greedy in (True, False):
                self._warm_prefill(B, Q, greedy)
                count += 1
        for B, K in decode_shapes:
            for greedy in (True, False):
                self._warm_decode(B, K, greedy)
                count += 1
        if self.spec_q and not flat:
            # The speculative verify family: one Q (= 1 + spec_ngram_k)
            # at the largest row bucket plus the lone-row shape (mixed
            # steps often verify a single drafting row). The flat engine
            # verifies inside the flat program instead.
            for B in {1, self.prefill_batch_buckets[-1]}:
                for greedy in (True, False):
                    self._warm_verify(B, greedy)
                    count += 1
        # The fused verify-window family: the scheduler's adaptive pick
        # stays within spec_windows (SchedulerConfig.spec_window_set),
        # so compiling exactly that set at the largest decode batch
        # keeps the budget-driven degrade from eating a runtime compile.
        for w in self.spec_windows:
            for greedy in (True, False):
                self._warm_verify_window(self.batch_buckets[-1], w, greedy)
                count += 1
        if flat:
            # The flat family's one shape axis is T: warm the largest
            # stream bucket (the saturated-step shape).
            for greedy in (True, False):
                self._warm_flat(self.flat_t_buckets[-1], greedy)
                count += 1
        elif self._unified is not None:
            # The unified mixed-step family at its largest row/column/
            # stream buckets — the shape a saturated mixed step lands on.
            for greedy in (True, False):
                self._warm_unified(
                    self.unified_row_buckets[-1],
                    self.unified_q_buckets[-1],
                    self.prefill_buckets[-1],
                    greedy,
                )
                count += 1
        return count

    def window1_shape_families(self) -> int:
        """Distinct (program, shape-bucket) combinations the engine can
        dispatch for WINDOW=1 step kinds — prefill chunks, plain decode,
        one-shot verify, mixed — i.e. the compile surface warmup and
        serving draw from. The flattened-token step collapses the
        bucketed (rows x Q x T) unified cross-product plus the split
        prefill/verify families to the flat T axis alone."""
        if self._flat is not None:
            return len(self.flat_t_buckets)
        n = len(self.prefill_batch_buckets) * len(self.prefill_buckets)
        n += len(self.batch_buckets)  # plain decode at window 1
        if self.spec_q:
            n += len(self.prefill_batch_buckets)  # one-shot verify rows
        if self._unified is not None:
            n += (
                len(self.unified_row_buckets)
                * len(self.unified_q_buckets)
                * len(self.prefill_buckets)
            )
        return n

    def _warm_flat(self, T: int, all_greedy: bool = False) -> None:
        B = self.flat_rows
        rn = 2 * B + -(-T // self.page)
        arrays = {
            "stream": np.zeros(T, np.int32),
            "row_start": np.zeros(B, np.int32),
            "pos0": np.zeros(B, np.int32),
            "qlens": np.zeros(B, np.int32),
            "kvlens": np.zeros(B, np.int32),
            "kind": np.zeros(B, np.uint8),
            "page_table": np.zeros((B, self.max_pages), np.int32),
            "temp": np.zeros(B, np.float32),
            "top_k": np.zeros(B, np.int32),
            "top_p": np.ones(B, np.float32),
            "seeds": np.zeros((B, self.unified_s), np.uint32),
            "wsrc": np.zeros(rn, np.int32),
            "woff": np.zeros(rn, np.int32),
            "wcnt": np.zeros(rn, np.int32),
            "wphys": np.zeros(rn, np.int32),
        }
        if self.swa is not None:
            arrays["swa_table"] = np.zeros((B, self.max_pages), np.int32)
            arrays["wphys_swa"] = np.zeros(rn, np.int32)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = np.zeros(B, np.int32)
        with self._dispatch_lock:
            arrays = self._sync_locked(_OP_FLAT, B, T, all_greedy, arrays)
            self._exec_flat(arrays, all_greedy)

    def _warm_unified(
        self, B: int, Q: int, T: int, all_greedy: bool = False
    ) -> None:
        arrays = {
            "stream": np.zeros(T, np.int32),
            "row_start": np.zeros(B, np.int32),
            "pos0": np.zeros(B, np.int32),
            "qlens": np.zeros(B, np.int32),
            "kvlens": np.zeros(B, np.int32),
            "kind": np.zeros(B, np.uint8),
            "page_table": np.zeros((B, self.max_pages), np.int32),
            "temp": np.zeros(B, np.float32),
            "top_k": np.zeros(B, np.int32),
            "top_p": np.ones(B, np.float32),
            "seeds": np.zeros((B, self.unified_s), np.uint32),
        }
        if self.swa is not None:
            arrays["swa_table"] = np.zeros((B, self.max_pages), np.int32)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = np.zeros(B, np.int32)
        with self._dispatch_lock:
            arrays = self._sync_locked(
                _OP_UNIFIED, B, (Q << 20) | T, all_greedy, arrays
            )
            self._exec_unified(arrays, Q, all_greedy)

    def _warm_prefill(self, B: int, Q: int, all_greedy: bool = False) -> None:
        arrays = {
            "tokens": np.zeros((B, Q), np.int32),
            "positions": np.zeros((B, Q), np.int32),
            "qlens": np.zeros(B, np.int32),
            "kvlens": np.zeros(B, np.int32),
            "page_table": np.zeros((B, self.max_pages), np.int32),
            "temp": np.zeros(B, np.float32),
            "top_k": np.zeros(B, np.int32),
            "top_p": np.ones(B, np.float32),
            "seeds": np.zeros(B, np.uint32),
        }
        if self.swa is not None:
            arrays["swa_table"] = np.zeros((B, self.max_pages), np.int32)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = np.zeros(B, np.int32)
        with self._dispatch_lock:
            arrays = self._sync_locked(_OP_PREFILL, B, Q, all_greedy, arrays)
            self._exec_prefill(arrays, all_greedy)

    def _warm_verify(self, B: int, all_greedy: bool = False) -> None:
        Q = self.spec_q
        arrays = {
            "tokens": np.zeros((B, Q), np.int32),
            "positions": np.zeros((B, Q), np.int32),
            "qlens": np.zeros(B, np.int32),
            "kvlens": np.zeros(B, np.int32),
            "page_table": np.zeros((B, self.max_pages), np.int32),
            "temp": np.zeros(B, np.float32),
            "top_k": np.zeros(B, np.int32),
            "top_p": np.ones(B, np.float32),
            "seeds": np.zeros((B, Q), np.uint32),
        }
        if self.swa is not None:
            arrays["swa_table"] = np.zeros((B, self.max_pages), np.int32)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = np.zeros(B, np.int32)
        with self._dispatch_lock:
            arrays = self._sync_locked(_OP_VERIFY, B, Q, all_greedy, arrays)
            self._exec_verify(arrays, all_greedy)

    def _warm_verify_window(
        self, B: int, window: int, all_greedy: bool = False
    ) -> None:
        Q = self.spec_q
        arrays = {
            "first": np.zeros(B, np.int32),
            "start": np.zeros(B, np.int32),
            "predraft": np.zeros((B, window * Q - 1), np.int32),
            "dlen": np.zeros(B, np.int32),
            "limit": np.ones(B, np.int32),
            "page_table": np.zeros((B, self.max_pages), np.int32),
            "active": np.zeros(B, np.uint8),
            "temp": np.zeros(B, np.float32),
            "top_k": np.zeros(B, np.int32),
            "top_p": np.ones(B, np.float32),
            "seeds": np.zeros((B, window, Q), np.uint32),
            "seed_base": np.zeros(B, np.uint32),
            "seeded": np.zeros(B, np.uint8),
            "out0": np.zeros(B, np.int32),
        }
        if self.swa is not None:
            arrays["swa_table"] = np.zeros((B, self.max_pages), np.int32)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = np.zeros(B, np.int32)
        with self._dispatch_lock:
            arrays = self._sync_locked(_OP_VERIFY_WINDOW, B, window, all_greedy, arrays)
            self._exec_verify_window(arrays, window, all_greedy)

    def _warm_decode(self, B: int, K: int, all_greedy: bool = False) -> None:
        arrays = {
            "first": np.zeros(B, np.int32),
            "start": np.zeros(B, np.int32),
            "page_table": np.zeros((B, self.max_pages), np.int32),
            "active": np.zeros(B, np.uint8),
            "temp": np.zeros(B, np.float32),
            "top_k": np.zeros(B, np.int32),
            "top_p": np.ones(B, np.float32),
            "seeds": np.zeros((B, K), np.uint32),
        }
        if self.swa is not None:
            arrays["swa_table"] = np.zeros((B, self.max_pages), np.int32)
        if self.cfg.num_lora_adapters:
            arrays["lora"] = np.zeros(B, np.int32)
        with self._dispatch_lock:
            arrays = self._sync_locked(_OP_DECODE, B, K, all_greedy, arrays)
            self._exec_decode(arrays, K, all_greedy)
