"""Request and sequence state for the continuous-batching engine.

Mirrors the request lifecycle of the reference's model server layer
(docs/architecture/core/model-servers.md:3-25): a request arrives with a
prompt and sampling parameters, is queued, scheduled incrementally
(chunked prefill), then decoded one token per engine step until a stop
condition, streaming tokens out as they are produced.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 16
    temperature: float = 1.0
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0
    stop_token_ids: tuple[int, ...] = ()
    ignore_eos: bool = False
    seed: int | None = None
    logprobs: bool = False

    @property
    def greedy(self) -> bool:
        return self.temperature <= 1e-5


class FinishReason(str, enum.Enum):
    STOP = "stop"          # hit EOS / stop token
    LENGTH = "length"      # hit max_tokens or max_model_len
    ABORT = "abort"        # client disconnect / cancelled


class RequestStatus(enum.Enum):
    WAITING = enum.auto()
    RUNNING = enum.auto()
    PREEMPTED = enum.auto()
    FINISHED = enum.auto()


class PriorityClass(enum.IntEnum):
    """Serving bands on the ONE continuous batch.

    ``priority`` stays a free integer (higher schedules first, FCFS
    within a value); the class boundary is the contract: any request at
    or below ``BATCH`` rides the offline backfill band — it only
    consumes token-budget/page headroom interactive rows left unused
    this step, never displaces an interactive admission, and is the
    first recompute-preemption victim the moment interactive load
    returns (docs/architecture/batch-processing.md). The serving layer
    maps the ``x-llmd-priority: batch`` header here; the EPP's
    batch-saturation-filter keys on the same boundary
    (llmd_tpu.epp.types.BATCH_PRIORITY — kept numerically identical,
    pinned by test)."""

    INTERACTIVE = 0
    BATCH = -100


@dataclasses.dataclass
class Request:
    """One inflight sequence.

    ``num_computed_tokens`` tracks how much of the prompt has been prefilled
    (chunked prefill advances it in steps); once it reaches
    ``len(prompt_token_ids)`` the sequence enters decode.
    """

    request_id: str
    prompt_token_ids: list[int]
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    arrival_time: float = dataclasses.field(default_factory=time.monotonic)
    priority: int = 0
    # Opaque KV-transfer params injected by the P/D routing sidecar
    # (reference disaggregation/README.md:104-131); interpreted by the
    # kvtransfer connector, not the engine core.
    kv_transfer_params: dict[str, Any] | None = None
    # LoRA adapter slot (0 = base model); set by the serving layer from
    # the requested model name. The adapter NAME rides lora_name for the
    # lora_requests_info metric.
    lora_id: int = 0
    lora_name: str = ""

    # --- mutable state ---
    status: RequestStatus = RequestStatus.WAITING
    output_token_ids: list[int] = dataclasses.field(default_factory=list)
    num_computed_tokens: int = 0
    # Physical page ids allocated to this sequence, in order. The
    # request is an ownership root for its pages: the scheduler's
    # _release/_truncate paths free from here (static-analysis.md).
    block_ids: list[int] = dataclasses.field(default_factory=list)  # llmd: owns(pages)
    # Ring pages for sliding-window layers (CacheConfig.swa_ring): a fixed
    # list of R pages from the ring pool, reused circularly — logical page
    # l of this sequence lives at swa_block_ids[l % R] on sliding layers.
    swa_block_ids: list[int] = dataclasses.field(default_factory=list)  # llmd: owns(pages)
    # Memoized [max_pages] ring-view table row (immutable once the ring is
    # allocated; invalidated whenever swa_block_ids is freed).
    swa_table_row: Any = None
    # Tokens dispatched to the device but not yet committed by a step
    # readback (async stepping, SchedulerConfig.async_scheduling): the
    # scheduler speculates the next batch against dispatched positions
    # while the in-flight step executes. Always 0 in synchronous mode
    # and between reconcile and the next dispatch.
    num_pending_tokens: int = 0
    # Number of prompt tokens satisfied from the prefix cache (skipped compute).
    num_cached_tokens: int = 0
    # Decode-time KV paging (OffloadConfig.decode_paging): logical page
    # index -> content hash of pages whose HBM copy was released to the
    # host tier. A stale physical id may linger in block_ids at these
    # indexes — every attention read below the sliding window is masked,
    # and _release skips them when freeing.
    paged_out: dict[int, bytes] = dataclasses.field(default_factory=dict)
    # Parked by the pager: committed KV lives in the host tier and the
    # scheduler must not re-admit this request until the pager has
    # streamed the attention window back into freshly allocated pages
    # (fetch-pending is a wait state, not a fault).
    kv_fetch_pending: bool = False
    # Outputs generated before a recompute-preemption folded them into the
    # prompt; counts toward max_tokens and reported output length.
    num_prior_output_tokens: int = 0
    # Speculative decoding accounting (SchedulerConfig.speculative_ngram):
    # draft tokens proposed for / accepted by this request across its
    # verify steps. Purely observational — acceptance itself lives in the
    # scheduler's update loop.
    spec_drafted_tokens: int = 0
    spec_accepted_tokens: int = 0
    # Draft backoff state: consecutive fully-rejected drafts. The
    # scheduler gates drafting eligibility on this against a GLOBAL
    # step clock (scheduler.spec_step), so backed-off rows retry on the
    # same aligned steps instead of smearing one drafting row across
    # every step — low-repetition traffic then runs almost every step as
    # a plain decode. Never affects WHAT is emitted (acceptance is exact
    # either way), only whether a draft is attempted — parity untouched.
    spec_consec_rejected: int = 0
    # Incremental n-gram index over all_token_ids (NgramProposer state;
    # valid across preemption because recompute folds output into the
    # prompt without changing the token sequence).
    spec_gram_state: Any = None
    finish_reason: FinishReason | None = None
    first_token_time: float | None = None
    finish_time: float | None = None
    # Per-step sampled logprob of each output token (if requested).
    output_logprobs: list[float] = dataclasses.field(default_factory=list)
    # KV-transfer params produced at finish by a kv_producer engine
    # (set by the connector's finish hook; echoed in RequestOutput).
    export_params: dict[str, Any] | None = None

    @property
    def is_batch(self) -> bool:
        """True when this request rides the offline backfill band."""
        return self.priority <= PriorityClass.BATCH

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_token_ids) + len(self.output_token_ids)

    @property
    def total_output_tokens(self) -> int:
        return self.num_prior_output_tokens + len(self.output_token_ids)

    @property
    def all_token_ids(self) -> list[int]:
        return self.prompt_token_ids + self.output_token_ids

    @property
    def in_decode(self) -> bool:
        return self.num_computed_tokens >= self.num_prompt_tokens

    @property
    def num_dispatched_tokens(self) -> int:
        """Committed + in-flight position: what the KV/pages will hold
        once the dispatched step lands. The scheduler plans against THIS
        (== num_computed_tokens whenever nothing is in flight)."""
        return self.num_computed_tokens + self.num_pending_tokens

    @property
    def in_decode_dispatched(self) -> bool:
        """in_decode once the in-flight step lands (async speculation:
        a prompt-completing chunk in flight makes the seq decode-ready
        for the next staged batch)."""
        return self.num_dispatched_tokens >= self.num_prompt_tokens

    @property
    def is_finished(self) -> bool:
        return self.status is RequestStatus.FINISHED

    def finish(self, reason: FinishReason) -> None:
        self.status = RequestStatus.FINISHED
        self.finish_reason = reason
        self.finish_time = time.monotonic()


@dataclasses.dataclass
class RequestOutput:
    """Incremental output for one request after an engine step."""

    request_id: str
    new_token_ids: list[int]
    finished: bool
    finish_reason: FinishReason | None
    num_prompt_tokens: int
    num_output_tokens: int
    num_cached_tokens: int = 0
    kv_transfer_params: dict[str, Any] | None = None
