"""llmd-tpu: a TPU-native distributed LLM inference serving framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the llm-d
serving stack (reference: /root/reference, an umbrella repo whose component
specs live in docs/architecture/**):

- Engine: continuous-batching JAX model server with a paged KV cache held as
  jax.Arrays, Pallas ragged paged attention, automatic prefix caching, and
  pjit/shard_map parallelism over a TPU device mesh (TP/DP/EP).
- EPP (endpoint picker): Filter->Score->Pick request scheduling, data layer,
  flow control, precise KV-cache indexing -- the accelerator-agnostic control
  plane, re-implemented natively (reference spec:
  docs/architecture/core/router/epp/README.md).
- KV transfer: ICI/DCN jax.Array KV shipper replacing NIXL
  (reference spec: docs/architecture/advanced/disaggregation/operations-vllm.md).

Package layout:
  engine/    continuous batching, paged KV cache, sampling, model runner
  models/    model families (Llama/Qwen dense, Mixtral/DeepSeek MoE)
  ops/       Pallas TPU kernels + XLA fallbacks
  parallel/  mesh construction, shardings, EP all-to-all
  server/    OpenAI-compatible HTTP serving + metrics protocol
  epp/       endpoint picker: scheduler, data layer, flow control, kv index
  router/    standalone router proxy + P/D routing sidecar
  kvtransfer/ P<->D KV-cache shipper (side channel, leases, pull model)
  utils/     shared helpers
"""

__version__ = "0.1.0"
