"""Observability: distributed tracing, dashboards, alert rules.

Reference surface: docs/operations/observability/tracing.md:14-102 (OTel
OTLP tracing across engine, sidecar, and EPP with
parentbased_traceidratio sampling, default ratio 0.1) and
proposals/distributed-tracing.md:60-111 (cache-hit attribution, P/D
decision intelligence, bottleneck identification). The environment ships
only the OTel *API*, so spans are produced by a lightweight in-house
tracer speaking the OTLP/HTTP JSON encoding, with file and in-memory
exporters for no-collector deployments and tests.
"""

from llmd_tpu.obs.tracing import (
    FileExporter,
    InMemoryExporter,
    OtlpHttpExporter,
    Span,
    Tracer,
    configure_tracing,
    get_tracer,
    parse_traceparent,
)

__all__ = [
    "FileExporter",
    "InMemoryExporter",
    "OtlpHttpExporter",
    "Span",
    "Tracer",
    "configure_tracing",
    "get_tracer",
    "parse_traceparent",
]
