"""Distributed tracing: W3C context propagation + OTLP/HTTP JSON export.

Semantics follow the reference tracing stack
(docs/operations/observability/tracing.md): every hop (router -> sidecar
-> engine) continues the incoming `traceparent`, sampling is
parent-based trace-id-ratio (default 0.1, reference
recipes/router/base.values.yaml:51-56), and spans carry the attributes
the design doc calls out (proposals/distributed-tracing.md:60-111):
cache-hit attribution (`llm_d.cache.hit_tokens`), P/D decision
(`llm_d.decision.prefill`), and per-phase timings for bottleneck ID.

The tracer is a no-op until `configure_tracing` is called, so the hot
path costs one attribute lookup when tracing is off.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import secrets
import threading
import time
import urllib.request

log = logging.getLogger(__name__)

_W3C_VERSION = "00"
FLAG_SAMPLED = 0x01


def _now_ns() -> int:
    return time.time_ns()


def parse_traceparent(value: str | None) -> tuple[str, str, int] | None:
    """traceparent -> (trace_id_hex32, parent_span_id_hex16, flags)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        flags = int(parts[3], 16)
    except ValueError:
        return None
    if parts[1] == "0" * 32 or parts[2] == "0" * 16:
        return None
    return parts[1], parts[2], flags


def format_traceparent(trace_id: str, span_id: str, sampled: bool) -> str:
    flags = FLAG_SAMPLED if sampled else 0
    return f"{_W3C_VERSION}-{trace_id}-{span_id}-{flags:02x}"


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "attributes", "events", "status_ok", "sampled", "_tracer", "kind",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: str | None,
        sampled: bool,
        kind: str = "SPAN_KIND_INTERNAL",
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.kind = kind
        self.start_ns = _now_ns()
        self.end_ns = 0
        self.attributes: dict = {}
        self.events: list[tuple[int, str, dict]] = []
        self.status_ok = True

    def set(self, key: str, value) -> "Span":
        if self.sampled:
            self.attributes[key] = value
        return self

    def event(self, name: str, **attrs) -> None:
        if self.sampled:
            self.events.append((_now_ns(), name, attrs))

    def error(self, message: str = "") -> None:
        self.status_ok = False
        if message and self.sampled:
            self.attributes["error.message"] = message

    def end(self) -> None:
        self.end_ns = _now_ns()
        if self.sampled and self._tracer is not None:
            self._tracer._export(self)

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id, self.sampled)

    # OTLP/JSON encoding (the /v1/traces HTTP payload item).
    def to_otlp(self) -> dict:
        def _attr(k, v):
            if isinstance(v, bool):
                val = {"boolValue": v}
            elif isinstance(v, int):
                val = {"intValue": str(v)}
            elif isinstance(v, float):
                val = {"doubleValue": v}
            else:
                val = {"stringValue": str(v)}
            return {"key": k, "value": val}

        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            **({"parentSpanId": self.parent_id} if self.parent_id else {}),
            "name": self.name,
            "kind": self.kind,
            "startTimeUnixNano": str(self.start_ns),
            "endTimeUnixNano": str(self.end_ns or _now_ns()),
            "attributes": [_attr(k, v) for k, v in self.attributes.items()],
            "events": [
                {
                    "timeUnixNano": str(ts),
                    "name": name,
                    "attributes": [_attr(k, v) for k, v in attrs.items()],
                }
                for ts, name, attrs in self.events
            ],
            "status": {"code": "STATUS_CODE_OK" if self.status_ok else "STATUS_CODE_ERROR"},
        }


class _NoopSpan:
    __slots__ = ()
    sampled = False
    trace_id = "0" * 32
    span_id = "0" * 16
    traceparent = ""

    def set(self, key, value):
        return self

    def event(self, name, **attrs):
        pass

    def error(self, message=""):
        pass

    def end(self):
        pass


NOOP_SPAN = _NoopSpan()


class InMemoryExporter:
    def __init__(self) -> None:
        self.spans: list[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)

    def close(self) -> None:
        pass


class FileExporter:
    """JSONL span log — grep-able tracing without a collector."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_otlp(), separators=(",", ":"))
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")

    def close(self) -> None:
        pass


class OtlpHttpExporter:
    """Batched OTLP/HTTP JSON exporter (collector :4318/v1/traces).

    Export happens on a background thread so span.end() never blocks the
    event loop; batches flush every `flush_s` or `max_batch` spans.
    """

    def __init__(
        self,
        endpoint: str,
        service_name: str,
        flush_s: float = 2.0,
        max_batch: int = 256,
        timeout_s: float = 5.0,
    ) -> None:
        self.endpoint = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.flush_s = flush_s
        self.max_batch = max_batch
        self.timeout_s = timeout_s
        self._buf: list[dict] = []  # llmd: guarded_by(_lock)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def export(self, span: Span) -> None:
        with self._lock:
            self._buf.append(span.to_otlp())
            if len(self._buf) > self.max_batch * 4:
                # collector down: drop oldest rather than grow unbounded
                del self._buf[: self.max_batch]

    def _payload(self, spans: list[dict]) -> bytes:
        return json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {
                            "attributes": [
                                {
                                    "key": "service.name",
                                    "value": {"stringValue": self.service_name},
                                }
                            ]
                        },
                        "scopeSpans": [
                            {"scope": {"name": "llmd-tpu"}, "spans": spans}
                        ],
                    }
                ]
            }
        ).encode()

    def _flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf[: self.max_batch], self._buf[self.max_batch:]
        if not batch:
            return
        try:
            req = urllib.request.Request(
                self.endpoint,
                data=self._payload(batch),
                headers={"content-type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=self.timeout_s).close()
        except Exception as e:
            log.debug("OTLP export failed: %s", e)

    def _run(self) -> None:
        while not self._stop.wait(self.flush_s):
            self._flush()
        self._flush()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.timeout_s + 1)


class Tracer:
    def __init__(
        self,
        service_name: str,
        exporter,
        sample_ratio: float = 0.1,
    ) -> None:
        self.service_name = service_name
        self.exporter = exporter
        self.sample_ratio = max(0.0, min(1.0, sample_ratio))
        # trace-id-ratio threshold over the low 8 bytes of the trace id
        self._threshold = int(self.sample_ratio * (1 << 64))

    # parent-based trace-id-ratio sampling (the reference default
    # `parentbased_traceidratio`): honor the parent's decision; root spans
    # sample by trace-id hash ratio.
    def _sample(self, trace_id: str, parent_flags: int | None) -> bool:
        if parent_flags is not None:
            return bool(parent_flags & FLAG_SAMPLED)
        return int(trace_id[16:], 16) < self._threshold

    def start_span(
        self,
        name: str,
        traceparent: str | None = None,
        parent: Span | None = None,
        kind: str = "SPAN_KIND_INTERNAL",
    ) -> Span:
        if parent is not None and not isinstance(parent, _NoopSpan):
            trace_id, parent_id = parent.trace_id, parent.span_id
            sampled = parent.sampled
        else:
            ctx = parse_traceparent(traceparent)
            if ctx is not None:
                trace_id, parent_id, flags = ctx
                sampled = self._sample(trace_id, flags)
            else:
                trace_id = secrets.token_hex(16)
                parent_id = None
                sampled = self._sample(trace_id, None)
        if not sampled:
            return NOOP_SPAN  # type: ignore[return-value]
        return Span(self, name, trace_id, secrets.token_hex(8), parent_id, True, kind)

    @contextlib.contextmanager
    def span(self, name: str, traceparent: str | None = None, parent=None, **attrs):
        s = self.start_span(name, traceparent, parent)
        for k, v in attrs.items():
            s.set(k, v)
        try:
            yield s
        except BaseException as e:
            s.error(str(e))
            raise
        finally:
            s.end()

    def _export(self, span: Span) -> None:
        try:
            self.exporter.export(span)
        except Exception:
            log.exception("span export failed")

    def close(self) -> None:
        self.exporter.close()


class _NoopTracer:
    sample_ratio = 0.0

    def start_span(self, name, traceparent=None, parent=None, kind=""):
        return NOOP_SPAN

    @contextlib.contextmanager
    def span(self, name, traceparent=None, parent=None, **attrs):
        yield NOOP_SPAN

    def close(self) -> None:
        pass


NOOP_TRACER = _NoopTracer()
_global_tracer = NOOP_TRACER


def configure_tracing(
    service_name: str,
    otlp_endpoint: str | None = None,
    trace_file: str | None = None,
    sample_ratio: float = 0.1,
    exporter=None,
) -> Tracer:
    """Install the process-global tracer. Exporter precedence: explicit >
    OTLP endpoint > file > env (`LLMD_OTLP_ENDPOINT`, `LLMD_TRACE_FILE`)."""
    global _global_tracer
    if exporter is None:
        otlp_endpoint = otlp_endpoint or os.environ.get("LLMD_OTLP_ENDPOINT")
        trace_file = trace_file or os.environ.get("LLMD_TRACE_FILE")
        if otlp_endpoint:
            exporter = OtlpHttpExporter(otlp_endpoint, service_name)
        elif trace_file:
            exporter = FileExporter(trace_file)
        else:
            exporter = InMemoryExporter()
    tracer = Tracer(service_name, exporter, sample_ratio)
    _global_tracer = tracer
    return tracer


def get_tracer():
    return _global_tracer


def reset_tracing() -> None:
    global _global_tracer
    try:
        _global_tracer.close()
    finally:
        _global_tracer = NOOP_TRACER
