"""CLI: ``python -m llmd_tpu.analysis [paths...] [--json] [--rules ...]``.

Exit codes: 0 clean, 1 findings, 2 usage error. With no paths the scan
set is the llmd_tpu package plus the parity side inputs (observability
assets, docs, tracked shell scripts) relative to --root (default: the
current directory, i.e. run it from the repo root).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from llmd_tpu.analysis.core import (
    CHECKERS,
    render_human,
    render_json,
    rule_names,
    run_analysis,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "python -m llmd_tpu.analysis",
        description="repo invariant linter (static-analysis.md)",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/directories to scan (default: the repo scan set)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument(
        "--rules", default=None,
        help="comma-separated subset of rules to run",
    )
    p.add_argument("--list-rules", action="store_true")
    p.add_argument(
        "--root", default=".",
        help="repo root findings are reported relative to (default: cwd)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # Import for the registry side effect before --list-rules.
    from llmd_tpu.analysis import checkers  # noqa: F401

    if args.list_rules:
        for name in sorted(rule_names()):
            desc = (
                CHECKERS[name].description
                if name in CHECKERS
                else "pragma hygiene (reason required, rule must exist)"
            )
            print(f"{name}: {desc}")
        return 0
    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    try:
        findings, nfiles = run_analysis(
            Path(args.root), args.paths or None, rules
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if nfiles == 0:
        # An empty scan set means the invariant tier silently enforced
        # NOTHING (wrong cwd/--root, moved package): fail loudly rather
        # than return a green exit CI would trust.
        print(
            "error: scan set is empty — run from the repo root or pass "
            "--root/paths (0 files means 0 invariants enforced)",
            file=sys.stderr,
        )
        return 2
    out = (
        render_json(findings, nfiles)
        if args.json
        else render_human(findings, nfiles)
    )
    print(out)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
