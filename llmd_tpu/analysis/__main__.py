"""CLI: ``python -m llmd_tpu.analysis [paths...] [--json] [--rules ...]``.

Exit codes: 0 clean, 1 findings, 2 usage error. With no paths the scan
set is the llmd_tpu package plus the parity side inputs (observability
assets, docs, tracked shell scripts) relative to --root (default: the
current directory, i.e. run it from the repo root).

CI surfaces: ``--sarif <path>`` additionally writes SARIF 2.1.0 (stable
per-finding rule ids) for PR annotation; ``--changed-only [BASE]``
scopes the scan to ``git diff BASE`` paths (default HEAD; plus staged
and untracked) so the annotation pass stays cheap — whole-tree parity
rules want the full default scan, so the gating run stays unscoped;
``--report-unused-pragmas`` lists ``# llmd: allow(...)`` pragmas that
no longer suppress anything (exit 0 either way: a non-blocking hygiene
report, since an unused pragma means the violation was FIXED).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from llmd_tpu.analysis.core import (
    CHECKERS,
    changed_paths,
    render_human,
    render_json,
    render_sarif,
    rule_names,
    run_analysis_details,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "python -m llmd_tpu.analysis",
        description="repo invariant linter (static-analysis.md)",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/directories to scan (default: the repo scan set)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument(
        "--sarif", metavar="PATH", default=None,
        help="also write findings as SARIF 2.1.0 to PATH (CI PR "
        "annotation; stdout output is unaffected)",
    )
    p.add_argument(
        "--changed-only", nargs="?", const="HEAD", default=None,
        metavar="BASE",
        help="scan only paths changed vs BASE (git diff + staged + "
        "untracked; default BASE: HEAD). An empty diff exits 0.",
    )
    p.add_argument(
        "--report-unused-pragmas", action="store_true",
        help="list `# llmd: allow(...)` pragmas that suppressed nothing "
        "this pass (standalone non-blocking mode: always exits 0; "
        "mutually exclusive with --json/--sarif)",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated subset of rules to run",
    )
    p.add_argument("--list-rules", action="store_true")
    p.add_argument(
        "--root", default=".",
        help="repo root findings are reported relative to (default: cwd)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # Import for the registry side effect before --list-rules.
    from llmd_tpu.analysis import checkers  # noqa: F401

    if args.list_rules:
        for name in sorted(rule_names()):
            desc = (
                CHECKERS[name].description
                if name in CHECKERS
                else "pragma hygiene (reason required, rule must exist)"
            )
            print(f"{name}: {desc}")
        return 0
    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    if args.report_unused_pragmas and (args.json or args.sarif):
        # The hygiene mode replaces the findings report AND the
        # exit-1 gate (always 0 by contract): combining it with the
        # machine outputs would silently discard real findings.
        print(
            "error: --report-unused-pragmas is a standalone mode "
            "(always exit 0) — run it as its own step, not with "
            "--json/--sarif", file=sys.stderr,
        )
        return 2
    paths = args.paths or None
    root = Path(args.root)
    try:
        if args.changed_only is not None:
            if paths:
                print(
                    "error: --changed-only and explicit paths are "
                    "mutually exclusive", file=sys.stderr,
                )
                return 2
            paths = changed_paths(root.resolve(), args.changed_only)
            if not paths:
                if args.sarif:
                    # The promised SARIF doc must exist (empty) even on
                    # an empty diff — a CI upload/ingest step fails on a
                    # missing path, or worse ingests a stale file.
                    Path(args.sarif).write_text(
                        render_sarif([]), encoding="utf-8"
                    )
                print("llmd-analysis: no changed files; nothing to scan")
                return 0
        findings, nfiles, unused = run_analysis_details(root, paths, rules)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if nfiles == 0:
        # An empty scan set means the invariant tier silently enforced
        # NOTHING (wrong cwd/--root, moved package): fail loudly rather
        # than return a green exit CI would trust.
        print(
            "error: scan set is empty — run from the repo root or pass "
            "--root/paths (0 files means 0 invariants enforced)",
            file=sys.stderr,
        )
        return 2
    if args.sarif:
        Path(args.sarif).write_text(
            render_sarif(findings), encoding="utf-8"
        )
    if args.report_unused_pragmas:
        for path, line, rule in unused:
            print(
                f"{path}:{line}: unused pragma `allow({rule})` — the "
                "violation it blessed is gone; delete the pragma"
            )
        print(
            f"llmd-analysis: {nfiles} file(s), "
            f"{len(unused)} unused pragma(s)"
        )
        return 0
    if args.json:
        from llmd_tpu.analysis import manifests

        deploy_objects = (
            len(manifests.render_corpus(root.resolve()).objects)
            if manifests.load_yaml() is not None
            else None
        )
        out = render_json(findings, nfiles, deploy_objects)
    else:
        out = render_human(findings, nfiles)
    print(out)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
