"""AST-based invariant linter for the engine's unwritten rules.

``python -m llmd_tpu.analysis`` runs every checker over the tree and
exits nonzero on findings (docs/architecture/static-analysis.md).
Stdlib-only by design: the CI lint job runs without jax installed.
"""

from llmd_tpu.analysis.core import (  # noqa: F401
    CHECKERS,
    Checker,
    Finding,
    Repo,
    changed_paths,
    register,
    rule_names,
    run_analysis,
    run_analysis_details,
)
