"""Render layer: the deploy/ tree as one corpus of resolved objects.

The deployment manifests are the product surface, but the linter's AST
rules stop at the Python boundary — probes, flags, env vars, and ports
in ``deploy/`` drift silently against the code they deploy. This module
closes the render gap: every kustomize base/overlay/component under
``deploy/`` is resolved (resources, components, inline JSON6902 and
strategic-merge patches, configMapGenerator, nameSuffix, labels with
includeSelectors — exactly the feature set the tree uses), and the
``deploy/charts/llmd-tpu`` Helm chart is rendered across a values
matrix mirroring the CI combinations, into one list of
:class:`RenderedObject` that ``checkers/deploy_parity.py`` walks.

Each object remembers its *source file* (root-relative) so findings
anchor to the line a human would edit, and its *unit* (the
kustomization root or chart variant) so duplicate-name checks don't
fire across independent overlays that intentionally share a base.

Render failures (a patch whose target moved, a template that no longer
parses) are collected as corpus errors, not exceptions — drift in the
render inputs is itself a finding, reported by DP001.

Stdlib + pyyaml only; pyyaml is gated so importing the analysis package
never needs it. Without pyyaml the corpus is empty and carries one
error saying so.
"""

from __future__ import annotations

import copy
import dataclasses
import re
from pathlib import Path

from llmd_tpu.analysis import helm_mini
from llmd_tpu.analysis.helm_mini import Renderer

# pyyaml binds lazily (the tree gate pins that importing the analysis
# package pulls in no third-party modules); None until first render.
yaml = None


def load_yaml():
    """Bind pyyaml on first use; returns the module or None."""
    global yaml
    if yaml is None:
        yaml = helm_mini.load_yaml()
    return yaml

# Chart values matrix: the combinations the reference CI helm-templates
# (mirrors tests/test_helm_template.py so the checked surface is the
# tested surface).
CHART_VALUES_MATRIX = (
    ("default", {}),
    ("observability", {
        "monitoring": {"enabled": True, "labels": {"release": "prom"}},
        "tracing": {"enabled": True, "sampleRatio": 0.25},
    }),
    ("minimal", {
        "prefill": {"enabled": False},
        "sidecar": {"enabled": False},
        "httpRoute": {"create": False},
    }),
    ("quantized", {
        "model": {"quantization": "int8"},
        "decode": {"enableDbo": True},
    }),
)


@dataclasses.dataclass
class RenderedObject:
    """One resolved Kubernetes object with provenance."""

    obj: dict
    unit: str    # kustomization root dir or "chart:<variant>"
    source: str  # root-relative path of the file to anchor findings to


@dataclasses.dataclass
class Corpus:
    objects: list[RenderedObject]
    units: list[str]
    errors: list[tuple[str, str]]  # (source path, message)

    def by_unit(self) -> dict[str, list[RenderedObject]]:
        out: dict[str, list[RenderedObject]] = {}
        for ro in self.objects:
            out.setdefault(ro.unit, []).append(ro)
        return out


def _rel(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root).as_posix()
    except ValueError:
        return path.as_posix()


# ------------------------------------------------------------------ #
# kustomize


def _json6902(obj: dict, ops: list[dict]) -> None:
    """Apply an RFC 6902 op list (the add/replace/remove subset the
    tree uses). Raises on a path that doesn't resolve — a patch whose
    target moved is drift, surfaced as a corpus error by the caller."""
    for op in ops:
        segs = [
            s.replace("~1", "/").replace("~0", "~")
            for s in str(op["path"]).split("/")[1:]
        ]
        parent = obj
        for s in segs[:-1]:
            parent = parent[int(s)] if isinstance(parent, list) else parent[s]
        last = segs[-1]
        kind = op["op"]
        if kind == "add":
            if isinstance(parent, list):
                if last == "-":
                    parent.append(op["value"])
                else:
                    parent.insert(int(last), op["value"])
            else:
                parent[last] = op["value"]
        elif kind == "replace":
            if isinstance(parent, list):
                parent[int(last)] = op["value"]  # raises on bad index
            else:
                if last not in parent:
                    raise KeyError(f"replace target {op['path']!r} absent")
                parent[last] = op["value"]
        elif kind == "remove":
            if isinstance(parent, list):
                del parent[int(last)]
            else:
                del parent[last]
        else:
            raise ValueError(f"unsupported JSON6902 op {kind!r}")


def _strategic_merge(base: dict, patch: dict) -> None:
    """Strategic-merge: dicts merge recursively; lists of named objects
    (containers, ports, env) merge by ``name``; scalar lists replace."""
    for k, v in patch.items():
        cur = base.get(k)
        if isinstance(v, dict) and isinstance(cur, dict):
            _strategic_merge(cur, v)
        elif (
            isinstance(v, list) and isinstance(cur, list)
            and all(isinstance(e, dict) and "name" in e for e in v)
            and all(isinstance(e, dict) and "name" in e for e in cur)
        ):
            by_name = {e["name"]: e for e in cur}
            for e in v:
                if e["name"] in by_name:
                    _strategic_merge(by_name[e["name"]], e)
                else:
                    cur.append(e)
        else:
            base[k] = v


def _target_matches(target: dict, obj: dict) -> bool:
    if target.get("kind") and obj.get("kind") != target["kind"]:
        return False
    name = (obj.get("metadata") or {}).get("name")
    if target.get("name") and name != target["name"]:
        return False
    return True


def _pod_template_paths(obj: dict) -> list[dict]:
    """The pod template metadata-bearing dicts of a workload object."""
    out = []
    spec = obj.get("spec") or {}
    if isinstance(spec.get("template"), dict):
        out.append(spec["template"])
    lwt = spec.get("leaderWorkerTemplate") or {}
    for key in ("leaderTemplate", "workerTemplate"):
        if isinstance(lwt.get(key), dict):
            out.append(lwt[key])
    return out


def _apply_labels(
    objs: list[RenderedObject], pairs: dict, include_selectors: bool
) -> None:
    for ro in objs:
        obj = ro.obj
        obj.setdefault("metadata", {}).setdefault("labels", {}).update(pairs)
        for tmpl in _pod_template_paths(obj):
            tmpl.setdefault("metadata", {}).setdefault(
                "labels", {}
            ).update(pairs)
        if not include_selectors:
            continue
        spec = obj.get("spec") or {}
        sel = spec.get("selector")
        if obj.get("kind") == "Service" and isinstance(sel, dict):
            sel.update(pairs)
        elif isinstance(sel, dict) and isinstance(
            sel.get("matchLabels"), dict
        ):
            sel["matchLabels"].update(pairs)


def _load_docs(path: Path, root: Path, unit: str, errors: list,
               consumed: set[Path] | None = None) -> list[RenderedObject]:
    if consumed is not None:
        consumed.add(path.resolve())
    try:
        docs = list(yaml.safe_load_all(path.read_text(encoding="utf-8")))
    except Exception as e:
        errors.append((_rel(path, root), f"YAML parse failed: {e}"))
        return []
    out = []
    for doc in docs:
        if isinstance(doc, dict) and doc:
            out.append(RenderedObject(doc, unit, _rel(path, root)))
        elif doc is not None:
            errors.append(
                (_rel(path, root), "top-level YAML document is not a mapping")
            )
    return out


def build_kustomization(
    kdir: Path, root: Path, errors: list, unit: str | None = None,
    consumed: set[Path] | None = None,
) -> list[RenderedObject]:
    """Resolve one kustomization directory to its object list."""
    kdir = kdir.resolve()
    unit = unit or _rel(kdir, root)
    kfile = kdir / "kustomization.yaml"
    if consumed is not None:
        consumed.add(kfile.resolve())
    try:
        spec = yaml.safe_load(kfile.read_text(encoding="utf-8")) or {}
    except Exception as e:
        errors.append((_rel(kfile, root), f"YAML parse failed: {e}"))
        return []

    objs: list[RenderedObject] = []
    for res in spec.get("resources") or []:
        p = (kdir / res).resolve()
        if p.is_dir():
            objs.extend(build_kustomization(
                p, root, errors, unit=unit, consumed=consumed,
            ))
        elif p.is_file():
            objs.extend(_load_docs(p, root, unit, errors, consumed))
        else:
            errors.append(
                (_rel(kfile, root), f"resource {res!r} does not exist")
            )

    # Components contribute their own resources and apply their patches
    # to the accumulated set.
    for comp in spec.get("components") or []:
        p = (kdir / comp).resolve()
        if not p.is_dir():
            errors.append(
                (_rel(kfile, root), f"component {comp!r} does not exist")
            )
            continue
        cobjs, cspec = [], {}
        if consumed is not None:
            consumed.add((p / "kustomization.yaml").resolve())
        try:
            cspec = yaml.safe_load(
                (p / "kustomization.yaml").read_text(encoding="utf-8")
            ) or {}
        except Exception as e:
            errors.append(
                (_rel(p / "kustomization.yaml", root),
                 f"YAML parse failed: {e}")
            )
        for res in cspec.get("resources") or []:
            rp = (p / res).resolve()
            if rp.is_dir():
                cobjs.extend(build_kustomization(
                    rp, root, errors, unit=unit, consumed=consumed,
                ))
            else:
                cobjs.extend(_load_docs(rp, root, unit, errors, consumed))
        objs.extend(cobjs)
        _apply_patches(
            cspec.get("patches") or [], p, objs, root, errors, consumed,
        )

    for gen in spec.get("configMapGenerator") or []:
        data = {}
        for fname in gen.get("files") or []:
            fp = kdir / fname
            try:
                data[Path(fname).name] = fp.read_text(encoding="utf-8")
            except OSError as e:
                errors.append(
                    (_rel(kfile, root),
                     f"configMapGenerator file {fname!r}: {e}")
                )
        for lit in gen.get("literals") or []:
            key, _, val = str(lit).partition("=")
            data[key] = val
        objs.append(RenderedObject(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": gen.get("name", "")},
                "data": data,
            },
            unit, _rel(kfile, root),
        ))

    _apply_patches(
        spec.get("patches") or [], kdir, objs, root, errors, consumed,
    )

    suffix = spec.get("nameSuffix")
    if suffix:
        for ro in objs:
            md = ro.obj.setdefault("metadata", {})
            md["name"] = f"{md.get('name', '')}{suffix}"
    for entry in spec.get("labels") or []:
        _apply_labels(
            objs, entry.get("pairs") or {},
            bool(entry.get("includeSelectors")),
        )
    return objs


def _apply_patches(
    patches: list, kdir: Path, objs: list[RenderedObject],
    root: Path, errors: list, consumed: set[Path] | None = None,
) -> None:
    for pat in patches:
        target = pat.get("target") or {}
        src = _rel(kdir / "kustomization.yaml", root)
        if "path" in pat:
            if consumed is not None:
                consumed.add((kdir / pat["path"]).resolve())
            try:
                body = yaml.safe_load(
                    (kdir / pat["path"]).read_text(encoding="utf-8")
                )
            except Exception as e:
                errors.append((src, f"patch {pat['path']!r}: {e}"))
                continue
        else:
            try:
                body = yaml.safe_load(pat.get("patch") or "")
            except Exception as e:
                errors.append((src, f"inline patch parse failed: {e}"))
                continue
        if not target and isinstance(body, dict):
            target = {
                "kind": body.get("kind"),
                "name": (body.get("metadata") or {}).get("name"),
            }
        hit = False
        for ro in objs:
            if not _target_matches(target, ro.obj):
                continue
            hit = True
            try:
                if isinstance(body, list):
                    _json6902(ro.obj, body)
                elif isinstance(body, dict):
                    _strategic_merge(ro.obj, copy.deepcopy(body))
            except (KeyError, IndexError, TypeError, ValueError) as e:
                errors.append((
                    src,
                    f"patch targeting {target.get('kind')}/"
                    f"{target.get('name')} failed to apply: {e!r} — the "
                    "patched path no longer exists in the base",
                ))
        if not hit:
            errors.append((
                src,
                f"patch target {target.get('kind')}/{target.get('name')} "
                "matches no rendered object",
            ))


# ------------------------------------------------------------------ #
# helm chart


def _merged_values(base: dict, overrides: dict) -> dict:
    vals = copy.deepcopy(base)
    for key, sub in overrides.items():
        if isinstance(sub, dict):
            node = vals.setdefault(key, {})
            node.update(copy.deepcopy(sub))
        else:
            vals[key] = sub
    return vals


def render_chart_unit(
    chart_dir: Path, values: dict, release: str, variant: str,
    root: Path, errors: list,
) -> list[RenderedObject]:
    """Render one values-matrix entry, per template file so every
    object anchors to the template a human would edit."""
    out: list[RenderedObject] = []
    r = Renderer(values, release)
    helpers = chart_dir / "templates" / "_helpers.tpl"
    if helpers.exists():
        r.render(helpers.read_text(encoding="utf-8"))
    for tpl in sorted((chart_dir / "templates").glob("*.yaml")):
        src = _rel(tpl, root)
        try:
            text = r.render(tpl.read_text(encoding="utf-8"))
            docs = list(yaml.safe_load_all(text))
        except Exception as e:
            errors.append((src, f"chart render ({variant}) failed: {e!r}"))
            continue
        for doc in docs:
            if isinstance(doc, dict) and doc:
                out.append(RenderedObject(doc, f"chart:{variant}", src))
    return out


# ------------------------------------------------------------------ #
# corpus

_CACHE: dict[str, Corpus] = {}


def kustomization_roots(root: Path) -> list[Path]:
    """Every kustomization dir under deploy/ that is a Kustomization
    (Components render only inside their includers)."""
    roots = []
    for kfile in sorted((root / "deploy").rglob("kustomization.yaml")):
        try:
            spec = yaml.safe_load(kfile.read_text(encoding="utf-8")) or {}
        except Exception:
            continue
        if spec.get("kind") != "Component":
            roots.append(kfile.parent)
    return roots


def render_corpus(root: Path) -> Corpus:
    """The whole deploy surface, cached per root so the checker and the
    CLI's object count share one render."""
    root = Path(root).resolve()
    key = str(root)
    if key in _CACHE:
        return _CACHE[key]
    objects: list[RenderedObject] = []
    errors: list[tuple[str, str]] = []
    units: list[str] = []
    if load_yaml() is None:
        corpus = Corpus([], [], [("deploy/", "pyyaml unavailable: deploy "
                                  "corpus not rendered")])
        _CACHE[key] = corpus
        return corpus
    consumed: set[Path] = set()
    if (root / "deploy").is_dir():
        for kdir in kustomization_roots(root):
            unit = _rel(kdir, root)
            units.append(unit)
            objects.extend(build_kustomization(
                kdir, root, errors, consumed=consumed,
            ))
        # Standalone manifests no kustomization references (swap-in
        # alternatives kept next to their recipes) still join the
        # corpus — "render every manifest" includes the spares. Only
        # docs that look like Kubernetes objects count: recipe dirs
        # also hold non-manifest YAML (benchmark workload specs).
        for path in sorted((root / "deploy").rglob("*.yaml")):
            rp = path.resolve()
            if rp in consumed or path.name == "kustomization.yaml":
                continue
            if "charts" in path.relative_to(root).parts:
                continue
            unit = f"file:{_rel(path, root)}"
            side_errors: list[tuple[str, str]] = []
            loaded = [
                ro for ro in _load_docs(path, root, unit, side_errors)
                if "kind" in ro.obj or "apiVersion" in ro.obj
            ]
            if loaded:
                units.append(unit)
                objects.extend(loaded)
                errors.extend(side_errors)
    chart = root / "deploy" / "charts" / "llmd-tpu"
    if chart.is_dir():
        try:
            base_values = yaml.safe_load(
                (chart / "values.yaml").read_text(encoding="utf-8")
            ) or {}
        except Exception as e:
            errors.append((_rel(chart / "values.yaml", root),
                           f"values.yaml parse failed: {e}"))
            base_values = {}
        for variant, overrides in CHART_VALUES_MATRIX:
            units.append(f"chart:{variant}")
            objects.extend(render_chart_unit(
                chart, _merged_values(base_values, overrides),
                "demo", variant, root, errors,
            ))
    corpus = Corpus(objects, units, errors)
    _CACHE[key] = corpus
    return corpus


def source_line(sf_text: str, needle: str) -> int:
    """Best-effort line anchor: first line of the source file containing
    the needle (a flag, path, or name the finding is about); 1 if the
    needle isn't literally present (e.g. rendered through a template)."""
    if needle:
        for i, line in enumerate(sf_text.splitlines(), 1):
            if needle in line:
                return i
    return 1
