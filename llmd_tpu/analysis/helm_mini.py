"""Minimal helm-template renderer for the chart's Go-template subset.

The reference CI validates every chart combination with ``helm template``
against a kind cluster (.github/workflows/ci-kustomize-dry-run.yaml:79-160).
This image has no helm binary, so the render layer brings its own renderer
covering exactly the constructs the chart uses: actions with whitespace
control, if/else, with, range-over-list, define/include, variables
(``$x :=``), pipelines, and the sprig-ish functions (default, printf,
trunc, trimSuffix, index, list, dict, eq, and, not, toYaml, nindent,
indent, quote). Unknown constructs raise — template drift fails the
render instead of silently producing garbage.

Lives in ``llmd_tpu.analysis`` so the deploy-parity checker can render
the chart's values matrix into the manifest corpus; ``tests/helm_mini.py``
re-exports it for the render tests. The analysis package stays importable
without third-party deps, so pyyaml is gated: importing this module never
fails, but rendering without pyyaml raises.
"""

from __future__ import annotations

import re

# pyyaml binds lazily: the tree gate pins that importing the analysis
# package pulls in NO third-party modules (the CI lint job may run
# before any install step). Rendering without pyyaml raises.
yaml = None


def load_yaml():
    """Bind pyyaml on first use; returns the module or None."""
    global yaml
    if yaml is None:
        try:
            import yaml as _yaml

            yaml = _yaml
        except ImportError:
            pass
    return yaml

_ACTION = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)
_TOKEN = re.compile(r'"(?:[^"\\]|\\.)*"|\(|\)|\||[^\s()|]+')


class Scope:
    def __init__(self, root, dot, variables):
        self.root = root  # the $ context
        self.dot = dot  # the . context
        self.vars = variables  # $name -> value


def _split_nodes(src: str):
    """Template source -> list of ("text", s) / ("action", body) nodes with
    whitespace control applied."""
    nodes = []
    pos = 0
    for m in _ACTION.finditer(src):
        text = src[pos : m.start()]
        if m.group(1) == "-":
            # helm's "-" trims ALL preceding whitespace incl. newlines
            text = text.rstrip()
        nodes.append(("text", text))
        nodes.append(("action", m.group(2), m.group(3) == "-"))
        pos = m.end()
    nodes.append(("text", src[pos:]))
    # apply trailing trim markers
    out = []
    trim_next = False
    for n in nodes:
        if n[0] == "text":
            s = n[1]
            if trim_next:
                s = s.lstrip()
                trim_next = False
            out.append(("text", s))
        else:
            out.append(("action", n[1]))
            trim_next = n[2]
    return out


class _Parser:
    """Builds a nested tree of blocks from the flat node list."""

    def __init__(self, nodes):
        self.nodes = nodes
        self.i = 0

    def parse(self, until=None):
        tree = []
        while self.i < len(self.nodes):
            kind, payload = self.nodes[self.i][0], self.nodes[self.i][1]
            self.i += 1
            if kind == "text":
                tree.append(("text", payload))
                continue
            head = payload.split(None, 1)[0] if payload else ""
            if head in ("end", "else") and until:
                return tree, head
            if head == "if":
                body, tail = self.parse(until=True)
                else_body = []
                if tail == "else":
                    else_body, tail = self.parse(until=True)
                assert tail == "end", payload
                tree.append(("if", payload[2:].strip(), body, else_body))
            elif head == "range":
                body, tail = self.parse(until=True)
                assert tail == "end"
                tree.append(("range", payload[5:].strip(), body))
            elif head == "with":
                body, tail = self.parse(until=True)
                else_body = []
                if tail == "else":
                    else_body, tail = self.parse(until=True)
                assert tail == "end"
                tree.append(("with", payload[4:].strip(), body, else_body))
            elif head == "define":
                name = payload.split(None, 1)[1].strip().strip('"')
                body, tail = self.parse(until=True)
                assert tail == "end"
                tree.append(("define", name, body))
            else:
                tree.append(("expr", payload))
        if until:
            raise SyntaxError("unclosed block")
        return tree, None


class Renderer:
    def __init__(self, values: dict, release_name: str = "test"):
        self.defines: dict[str, list] = {}
        self.root = {
            "Values": values,
            "Release": {"Name": release_name, "Service": "Helm"},
            "Chart": {"Name": "llmd-tpu"},
        }

    # -- expression evaluation ---------------------------------------- #

    def _resolve_path(self, base, path: str):
        cur = base
        for part in [p for p in path.split(".") if p]:
            if cur is None:
                return None
            if isinstance(cur, dict):
                cur = cur.get(part)
            else:
                cur = getattr(cur, part, None)
        return cur

    def _eval_tokens(self, toks: list, scope: Scope):
        """Evaluate one pipeline segment (function call or primary)."""
        if not toks:
            return None
        if len(toks) == 1:
            return self._primary(toks[0], scope)
        fn, args = toks[0], toks[1:]
        return self._call(fn, [self._primary(a, scope) for a in args], scope)

    def _primary(self, tok, scope: Scope):
        if isinstance(tok, list):  # parenthesized subexpression
            return self._pipeline(tok, scope)
        if tok.startswith('"'):
            return tok[1:-1].encode().decode("unicode_escape")
        if re.fullmatch(r"-?\d+", tok):
            return int(tok)
        if re.fullmatch(r"-?\d*\.\d+", tok):
            return float(tok)
        if tok == ".":
            return scope.dot
        if tok == "$":
            return self.root
        if tok.startswith("$"):
            name, _, rest = tok[1:].partition(".")
            if name == "" :
                return self._resolve_path(self.root, rest)
            base = scope.vars[name]
            return self._resolve_path(base, rest) if rest else base
        if tok.startswith("."):
            return self._resolve_path(scope.dot, tok[1:])
        # bare function with no args (e.g. in a pipe)
        return self._call(tok, [], scope)

    def _call(self, fn: str, args: list, scope: Scope):
        if fn == "include":
            name, ctx = args[0], args[1]
            return self._render_tree(
                self.defines[name], Scope(self.root, ctx, dict(scope.vars))
            )
        if fn == "default":
            d, v = args[0], args[1] if len(args) > 1 else None
            return v if v not in (None, "", 0, {}, []) else d
        if fn == "printf":
            return args[0] % tuple(args[1:])
        if fn == "trunc":
            n, s = args[0], args[1]
            return str(s)[:n]
        if fn == "trimSuffix":
            suf, s = args[0], args[1]
            return str(s)[: -len(suf)] if str(s).endswith(suf) else str(s)
        if fn == "quote":
            return '"%s"' % args[0]
        if fn == "index":
            cur = args[0]
            for k in args[1:]:
                cur = cur[k]
            return cur
        if fn == "list":
            return list(args)
        if fn == "dict":
            return {args[i]: args[i + 1] for i in range(0, len(args), 2)}
        if fn == "eq":
            return all(a == args[0] for a in args[1:])
        if fn == "ne":
            return args[0] != args[1]
        if fn == "and":
            out = True
            for a in args:
                out = a
                if not self._truthy(a):
                    return a
            return out
        if fn == "or":
            for a in args:
                if self._truthy(a):
                    return a
            return args[-1] if args else None
        if fn == "not":
            return not self._truthy(args[0])
        if fn == "toYaml":
            y = load_yaml()
            if y is None:
                raise RuntimeError("toYaml requires pyyaml")
            return y.safe_dump(args[0], default_flow_style=False).rstrip()
        if fn == "nindent":
            n, s = args[0], str(args[1])
            pad = " " * n
            return "\n" + "\n".join(
                pad + ln if ln else ln for ln in s.splitlines()
            )
        if fn == "indent":
            n, s = args[0], str(args[1])
            pad = " " * n
            return "\n".join(pad + ln if ln else ln for ln in s.splitlines())
        raise NameError(f"unsupported template function {fn!r}")

    @staticmethod
    def _truthy(v) -> bool:
        return bool(v) and v != 0

    def _tokenize(self, expr: str):
        """Flat tokens -> nested lists for parentheses."""
        flat = _TOKEN.findall(expr)
        def build(i):
            out = []
            while i < len(flat):
                t = flat[i]
                if t == "(":
                    sub, i = build(i + 1)
                    out.append(sub)
                elif t == ")":
                    return out, i
                else:
                    out.append(t)
                i += 1
            return out, i
        tree, _ = build(0)
        return tree

    def _pipeline(self, toks: list, scope: Scope):
        # split on "|"
        segments, cur = [], []
        for t in toks:
            if t == "|":
                segments.append(cur)
                cur = []
            else:
                cur.append(t)
        segments.append(cur)
        val = self._eval_tokens(segments[0], scope)
        for seg in segments[1:]:
            fn, args = seg[0], [self._primary(a, scope) for a in seg[1:]]
            val = self._call(fn, args + [val], scope)
        return val

    def eval_expr(self, expr: str, scope: Scope):
        # variable assignment: $x := pipeline
        m = re.match(r"^\$(\w+)\s*:=\s*(.*)$", expr, re.S)
        if m:
            scope.vars[m.group(1)] = self._pipeline(
                self._tokenize(m.group(2)), scope
            )
            return ""
        return self._pipeline(self._tokenize(expr), scope)

    # -- tree rendering ------------------------------------------------ #

    def _render_tree(self, tree: list, scope: Scope) -> str:
        out = []
        for node in tree:
            kind = node[0]
            if kind == "text":
                out.append(node[1])
            elif kind == "expr":
                v = self.eval_expr(node[1], scope)
                out.append("" if v is None else str(v))
            elif kind == "if":
                cond = self.eval_expr(node[1], scope)
                branch = node[2] if self._truthy(cond) else node[3]
                out.append(self._render_tree(branch, scope))
            elif kind == "with":
                v = self.eval_expr(node[1], scope)
                if self._truthy(v):
                    out.append(self._render_tree(
                        node[2], Scope(self.root, v, dict(scope.vars))
                    ))
                else:
                    out.append(self._render_tree(node[3], scope))
            elif kind == "range":
                body_expr = node[1]
                m = re.match(r"^\$(\w+)\s*:=\s*(.*)$", body_expr, re.S)
                if m:
                    items = self._pipeline(self._tokenize(m.group(2)), scope)
                    for item in items or []:
                        s2 = Scope(self.root, scope.dot, dict(scope.vars))
                        s2.vars[m.group(1)] = item
                        out.append(self._render_tree(node[2], s2))
                else:
                    items = self.eval_expr(body_expr, scope)
                    for item in items or []:
                        out.append(self._render_tree(
                            node[2], Scope(self.root, item, dict(scope.vars))
                        ))
            elif kind == "define":
                self.defines[node[1]] = node[2]
        return "".join(out)

    def render(self, src: str) -> str:
        tree, _ = _Parser(_split_nodes(src)).parse()
        scope = Scope(self.root, self.root, {})
        return self._render_tree(tree, scope)


def render_chart(chart_dir, values: dict, release_name: str = "test") -> list:
    """helm-template the chart: returns the parsed YAML docs of every
    rendered template (helpers first so defines register)."""
    from pathlib import Path

    if load_yaml() is None:
        raise RuntimeError("rendering the chart requires pyyaml")
    chart_dir = Path(chart_dir)
    r = Renderer(values, release_name)
    helpers = chart_dir / "templates" / "_helpers.tpl"
    if helpers.exists():
        r.render(helpers.read_text())
    docs = []
    for tpl in sorted((chart_dir / "templates").glob("*.yaml")):
        text = r.render(tpl.read_text())
        for doc in yaml.safe_load_all(text):
            if doc:
                docs.append(doc)
    return docs
