"""Invariant-linter core: findings, pragmas, file model, checker registry.

The engine's correctness rests on conventions no runtime test can see
failing — device work must stay inside the bucketed traced shape
families, host↔device syncs must flow through the declared coalesced
readback sites, every lockstep opcode needs a follower dispatch arm,
and metrics/flags/docs drift silently. Each convention is mechanized as
a checker over the stdlib ``ast`` (plus plain text for the parity
checkers); ``python -m llmd_tpu.analysis`` runs them all and exits
nonzero on any finding (docs/architecture/static-analysis.md).

Deliberately stdlib-only: the CI lint job runs this without jax (or any
third-party package) installed.

Suppression grammar — a finding on line L is suppressed by a pragma
comment on line L or line L-1::

    # llmd: allow(<rule>[, <rule>...]) -- <reason>

The reason is mandatory: a pragma without one is itself a finding
(``pragma/PRAGMA001``), as is a pragma naming an unknown rule
(``pragma/PRAGMA002``). Unused pragmas are tolerated (a fix that
removes the violation should not fail the build until the pragma is
cleaned up).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import subprocess
from pathlib import Path

PRAGMA_RE = re.compile(
    r"#\s*llmd:\s*allow\(\s*([a-z0-9_,\s-]+?)\s*\)\s*(?:--\s*(\S.*))?$"
)

# Directories whose Python modules sit on the per-step serving hot path:
# the host-sync and trace-discipline rules apply only here.
HOT_PATH_PARTS = frozenset({"engine", "ops", "parallel"})


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str  # checker name, e.g. "host-sync" (pragma key)
    code: str  # stable per-finding id, e.g. "HS001"
    path: str  # root-relative posix path
    line: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}/{self.code}] {self.message}"


class SourceFile:
    """A scanned file: text, lines, lazy AST, and the pragma index."""

    def __init__(self, root: Path, path: Path) -> None:
        self.abspath = path
        try:
            self.path = path.relative_to(root).as_posix()
        except ValueError:
            # Explicit path outside --root (e.g. a scratch fixture):
            # report it absolute rather than refusing to scan it.
            self.path = path.as_posix()
        try:
            self.text = path.read_text(encoding="utf-8", errors="replace")
        except OSError:
            self.text = ""
        self.lines = self.text.splitlines()
        self._tree: ast.AST | None | bool = False  # False = not parsed yet
        # line -> set of rule names allowed there; line 0 never matches.
        self.pragmas: dict[int, set[str]] = {}
        # (line, rules) per pragma COMMENT (for hygiene checks).
        self.pragma_decls: list[tuple[int, set[str]]] = []
        self.bad_pragmas: list[tuple[int, str]] = []  # (line, defect)
        # Pragmas only mean something where `#` starts a comment; docs
        # quoting pragma examples must not trip the hygiene rules.
        suppressible = self.path.endswith((".py", ".sh"))
        for i, line in enumerate(self.lines, 1):
            if not suppressible:
                break
            m = PRAGMA_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if not m.group(2):
                self.bad_pragmas.append(
                    (i, "pragma has no reason (grammar: "
                        "`# llmd: allow(<rule>) -- <reason>`)")
                )
            self.pragma_decls.append((i, rules))
            # A pragma blesses its own line and the next one, so both
            # trailing-comment and line-above placements work.
            self.pragmas.setdefault(i, set()).update(rules)
            self.pragmas.setdefault(i + 1, set()).update(rules)

    @property
    def is_python(self) -> bool:
        return self.path.endswith(".py")

    @property
    def name(self) -> str:
        return self.abspath.name

    @property
    def tree(self) -> ast.AST | None:
        """Parsed module, or None when not Python / syntactically broken
        (compileall stays the syntax floor; we don't double-report)."""
        if self._tree is False:
            self._tree = None
            if self.is_python:
                try:
                    self._tree = ast.parse(self.text)
                except SyntaxError:
                    self._tree = None
        return self._tree

    def allows(self, rule: str, line: int) -> bool:
        return rule in self.pragmas.get(line, ())

    @property
    def hot_path(self) -> bool:
        return bool(HOT_PATH_PARTS.intersection(Path(self.path).parts))


class Repo:
    """The file set one analysis run sees."""

    def __init__(self, root: Path, files: list[SourceFile]) -> None:
        self.root = root
        self.files = files

    def named(self, name: str) -> list[SourceFile]:
        return [f for f in self.files if f.name == name]


class Checker:
    """Base class; subclasses register with @register."""

    name = "base"
    description = ""

    def run(self, repo: Repo) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


CHECKERS: dict[str, type[Checker]] = {}


def register(cls: type[Checker]) -> type[Checker]:
    assert cls.name not in CHECKERS, f"duplicate checker {cls.name}"
    CHECKERS[cls.name] = cls
    return cls


def rule_names() -> set[str]:
    return set(CHECKERS) | {"pragma"}


# ------------------------------------------------------------------ #
# file discovery

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules", ".venv"}

# The default scan set: the Python package the AST rules cover, plus the
# side inputs the parity checkers diff against.
_DEFAULT_GLOBS = (
    "llmd_tpu/**/*.py",
    "observability/**/*.json",
    "observability/**/*.yaml",
    "docs/**/*.md",
    "README.md",
)


def _tracked_shell_scripts(root: Path) -> list[Path]:
    try:
        out = subprocess.run(
            ["git", "ls-files", "*.sh"], capture_output=True, text=True,
            cwd=root,
        )
        paths = [root / p for p in out.stdout.splitlines() if p]
        if paths:
            return paths
    except OSError:
        pass
    return [
        p for p in root.rglob("*.sh")
        if not _SKIP_DIRS.intersection(p.relative_to(root).parts)
    ]


def discover(root: Path, paths: list[str] | None = None) -> list[SourceFile]:
    root = root.resolve()
    found: list[Path] = []
    if paths:
        for raw in paths:
            p = Path(raw)
            if not p.is_absolute():
                p = root / p
            if p.is_dir():
                for q in sorted(p.rglob("*")):
                    if q.is_file() and q.suffix in (
                        ".py", ".sh", ".json", ".yaml", ".md"
                    ):
                        found.append(q)
            elif p.is_file():
                found.append(p)
    else:
        for pattern in _DEFAULT_GLOBS:
            found.extend(sorted(root.glob(pattern)))
        found.extend(_tracked_shell_scripts(root))
    out, seen = [], set()
    for p in found:
        p = p.resolve()
        rel = p.relative_to(root).parts if root in p.parents or p == root else ()
        if p in seen or _SKIP_DIRS.intersection(rel):
            continue
        seen.add(p)
        out.append(SourceFile(root, p))
    return out


# ------------------------------------------------------------------ #
# run loop

def run_analysis(
    root: Path,
    paths: list[str] | None = None,
    rules: list[str] | None = None,
) -> tuple[list[Finding], int]:
    """Run the (selected) checkers over the scan set.

    Returns (surviving findings, files scanned). Pragma suppression and
    pragma hygiene are applied here so every checker gets them for free.
    """
    # Import for side effect: checker registration.
    from llmd_tpu.analysis import checkers  # noqa: F401

    repo = Repo(root.resolve(), discover(root, paths))
    selected = sorted(rules) if rules else sorted(CHECKERS) + ["pragma"]
    unknown = [r for r in selected if r not in CHECKERS and r != "pragma"]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
    findings: list[Finding] = []
    for name in selected:
        if name != "pragma":
            findings.extend(CHECKERS[name]().run(repo))
    by_path = {f.path: f for f in repo.files}
    kept = [
        f for f in findings
        if f.path not in by_path or not by_path[f.path].allows(f.rule, f.line)
    ]
    if "pragma" in selected:
        known = rule_names()
        for sf in repo.files:
            for line, defect in sf.bad_pragmas:
                kept.append(Finding("pragma", "PRAGMA001", sf.path, line, defect))
            for line, names in sf.pragma_decls:
                for r in sorted(names - known):
                    kept.append(Finding(
                        "pragma", "PRAGMA002", sf.path, line,
                        f"pragma allows unknown rule {r!r} "
                        f"(known: {', '.join(sorted(known))})",
                    ))
    kept.sort(key=lambda f: (f.path, f.line, f.code))
    return kept, len(repo.files)


def render_human(findings: list[Finding], nfiles: int) -> str:
    lines = [f.render() for f in findings]
    lines.append(
        f"llmd-analysis: {nfiles} file(s), {len(findings)} finding(s)"
    )
    return "\n".join(lines)


def render_json(findings: list[Finding], nfiles: int) -> str:
    return json.dumps(
        {"files": nfiles, "findings": [f.to_dict() for f in findings]},
        indent=2,
    )
