"""Invariant-linter core: findings, pragmas, file model, checker registry.

The engine's correctness rests on conventions no runtime test can see
failing — device work must stay inside the bucketed traced shape
families, host↔device syncs must flow through the declared coalesced
readback sites, every lockstep opcode needs a follower dispatch arm,
and metrics/flags/docs drift silently. Each convention is mechanized as
a checker over the stdlib ``ast`` (plus plain text for the parity
checkers); ``python -m llmd_tpu.analysis`` runs them all and exits
nonzero on any finding (docs/architecture/static-analysis.md).

Deliberately stdlib-only: the CI lint job runs this without jax (or any
third-party package) installed.

Suppression grammar — a finding on line L is suppressed by a pragma
comment on line L or line L-1::

    # llmd: allow(<rule>[, <rule>...]) -- <reason>

The reason is mandatory: a pragma without one is itself a finding
(``pragma/PRAGMA001``), as is a pragma naming an unknown rule
(``pragma/PRAGMA002``). Unused pragmas are tolerated (a fix that
removes the violation should not fail the build until the pragma is
cleaned up).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import subprocess
import tokenize
from pathlib import Path

PRAGMA_RE = re.compile(
    r"#\s*llmd:\s*allow\(\s*([a-z0-9_,\s-]+?)\s*\)\s*(?:--\s*(\S.*))?$"
)

# Directories whose Python modules sit on the per-step serving hot path:
# the host-sync and trace-discipline rules apply only here.
HOT_PATH_PARTS = frozenset({"engine", "ops", "parallel"})


def _python_comment_lines(text: str) -> dict[int, str] | None:
    """line -> comment token text, via tokenize — so a pragma quoted in
    a string literal is not a pragma. None when the file doesn't
    tokenize (broken syntax; the per-line regex fallback applies, and
    compileall owns reporting the breakage)."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None
    return out


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str  # checker name, e.g. "host-sync" (pragma key)
    code: str  # stable per-finding id, e.g. "HS001"
    path: str  # root-relative posix path
    line: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}/{self.code}] {self.message}"


class SourceFile:
    """A scanned file: text, lines, lazy AST, and the pragma index."""

    def __init__(self, root: Path, path: Path) -> None:
        self.abspath = path
        try:
            self.path = path.relative_to(root).as_posix()
        except ValueError:
            # Explicit path outside --root (e.g. a scratch fixture):
            # report it absolute rather than refusing to scan it.
            self.path = path.as_posix()
        try:
            self.text = path.read_text(encoding="utf-8", errors="replace")
        except OSError:
            self.text = ""
        self.lines = self.text.splitlines()
        self._tree: ast.AST | None | bool = False  # False = not parsed yet
        # line -> set of rule names allowed there; line 0 never matches.
        self.pragmas: dict[int, set[str]] = {}
        # (line, rules) per pragma COMMENT (for hygiene checks).
        self.pragma_decls: list[tuple[int, set[str]]] = []
        self.bad_pragmas: list[tuple[int, str]] = []  # (line, defect)
        # Pragmas only mean something where `#` starts a comment; docs
        # quoting pragma examples must not trip the hygiene rules, and
        # neither must pragma grammar quoted inside Python STRING
        # literals (checker messages teach the grammar) — for .py files
        # only real COMMENT tokens count. YAML joined the suppressible
        # set with the deploy-parity rules: `# llmd: allow(...)` works
        # as a YAML comment on the offending line or the line above.
        suppressible = self.path.endswith((".py", ".sh", ".yaml"))
        comment_lines = (
            _python_comment_lines(self.text)
            if suppressible and self.is_python
            else None
        )
        for i, line in enumerate(self.lines, 1):
            if not suppressible:
                break
            if comment_lines is not None:
                line = comment_lines.get(i, "")
            m = PRAGMA_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if not m.group(2):
                self.bad_pragmas.append(
                    (i, "pragma has no reason (grammar: "
                        "`# llmd: allow(<rule>) -- <reason>`)")
                )
            self.pragma_decls.append((i, rules))
            # A pragma blesses its own line and the next one, so both
            # trailing-comment and line-above placements work.
            self.pragmas.setdefault(i, set()).update(rules)
            self.pragmas.setdefault(i + 1, set()).update(rules)

    @property
    def is_python(self) -> bool:
        return self.path.endswith(".py")

    @property
    def name(self) -> str:
        return self.abspath.name

    @property
    def tree(self) -> ast.AST | None:
        """Parsed module, or None when not Python / syntactically broken
        (compileall stays the syntax floor; we don't double-report)."""
        if self._tree is False:
            self._tree = None
            if self.is_python:
                try:
                    self._tree = ast.parse(self.text)
                except SyntaxError:
                    self._tree = None
        return self._tree

    def allows(self, rule: str, line: int) -> bool:
        return rule in self.pragmas.get(line, ())

    @property
    def hot_path(self) -> bool:
        return bool(HOT_PATH_PARTS.intersection(Path(self.path).parts))


class Repo:
    """The file set one analysis run sees."""

    def __init__(self, root: Path, files: list[SourceFile]) -> None:
        self.root = root
        self.files = files

    def named(self, name: str) -> list[SourceFile]:
        return [f for f in self.files if f.name == name]


class Checker:
    """Base class; subclasses register with @register."""

    name = "base"
    description = ""

    def run(self, repo: Repo) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


CHECKERS: dict[str, type[Checker]] = {}


def register(cls: type[Checker]) -> type[Checker]:
    assert cls.name not in CHECKERS, f"duplicate checker {cls.name}"
    CHECKERS[cls.name] = cls
    return cls


def rule_names() -> set[str]:
    return set(CHECKERS) | {"pragma"}


# ------------------------------------------------------------------ #
# file discovery

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules", ".venv"}

# The default scan set: the Python package the AST rules cover, plus the
# side inputs the parity checkers diff against.
_DEFAULT_GLOBS = (
    "llmd_tpu/**/*.py",
    "observability/**/*.json",
    "observability/**/*.yaml",
    "deploy/**/*.yaml",
    "docs/**/*.md",
    "README.md",
)


def _tracked_shell_scripts(root: Path) -> list[Path]:
    try:
        out = subprocess.run(
            ["git", "ls-files", "*.sh"], capture_output=True, text=True,
            cwd=root,
        )
        paths = [root / p for p in out.stdout.splitlines() if p]
        if paths:
            return paths
    except OSError:
        pass
    return [
        p for p in root.rglob("*.sh")
        if not _SKIP_DIRS.intersection(p.relative_to(root).parts)
    ]


def discover(root: Path, paths: list[str] | None = None) -> list[SourceFile]:
    root = root.resolve()
    found: list[Path] = []
    if paths:
        for raw in paths:
            p = Path(raw)
            if not p.is_absolute():
                p = root / p
            if p.is_dir():
                for q in sorted(p.rglob("*")):
                    if q.is_file() and q.suffix in (
                        ".py", ".sh", ".json", ".yaml", ".md"
                    ):
                        found.append(q)
            elif p.is_file():
                found.append(p)
    else:
        for pattern in _DEFAULT_GLOBS:
            found.extend(sorted(root.glob(pattern)))
        found.extend(_tracked_shell_scripts(root))
    out, seen = [], set()
    for p in found:
        p = p.resolve()
        rel = p.relative_to(root).parts if root in p.parents or p == root else ()
        if p in seen or _SKIP_DIRS.intersection(rel):
            continue
        seen.add(p)
        out.append(SourceFile(root, p))
    return out


# ------------------------------------------------------------------ #
# run loop

def run_analysis(
    root: Path,
    paths: list[str] | None = None,
    rules: list[str] | None = None,
) -> tuple[list[Finding], int]:
    """Run the (selected) checkers over the scan set.

    Returns (surviving findings, files scanned). Pragma suppression and
    pragma hygiene are applied here so every checker gets them for free.
    """
    findings, nfiles, _ = run_analysis_details(root, paths, rules)
    return findings, nfiles


def run_analysis_details(
    root: Path,
    paths: list[str] | None = None,
    rules: list[str] | None = None,
) -> tuple[list[Finding], int, list[tuple[str, int, str]]]:
    """:func:`run_analysis` plus the unused-pragma ledger: every
    ``# llmd: allow(...)`` declaration among whose named rules at least
    one RAN this pass yet suppressed no finding, as
    ``(path, line, rule)`` triples — the ``--report-unused-pragmas``
    hygiene surface (a pragma that no longer suppresses anything is a
    stale claim about the code next to it)."""
    # Import for side effect: checker registration.
    from llmd_tpu.analysis import checkers  # noqa: F401

    repo = Repo(root.resolve(), discover(root, paths))
    selected = sorted(rules) if rules else sorted(CHECKERS) + ["pragma"]
    unknown = [r for r in selected if r not in CHECKERS and r != "pragma"]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
    ran = {r for r in selected if r != "pragma"}
    findings: list[Finding] = []
    for name in selected:
        if name != "pragma":
            findings.extend(CHECKERS[name]().run(repo))
    by_path = {f.path: f for f in repo.files}
    kept: list[Finding] = []
    # path -> {(pragma line, rule)} that suppressed at least one finding.
    used_by_path: dict[str, set[tuple[int, str]]] = {}
    for f in findings:
        sf = by_path.get(f.path)
        if sf is None or not sf.allows(f.rule, f.line):
            kept.append(f)
        else:
            # A finding at line L is blessed by a pragma at L or L-1.
            hits = used_by_path.setdefault(f.path, set())
            for pline in (f.line, f.line - 1):
                if f.rule in sf.pragmas.get(pline, ()) and any(
                    dl == pline for dl, _ in sf.pragma_decls
                ):
                    hits.add((pline, f.rule))
    unused: list[tuple[str, int, str]] = []
    for sf in repo.files:
        hits = used_by_path.get(sf.path, set())
        for line, names in sf.pragma_decls:
            for r in sorted(names & ran):
                if (line, r) not in hits:
                    unused.append((sf.path, line, r))
    if "pragma" in selected:
        known = rule_names()
        for sf in repo.files:
            for line, defect in sf.bad_pragmas:
                kept.append(Finding("pragma", "PRAGMA001", sf.path, line, defect))
            for line, names in sf.pragma_decls:
                for r in sorted(names - known):
                    kept.append(Finding(
                        "pragma", "PRAGMA002", sf.path, line,
                        f"pragma allows unknown rule {r!r} "
                        f"(known: {', '.join(sorted(known))})",
                    ))
    kept.sort(key=lambda f: (f.path, f.line, f.code))
    unused.sort()
    return kept, len(repo.files), unused


def changed_paths(root: Path, base: str = "HEAD") -> list[str]:
    """The ``--changed-only`` scan set: paths touched vs ``base`` (plus
    staged and untracked files), so CI can annotate just a PR's diff.
    Missing git / not a repo raises ValueError — silently scanning
    nothing would hand CI a hollow green exit."""
    out: list[str] = []
    try:
        # git prints paths relative to the repo TOPLEVEL regardless of
        # cwd; resolve against it, or a --root pointing at a
        # subdirectory would silently drop every changed file and hand
        # CI exactly the hollow green exit this function guards against.
        tl = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, cwd=root,
        )
        if tl.returncode != 0:
            raise ValueError(
                "--changed-only: `git rev-parse --show-toplevel` "
                "failed: " + tl.stderr.strip()
            )
        toplevel = Path(tl.stdout.strip())
        for args in (
            ["git", "diff", "--name-only", base],
            ["git", "diff", "--name-only", "--cached"],
            # --full-name: ls-files prints cwd-relative paths (unlike
            # diff's toplevel-relative), which would mis-root untracked
            # files when --root is a repo subdirectory.
            ["git", "ls-files", "--others", "--exclude-standard",
             "--full-name"],
        ):
            r = subprocess.run(
                args, capture_output=True, text=True, cwd=root,
            )
            if r.returncode != 0:
                raise ValueError(
                    f"--changed-only: `{' '.join(args)}` failed: "
                    + r.stderr.strip()
                )
            out.extend(p for p in r.stdout.splitlines() if p)
    except OSError as e:
        raise ValueError(f"--changed-only needs git: {e}") from e
    root = root.resolve()
    seen: set[Path] = set()
    kept: list[str] = []
    for p in out:
        full = (toplevel / p).resolve()
        if not full.is_file() or full in seen:
            continue
        seen.add(full)
        try:
            kept.append(str(full.relative_to(root)))
        except ValueError:
            continue  # changed, but outside --root: not in scope
    return kept


def render_human(findings: list[Finding], nfiles: int) -> str:
    lines = [f.render() for f in findings]
    lines.append(
        f"llmd-analysis: {nfiles} file(s), {len(findings)} finding(s)"
    )
    return "\n".join(lines)


def render_json(
    findings: list[Finding], nfiles: int,
    deploy_objects: int | None = None,
) -> str:
    doc: dict = {"files": nfiles}
    if deploy_objects is not None:
        # How many resolved Kubernetes objects the deploy-parity render
        # layer produced (kustomize roots + chart values matrix) — the
        # CI lint job pins this above a floor so an import failure in
        # the render layer can't silently shrink the checked surface.
        doc["deploy_objects"] = deploy_objects
    doc["findings"] = [f.to_dict() for f in findings]
    return json.dumps(doc, indent=2)


_SARIF_HELP_URI = (
    "https://github.com/llm-d/llmd-tpu/blob/main/docs/architecture/"
    "static-analysis.md"
)


def render_sarif(findings: list[Finding]) -> str:
    """SARIF 2.1.0 for PR annotation: one run, one rule per stable
    per-finding code (``HS001``/``CC002``/...), file+line locations.
    The rule metadata carries the checker name (the pragma key) so an
    annotation tells the reader how to suppress as well as what broke."""
    # Import for side effect: checker registration (descriptions).
    from llmd_tpu.analysis import checkers  # noqa: F401

    rules: dict[str, dict] = {}
    results: list[dict] = []
    for f in findings:
        if f.code not in rules:
            desc = (
                CHECKERS[f.rule].description
                if f.rule in CHECKERS
                else "pragma hygiene (reason required, rule must exist)"
            )
            rules[f.code] = {
                "id": f.code,
                "name": f.rule,
                "shortDescription": {"text": f"{f.rule}: {desc}"},
                "helpUri": _SARIF_HELP_URI,
                "properties": {"pragma": f"# llmd: allow({f.rule}) -- "},
            }
        results.append({
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f"[{f.rule}/{f.code}] {f.message}"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
        })
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "llmd-analysis",
                    "informationUri": _SARIF_HELP_URI,
                    "rules": [rules[k] for k in sorted(rules)],
                },
            },
            # No "uri": the SARIF 2.1.0 unknown-base convention — the
            # consumer supplies the checkout root. A concrete file:///
            # here would make spec-conforming tools resolve every
            # location against the filesystem root.
            "originalUriBaseIds": {
                "SRCROOT": {
                    "description": {"text": "repository root"},
                },
            },
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)
