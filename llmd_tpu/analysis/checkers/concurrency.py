"""concurrency: guarded-by, lock order, async/blocking discipline.

The serving stack mixes engine/publisher/staging threads,
``threading.Lock``-guarded state, and asyncio loops — and the last two
review passes each caught a real concurrency bug by hand (set-ordering
nondeterminism breaking byte-identical scoreboards; ``BlockStored``
emits racing the publisher-thread medium swap outside the sink lock).
These rules mechanize that review, in the mold of Clang's thread-safety
(``guarded_by``) analysis; the runtime half lives in
:mod:`llmd_tpu.analysis.sanitize` (the lock sanitizer, armed by
``LLMD_LOCKSAN=1``).

Rules
-----

CC001 **guarded-by** — an attribute whose ``__init__`` assignment
carries the annotation (same line or the line above)::

    self._buf = []  # llmd: guarded_by(_lock)

may only be read or written while the named guard is held: lexically
inside ``with self._lock:`` (or a ``with`` on a ``threading.Condition``
the ``__init__`` built over that same lock), inside ``__init__`` itself,
inside a method whose name ends in ``_locked`` (the tree's
called-with-lock-held convention — the *caller* of a ``*_locked``
helper is checked instead), or inside a method decorated ``@_locked``
(the tree's acquire-around-the-whole-method decorator, which takes
``self._lock`` — so the decorator counts as holding ``_lock``).

CC002 **lock-order** — the whole-tree lock-acquisition graph: nesting
``with`` blocks on two lock-ish objects adds the edge *outer → inner*,
and a method that calls a sibling method while holding a lock inherits
the callee's first-level acquisitions (one level of intra-class call
edges, no transitive closure). Any cycle in the global graph is a
potential deadlock: two threads walking the cycle from different entry
points block each other forever. Findings attribute every edge of the
cycle.

CC003 **no-await-under-lock / no-block-in-async** — inside ``async
def`` in the event-loop packages (``epp/``, ``serve/``, ``batch/``,
``fleetsim/``): no ``await`` while a ``threading`` lock is held (the
loop thread parks on the await with the lock held; every other thread
— including the one that would let the awaited thing complete — then
blocks on the lock: instant deadlock potential), no ``time.sleep``
(blocks the whole loop; use ``asyncio.sleep``), and no bare
``lock.acquire()`` (a contended acquire blocks the loop; take the lock
in a ``with`` around straight-line code instead).

CC004 **cross-thread loop calls** — ``loop.call_soon(...)`` /
``loop.create_task(...)`` / ``asyncio.ensure_future(...)`` from a
thread-target function (anything passed as ``Thread(target=...)``, or
a helper such a function calls — one level, same class) corrupts the
loop's internals: only ``call_soon_threadsafe`` /
``run_coroutine_threadsafe`` are loop-thread-safe entry points.

Lock-ish heuristic: a ``with`` item (or ``acquire()`` receiver) whose
final name component matches ``lock|cond|mutex`` (case-insensitive).
That is what the tree's naming convention already guarantees; an
object that IS a lock but dodges the name dodges the rules, which is
the acceptable failure direction (under- not over-flagging).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from llmd_tpu.analysis.core import (
    Checker,
    Finding,
    Repo,
    _python_comment_lines,
    register,
)

# CC003 scope: packages whose async defs run on serving event loops.
ASYNC_SCOPE_PARTS = frozenset({"epp", "serve", "batch", "fleetsim"})

_LOCKISH_RE = re.compile(r"(lock|cond|mutex)", re.I)

GUARDED_BY_RE = re.compile(r"#\s*llmd:\s*guarded_by\(\s*([A-Za-z_][\w]*)\s*\)")

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})

_UNSAFE_LOOP_CALLS = frozenset({"call_soon", "create_task", "ensure_future"})


def _lockish_name(expr: ast.expr) -> str | None:
    """``self._lock`` -> ``_lock``; ``_lock`` -> ``_lock``; else None.
    Only lock-ish final components qualify."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return None
    return name if _LOCKISH_RE.search(name) else None


def _self_attr(expr: ast.expr) -> str | None:
    """``self.X`` -> ``X`` (else None)."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


# ------------------------------------------------------------------ #
# per-class model


class _ClassInfo:
    def __init__(
        self, sf, node: ast.ClassDef,
        comments: dict[int, str] | None = None,
    ) -> None:
        self.sf = sf
        self.node = node
        # line -> comment token (tokenize): grammar quoted inside a
        # string literal must not mint a phantom guarded attribute.
        # None = file didn't tokenize; raw-line regex fallback.
        self.comments = comments
        self.name = node.name
        # guarded attr -> (guard attr, annotation line)
        self.guarded: dict[str, tuple[str, int]] = {}
        # condition attr -> underlying lock attr (Condition(self._lock))
        self.cond_alias: dict[str, str] = {}
        self.methods: dict[str, ast.AST] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        init = self.methods.get("__init__")
        if init is not None:
            self._scan_init(init)
        # *_locked method -> guards its body needs (from the guarded
        # attrs it touches): the CALLER must hold these at the call.
        self.locked_needs: dict[str, set[str]] = {}
        for name, fn in self.methods.items():
            if not name.endswith("_locked"):
                continue
            needs: set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Attribute):
                    attr = _self_attr(sub)
                    if attr in self.guarded:
                        needs.add(self.guarded[attr][0])
            if needs:
                self.locked_needs[name] = needs

    def _scan_init(self, init: ast.AST) -> None:
        for stmt in ast.walk(init):
            if isinstance(stmt, ast.Assign):
                tnodes = stmt.targets
            elif isinstance(stmt, ast.AnnAssign):
                tnodes = [stmt.target]
            else:
                continue
            targets = [
                a for t in tnodes if (a := _self_attr(t)) is not None
            ]
            if not targets:
                continue
            # Condition alias: self._cond = threading.Condition(self._lock)
            v = stmt.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, (ast.Attribute, ast.Name))
                and (
                    v.func.attr
                    if isinstance(v.func, ast.Attribute)
                    else v.func.id
                )
                == "Condition"
                and v.args
            ):
                inner = _self_attr(v.args[0])
                if inner is not None:
                    for t in targets:
                        self.cond_alias[t] = inner
            for line in (stmt.lineno, stmt.lineno - 1):
                raw = (
                    self.sf.lines[line - 1]
                    if 0 < line <= len(self.sf.lines)
                    else ""
                )
                if line != stmt.lineno and not raw.lstrip().startswith("#"):
                    # The line above only annotates as a standalone
                    # comment — a trailing annotation up there belongs
                    # to THAT line's assignment, not this one.
                    continue
                hay = (
                    self.comments.get(line, "")
                    if self.comments is not None
                    else raw
                )
                m = GUARDED_BY_RE.search(hay)
                if m:
                    for t in targets:
                        self.guarded[t] = (m.group(1), stmt.lineno)
                    break

    def guards_satisfying(self, guard: str) -> set[str]:
        """Holding any of these attrs counts as holding ``guard``."""
        out = {guard}
        for cond, lock in self.cond_alias.items():
            if lock == guard:
                out.add(cond)
        return out


def _classes(sf) -> list[_ClassInfo]:
    if sf.tree is None:
        return []
    comments = _python_comment_lines(sf.text)
    return [
        _ClassInfo(sf, n, comments)
        for n in ast.walk(sf.tree)
        if isinstance(n, ast.ClassDef)
    ]


# ------------------------------------------------------------------ #
# CC001 guarded-by


class _GuardedVisitor(ast.NodeVisitor):
    """Walk one method tracking the lexically-held guard set."""

    def __init__(self, checker, ci: _ClassInfo, method: ast.AST) -> None:
        self.checker = checker
        self.ci = ci
        self.method = method
        self.held: list[str] = []  # stack of held self-attr names
        # @_locked decorator: the whole body runs under self._lock.
        for dec in getattr(method, "decorator_list", ()):
            name = (
                dec.id if isinstance(dec, ast.Name)
                else dec.attr if isinstance(dec, ast.Attribute) else ""
            )
            if name == "_locked":
                self.held.append("_lock")

    def run(self) -> None:
        for stmt in self.method.body:  # type: ignore[attr-defined]
            self.visit(stmt)

    def _visit_with(self, node) -> None:
        pushed = 0
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and _LOCKISH_RE.search(attr):
                self.held.append(attr)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - pushed : len(self.held)]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr in self.ci.guarded:
            guard, _ = self.ci.guarded[attr]
            ok = set(self.held) & self.ci.guards_satisfying(guard)
            if not ok:
                self.checker._finding(
                    self.ci.sf, "CC001", node.lineno,
                    f"{self.ci.name}.{attr} is annotated "
                    f"guarded_by({guard}) but accessed in "
                    f"{self.method.name} without holding self.{guard} "
                    "(wrap in `with self." + guard + ":`, rename the "
                    "method `*_locked` if callers hold it, or pragma "
                    "`# llmd: allow(concurrency) -- <reason>`)",
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # Calling a *_locked sibling transfers the obligation here: the
        # helper's body is exempt because ITS caller holds the guard.
        callee = _self_attr(node.func)
        needs = self.ci.locked_needs.get(callee or "")
        if needs:
            held: set[str] = set()
            for g in self.held:
                held |= {
                    guard
                    for guard in needs
                    if g in self.ci.guards_satisfying(guard)
                }
            missing = needs - held
            if missing:
                self.checker._finding(
                    self.ci.sf, "CC001", node.lineno,
                    f"call to {self.ci.name}.{callee} from "
                    f"{self.method.name} without holding "
                    f"{sorted('self.' + m for m in missing)} — *_locked "
                    "helpers run with their caller's lock held by "
                    "contract",
                )
        self.generic_visit(node)


# ------------------------------------------------------------------ #
# CC002 lock-order graph


class _AcqVisitor(ast.NodeVisitor):
    """Collect (outer-held stack, acquired lock, call sites) per method."""

    def __init__(self) -> None:
        self.held: list[str] = []
        # edges within this method: (outer, inner, line)
        self.edges: list[tuple[str, str, int]] = []
        # locks acquired at top level (no outer held): [(lock, line)]
        self.first_acquitions: list[tuple[str, int]] = []
        # sibling calls: (held-at-call-site tuple, callee name, line)
        self.calls: list[tuple[tuple[str, ...], str, int]] = []

    def _visit_with(self, node) -> None:
        pushed = 0
        for item in node.items:
            name = _lockish_name(item.context_expr)
            if name is not None:
                if self.held:
                    self.edges.append((self.held[-1], name, node.lineno))
                else:
                    self.first_acquitions.append((name, node.lineno))
                self.held.append(name)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - pushed : len(self.held)]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Call(self, node: ast.Call) -> None:
        callee = _self_attr(node.func)
        if callee is not None:
            self.calls.append((tuple(self.held), callee, node.lineno))
        self.generic_visit(node)


def _lock_order_edges(ci: _ClassInfo) -> list[tuple[str, str, int, str]]:
    """(outer, inner, line, method) edges for one class: nested withs
    plus one level of intra-class call edges."""
    per_method: dict[str, _AcqVisitor] = {}
    for name, fn in ci.methods.items():
        v = _AcqVisitor()
        for dec in getattr(fn, "decorator_list", ()):
            dname = (
                dec.id if isinstance(dec, ast.Name)
                else dec.attr if isinstance(dec, ast.Attribute) else ""
            )
            if dname == "_locked":
                # @_locked acquires self._lock around the whole body.
                v.first_acquitions.append(("_lock", fn.lineno))
                v.held.append("_lock")
        for stmt in fn.body:  # type: ignore[attr-defined]
            v.visit(stmt)
        v.held.clear()
        per_method[name] = v
    edges: list[tuple[str, str, int, str]] = []
    for name, v in per_method.items():
        for outer, inner, line in v.edges:
            edges.append((outer, inner, line, name))
        # One level of call edges: while holding L, calling a sibling
        # that first-acquires M adds L -> M.
        for held, callee, line in v.calls:
            if not held:
                continue
            cv = per_method.get(callee)
            if cv is None:
                continue
            for inner, _ in cv.first_acquitions:
                edges.append((held[-1], inner, line, name))
    return edges


def _find_cycles(
    graph: dict[str, set[str]],
) -> list[list[str]]:
    """Simple DFS cycle enumeration; each cycle reported once, rotated
    to start at its smallest node (the graph is tiny — lock attrs)."""
    cycles: set[tuple[str, ...]] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    i = path.index(min(path))
                    cycles.add(tuple(path[i:] + path[:i]))
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))
    return [list(c) for c in sorted(cycles)]


# ------------------------------------------------------------------ #
# CC003 async blocking


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walk one async def body (not nested defs)."""

    def __init__(self, checker, sf, fn) -> None:
        self.checker = checker
        self.sf = sf
        self.fn = fn
        self.held: list[str] = []  # sync-with lock-ish stack

    def run(self) -> None:
        for stmt in self.fn.body:
            self.visit(stmt)

    # Nested defs run elsewhere (executor threads, callbacks): their
    # bodies are not this event-loop coroutine's straight line.
    def visit_FunctionDef(self, node) -> None:
        pass

    def visit_AsyncFunctionDef(self, node) -> None:
        pass

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            if _lockish_name(item.context_expr) is not None:
                self.held.append(_lockish_name(item.context_expr))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - pushed : len(self.held)]

    def visit_Await(self, node: ast.Await) -> None:
        if self.held:
            self.checker._finding(
                self.sf, "CC003", node.lineno,
                f"await while holding threading lock `{self.held[-1]}` "
                f"in async {self.fn.name}: the loop thread parks on the "
                "await with the lock held and every other thread blocks "
                "behind it — restructure so the lock covers only "
                "straight-line code",
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "sleep"
            and isinstance(f.value, ast.Name)
            and f.value.id == "time"
        ):
            self.checker._finding(
                self.sf, "CC003", node.lineno,
                f"time.sleep in async {self.fn.name} blocks the whole "
                "event loop: use `await asyncio.sleep(...)`",
            )
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "acquire"
            and _lockish_name(f.value) is not None
        ):
            self.checker._finding(
                self.sf, "CC003", node.lineno,
                f"bare `{_lockish_name(f.value)}.acquire()` in async "
                f"{self.fn.name} can block the event loop on contention: "
                "hold the lock in a `with` around straight-line code",
            )
        self.generic_visit(node)

    def generic_visit(self, node) -> None:
        # Awaited lock-ish acquires (asyncio primitives) are fine; the
        # Await visitor above sees them first only when a threading lock
        # is already held, which is the actual hazard.
        super().generic_visit(node)


# ------------------------------------------------------------------ #
# CC004 cross-thread loop calls


def _thread_target_names(tree: ast.AST) -> set[str]:
    """Function/method names passed as Thread(target=...) anywhere in
    the module (matched by name: `self._run`, `run`, `module_fn`)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = (
            node.func.attr
            if isinstance(node.func, ast.Attribute)
            else node.func.id if isinstance(node.func, ast.Name) else ""
        )
        if fname != "Thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            t = kw.value
            if isinstance(t, ast.Attribute):
                out.add(t.attr)
            elif isinstance(t, ast.Name):
                out.add(t.id)
    return out


class _LoopCallVisitor(ast.NodeVisitor):
    def __init__(self) -> None:
        self.unsafe: list[tuple[str, int]] = []  # (call name, line)
        self.sibling_calls: set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _UNSAFE_LOOP_CALLS:
                # loop.create_task / loop.call_soon / asyncio.ensure_future;
                # exclude x.call_soon_threadsafe (different attr already).
                recv = f.value
                recv_name = (
                    recv.attr if isinstance(recv, ast.Attribute)
                    else recv.id if isinstance(recv, ast.Name) else ""
                )
                # tg.create_task (TaskGroup) only exists inside async
                # defs, which are not thread targets; loop-ish or
                # asyncio receivers are the hazard.
                if f.attr == "ensure_future" or "loop" in recv_name.lower() \
                        or recv_name == "asyncio":
                    self.unsafe.append((f"{recv_name}.{f.attr}", node.lineno))
            sib = _self_attr(f)
            if sib is not None:
                self.sibling_calls.add(sib)
        elif isinstance(f, ast.Name) and f.id in _UNSAFE_LOOP_CALLS:
            self.unsafe.append((f.id, node.lineno))
        self.generic_visit(node)


# ------------------------------------------------------------------ #


@register
class ConcurrencyChecker(Checker):
    name = "concurrency"
    description = (
        "guarded_by annotations hold (CC001), the whole-tree lock-order "
        "graph is acyclic (CC002), async defs in epp//serve//batch//"
        "fleetsim/ never block or await under a threading lock (CC003), "
        "and thread-target functions touch event loops only through "
        "*_threadsafe entry points (CC004)"
    )

    def __init__(self) -> None:
        self.findings: list[Finding] = []

    def _finding(self, sf, code: str, line: int, msg: str) -> None:
        self.findings.append(Finding(self.name, code, sf.path, line, msg))

    def run(self, repo: Repo) -> list[Finding]:
        self.findings = []
        # node -> {inner}, plus attribution: (outer, inner) -> (sf, line)
        graph: dict[str, set[str]] = {}
        edge_site: dict[tuple[str, str], tuple] = {}
        for sf in repo.files:
            if not sf.is_python or sf.tree is None:
                continue
            parts = set(Path(sf.path).parts)
            classes = _classes(sf)
            # CC001
            for ci in classes:
                if not ci.guarded:
                    continue
                for mname, fn in ci.methods.items():
                    if mname == "__init__" or mname.endswith("_locked"):
                        continue
                    _GuardedVisitor(self, ci, fn).run()
            # CC002: accumulate the whole-tree graph. Node identity is
            # (module-qualified class, lock attr): a cycle is only a
            # deadlock when the SAME locks are reachable in both orders.
            for ci in classes:
                mod = sf.path
                for outer, inner, line, method in _lock_order_edges(ci):
                    a = f"{mod}::{ci.name}.{outer}"
                    b = f"{mod}::{ci.name}.{inner}"
                    if a == b:
                        continue  # RLock re-entry, not an order edge
                    graph.setdefault(a, set()).add(b)
                    edge_site.setdefault((a, b), (sf, line, method))
            # CC003
            if parts & ASYNC_SCOPE_PARTS:
                for node in ast.walk(sf.tree):
                    if isinstance(node, ast.AsyncFunctionDef):
                        _AsyncBodyVisitor(self, sf, node).run()
            # CC004
            targets = _thread_target_names(sf.tree)
            if targets:
                self._check_loop_calls(sf, targets)
        # CC002 cycle detection over the accumulated graph.
        for cycle in _find_cycles(graph):
            pretty = " -> ".join(
                n.split("::", 1)[1] for n in cycle + [cycle[0]]
            )
            for i, node in enumerate(cycle):
                nxt = cycle[(i + 1) % len(cycle)]
                sf, line, method = edge_site[(node, nxt)]
                self._finding(
                    sf, "CC002", line,
                    f"lock-order cycle (potential deadlock): {pretty} — "
                    f"this edge acquired in {method}; pick one global "
                    "order (or drop one lock) so every thread nests "
                    "these locks the same way",
                )
        self.findings.sort(key=lambda f: (f.path, f.line, f.code))
        return self.findings

    def _check_loop_calls(self, sf, targets: set[str]) -> None:
        """CC004 over one module: thread-target functions (plus the
        same-class helpers they call, one level) must not touch a loop
        except through *_threadsafe."""
        # name -> list of function nodes (methods may repeat names
        # across classes; check per class to keep call edges honest).
        scopes: list[dict[str, ast.AST]] = []
        module_fns: dict[str, ast.AST] = {}
        for node in sf.tree.body:
            if isinstance(node, ast.FunctionDef):
                module_fns[node.name] = node
            elif isinstance(node, ast.ClassDef):
                scopes.append({
                    n.name: n
                    for n in node.body
                    if isinstance(n, ast.FunctionDef)
                })
        scopes.append(module_fns)
        for fns in scopes:
            hit = targets & set(fns)
            if not hit:
                continue
            checked: set[str] = set()
            frontier = set(hit)
            depth = 0
            while frontier and depth <= 1:
                next_frontier: set[str] = set()
                for name in sorted(frontier):
                    if name in checked or name not in fns:
                        continue
                    checked.add(name)
                    v = _LoopCallVisitor()
                    v.visit(fns[name])
                    for call, line in v.unsafe:
                        self._finding(
                            sf, "CC004", line,
                            f"`{call}` reached from thread-target "
                            f"function {name}: event loops are not "
                            "thread-safe — use call_soon_threadsafe / "
                            "run_coroutine_threadsafe from other threads",
                        )
                    next_frontier |= v.sibling_calls
                frontier = next_frontier - checked
                depth += 1
