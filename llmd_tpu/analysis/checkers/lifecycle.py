"""lifecycle: resource acquire/release discipline (RL001-RL003).

The last four review passes each hand-caught a leaked *handle*: the
AdapterPool duplicate-install race leaked a slot out of both ``_free``
and ``_slot_of`` (PR 13), the endpoint breaker burned one half-open
probe grant per cooldown window (PR 8), and the spec-decode rollback
needed ``_truncate_spec_pages`` invariants to keep provisional KV pages
from escaping (PR 2/4). These rules mechanize that review the way the
``concurrency`` rules mechanized the lock review; the runtime half is
the leak sanitizer in :mod:`llmd_tpu.analysis.sanitize`
(``LLMD_LEAKSAN=1``).

Protocol declaration — on the owning class (the resource manager), a
comment on the ``class`` line or the line(s) directly above::

    # llmd: resource(pages, recv=alloc, acquire=allocate|touch:arg,
    #                release=free, transfer=commit_page)

- ``recv=`` — ``|``-separated substrings; a call site participates only
  when the receiver's final name component contains one (case-
  insensitive), or the receiver is ``self`` inside the declaring class.
  Guards generic method names (``free``, ``acquire``) against unrelated
  classes.
- ``acquire=`` — methods that mint a handle. Default handle is the
  return value (``:ret``); ``:arg`` / ``:argN`` declares the N-th
  positional argument (1-based) as the handle instead (lease-style
  protocols key the handle on the *name* passed in).
- ``release=`` / ``transfer=`` — methods that end a handle's life
  (refund vs. publish-to-owned-state). Handle is the first positional
  argument unless ``:argN`` says otherwise.

Ownership handoffs out of the checked scope are declared, not guessed::

    self._entries = {}  # llmd: owns(pages)     (attribute is a root)
    # llmd: transfers(pages)                    (on a def: ownership
    def steal(self, ids): ...                    crosses this boundary)

Storing a handle into an ``owns``-annotated attribute (assignment,
subscript, or a mutator call such as ``.append``/``.extend``), passing
it by matching keyword to any constructor/call, passing it to a
``transfers``-marked callee, or returning it from a ``transfers``-marked
function all count as release-equivalent handoffs.

Rules
-----

RL001 **release-on-all-paths** — every acquisition must reach a
release, transfer, or declared handoff on every exit path. A ``return``
or ``raise`` with a live handle, a loop iteration that ends with one, a
reacquire over one, and an exception-capable call between acquire and
release with no covering ``finally`` (or broad ``except`` that
releases) are all findings — reported once per acquisition, AT the
acquisition line, so one pragma covers the site.

RL002 **release-pairing** — double-release of the same handle variable
on one path, and release of a variable that was only *peeked* (assigned
from a non-acquire method of the same resource manager, e.g.
``slot_of``): flow-insensitive per-function pairing over the handle
variable.

RL003 **escaping-handle** — a handle stored into state that is not
``owns``-annotated, or returned from a function that is not
``transfers``-marked, silently moves ownership outside the checked
scope; the leak just happens later, somewhere the checker cannot see.
"""

from __future__ import annotations

import ast
import re

from llmd_tpu.analysis.core import (
    Checker,
    Finding,
    Repo,
    _python_comment_lines,
    register,
)

# Matched against the comment BLOCK around a class def joined into one
# line (continuation lines stripped of their leading `#`), so the
# declaration may wrap across comment lines; `)` never appears inside
# the grammar's values, so the first close-paren ends it.
RESOURCE_RE = re.compile(
    r"llmd:\s*resource\(\s*([a-z0-9_-]+)\s*(?:,\s*([^)]*?))?\s*\)"
)
OWNS_RE = re.compile(r"#\s*llmd:\s*owns\(\s*([a-z0-9_,\s-]+?)\s*\)")
TRANSFERS_RE = re.compile(r"#\s*llmd:\s*transfers\(\s*([a-z0-9_,\s-]+?)\s*\)")

# Calls that cannot plausibly raise mid-protocol (the exception-edge
# check ignores them): builtins plus the no-fail container mutators.
_SAFE_CALLS = frozenset({
    "len", "int", "str", "float", "bool", "list", "dict", "set", "tuple",
    "sorted", "min", "max", "sum", "enumerate", "zip", "range", "repr",
    "isinstance", "getattr", "hasattr", "print", "id", "iter", "next",
    "abs", "round", "frozenset",
})
_SAFE_METHODS = frozenset({
    "append", "extend", "pop", "popleft", "popitem", "add", "discard",
    "remove", "clear", "update", "get", "items", "keys", "values",
    "move_to_end", "setdefault", "insert", "count", "index", "copy",
    "join", "split", "strip", "encode", "decode", "format", "startswith",
    "endswith", "lower", "upper", "debug", "info", "warning", "error",
    "monotonic", "perf_counter", "time",
})
# Mutator methods through which a handle lands in an owns-annotated
# container attribute.
_OWNS_MUTATORS = frozenset({
    "append", "extend", "add", "update", "insert", "setdefault", "put",
})


def _name_chain(expr: ast.expr) -> str | None:
    """``pod.address`` -> "pod.address", ``x`` -> "x" (depth <= 2 so
    handle keys stay stable; deeper chains are not tracked)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return f"{expr.value.id}.{expr.attr}"
    return None


def _recv_name(expr: ast.expr) -> str | None:
    """Final name component of a call receiver (``self.adapter_pool``
    -> ``adapter_pool``; ``self`` -> ``self``)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


class _Protocol:
    def __init__(self, name: str, cls: str, path: str, line: int) -> None:
        self.name = name
        self.cls = cls
        self.path = path
        self.line = line
        self.recv: list[str] = []
        self.acquire: dict[str, object] = {}  # method -> "ret" | int (1-based)
        self.release: dict[str, int] = {}
        self.transfer: dict[str, int] = {}

    @property
    def methods(self) -> set[str]:
        return set(self.acquire) | set(self.release) | set(self.transfer)

    def recv_matches(self, recv: str | None, in_owner_class: bool) -> bool:
        if recv == "self" or recv == "cls":
            return in_owner_class
        if not self.recv:
            return True
        if recv is None:
            return False
        low = recv.lower()
        return any(hint in low for hint in self.recv)


def _parse_methods(raw: str, default_mode) -> dict:
    out: dict = {}
    for tok in raw.split("|"):
        tok = tok.strip()
        if not tok:
            continue
        mode = default_mode
        if ":" in tok:
            tok, suffix = tok.split(":", 1)
            if suffix == "ret":
                mode = "ret"
            elif suffix == "arg":
                mode = 1
            elif suffix.startswith("arg"):
                mode = int(suffix[3:])
        out[tok] = mode
    return out


class _Registry:
    """Tree-wide protocol / owns / transfers declarations."""

    def __init__(self) -> None:
        self.protocols: list[_Protocol] = []
        # method name -> [protocols declaring it] (for call matching)
        self.by_method: dict[str, list[_Protocol]] = {}
        # attribute name -> resources it is an ownership root for
        self.owns: dict[str, set[str]] = {}
        # function/method NAME -> resources whose ownership crosses it
        self.transfers: dict[str, set[str]] = {}

    def add_protocol(self, p: _Protocol) -> None:
        self.protocols.append(p)
        for m in p.methods:
            self.by_method.setdefault(m, []).append(p)

    def match_call(
        self, call: ast.Call, in_class: str | None
    ) -> tuple[_Protocol, str, object] | None:
        """(protocol, kind, mode) for a protocol-method call, else None.
        kind in {"acquire", "release", "transfer"}."""
        if not isinstance(call.func, ast.Attribute):
            return None
        mname = call.func.attr
        cands = self.by_method.get(mname)
        if not cands:
            return None
        recv = _recv_name(call.func.value)
        for p in cands:
            if not p.recv_matches(recv, in_class == p.cls):
                continue
            if mname in p.acquire:
                return p, "acquire", p.acquire[mname]
            if mname in p.release:
                return p, "release", p.release[mname]
            return p, "transfer", p.transfer[mname]
        return None

    def peek_call(self, call: ast.Call, in_class: str | None) -> str | None:
        """Resource name when ``call`` is a recv-matched call to a
        NON-acquire method of a manager (a peek like ``slot_of``):
        releasing its result is RL002's release-without-acquire."""
        if not isinstance(call.func, ast.Attribute):
            return None
        mname = call.func.attr
        recv = _recv_name(call.func.value)
        for p in self.protocols:
            if mname in p.methods:
                continue
            # Only confidently-owned receivers count (a recv hint must
            # match; bare self/unhinted receivers are too ambiguous).
            if recv is not None and recv not in ("self", "cls") and p.recv \
                    and any(h in recv.lower() for h in p.recv):
                return p.name
        return None


def build_registry(repo: Repo) -> tuple[_Registry, list[Finding]]:
    reg = _Registry()
    findings: list[Finding] = []
    for sf in repo.files:
        if not sf.is_python or sf.tree is None:
            continue
        comments = _python_comment_lines(sf.text) or {}

        def comment_at(line: int) -> str:
            if comments:
                return comments.get(line, "")
            return sf.lines[line - 1] if 0 < line <= len(sf.lines) else ""

        def decl_comments(node) -> list[tuple[int, str]]:
            """Comment on the def/class line plus up to 3 consecutive
            comment lines directly above (skipping decorators)."""
            out = [(node.lineno, comment_at(node.lineno))]
            top = min(
                [node.lineno]
                + [d.lineno for d in getattr(node, "decorator_list", ())]
            )
            for back in range(1, 4):
                line = top - back
                raw = sf.lines[line - 1] if 0 < line <= len(sf.lines) else ""
                if not raw.lstrip().startswith("#"):
                    break
                out.append((line, comment_at(line)))
            return out

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                # Join the comment block (lines above + class line, in
                # source order, continuation `#` stripped) so wrapped
                # declarations — the form the docs' grammar examples
                # use — parse identically to single-line ones.
                block = sorted(decl_comments(node))
                joined = " ".join(
                    text.lstrip("#").strip() for _, text in block if text
                )
                for m in RESOURCE_RE.finditer(joined):
                    line = next(
                        (ln for ln, text in block if m.group(1) in text
                         and "resource" in text),
                        node.lineno,
                    )
                    p = _Protocol(m.group(1), node.name, sf.path, line)
                    for part in (m.group(2) or "").split(","):
                        part = part.strip()
                        if not part or "=" not in part:
                            continue
                        key, _, val = part.partition("=")
                        key = key.strip()
                        if key == "recv":
                            p.recv = [
                                v.strip().lower()
                                for v in val.split("|") if v.strip()
                            ]
                        elif key == "acquire":
                            p.acquire = _parse_methods(val, "ret")
                        elif key == "release":
                            p.release = _parse_methods(val, 1)
                        elif key == "transfer":
                            p.transfer = _parse_methods(val, 1)
                    if not p.acquire:
                        findings.append(Finding(
                            "release-on-all-paths", "RL001", sf.path, line,
                            f"resource({p.name}) declares no acquire= "
                            "methods — the protocol is unenforceable",
                        ))
                        continue
                    reg.add_protocol(p)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for _, text in decl_comments(node):
                    m = TRANSFERS_RE.search(text)
                    if m:
                        reg.transfers.setdefault(node.name, set()).update(
                            r.strip() for r in m.group(1).split(",")
                            if r.strip()
                        )
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                for line in (node.lineno, node.lineno - 1):
                    raw = (
                        sf.lines[line - 1]
                        if 0 < line <= len(sf.lines) else ""
                    )
                    if line != node.lineno and not raw.lstrip().startswith("#"):
                        continue
                    m = OWNS_RE.search(comment_at(line))
                    if not m:
                        continue
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    names = {
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    }
                    for t in targets:
                        attr = (
                            t.attr if isinstance(t, ast.Attribute)
                            else t.id if isinstance(t, ast.Name) else None
                        )
                        if attr:
                            reg.owns.setdefault(attr, set()).update(names)
                    break
    return reg, findings


# ------------------------------------------------------------------ #
# per-function handle-flow walk


class _Handle:
    __slots__ = ("resource", "line", "guard", "release_lines", "reported")

    def __init__(self, resource: str, line: int) -> None:
        self.resource = resource
        self.line = line
        self.guard: str | None = None  # result var gating an :arg acquire
        self.release_lines: list[int] = []
        self.reported: set[str] = set()  # codes already filed


class _FnWalker:
    """Walk one function body threading (env, dead) through branches.

    env: handle-key -> _Handle for LIVE handles on the current path.
    dead: handle-key -> (handle, kind) after release/transfer/handoff.
    peeked: var -> resource (assigned from a manager peek method).
    """

    def __init__(
        self, an: "_Analysis", sf, fn, cls_name: str | None,
        exempt: set[str] | None = None,
    ) -> None:
        self.an = an
        self.sf = sf
        self.fn = fn
        self.cls = cls_name
        # Resources whose protocol THIS method implements: a protocol
        # method's body is exempt from its own resource's rules (it IS
        # the implementation) but fully checked for every other
        # resource it uses (apply_bundle releases `bundles` yet must
        # still balance the `pages` it allocates).
        self.exempt = exempt or set()
        self.peeked: dict[str, str] = {}
        self.transfers = an.reg.transfers.get(fn.name, set())
        # Stack of protector frames: (finally-set, handler-set) of
        # (key, resource) released there. finally covers every exit;
        # a broad except handler covers only exception edges.
        self.protectors: list[tuple[set, set]] = []

    # ---- findings ---------------------------------------------------- #

    def _file(self, rule: str, code: str, line: int, msg: str) -> None:
        self.an.findings.append(Finding(rule, code, self.sf.path, line, msg))

    def leak(self, h: _Handle, why: str) -> None:
        """RL001, once per acquisition, at the acquisition line."""
        if "RL001" in h.reported:
            return
        h.reported.add("RL001")
        partial = (
            f" (released at line {h.release_lines[0]} on another path)"
            if h.release_lines else ""
        )
        self._file(
            "release-on-all-paths", "RL001", h.line,
            f"{h.resource} handle acquired here {why}{partial} — release "
            "or transfer it on every exit path (try/finally, a declared "
            "handoff into `# llmd: owns(...)` state, or a "
            "`# llmd: transfers(...)` boundary)",
        )

    # ---- helpers ----------------------------------------------------- #

    def protected(self, env, h: _Handle, exc: bool = True) -> bool:
        """A handle is protected when ANY of its live aliases is
        released in an enclosing finally (every exit) or — for
        exception edges only — a broad except handler."""
        keys = [k for k, v in env.items() if v is h]
        for fin, handler in self.protectors:
            for k in keys:
                if (k, h.resource) in fin:
                    return True
                if exc and (k, h.resource) in handler:
                    return True
        return False

    def _match(self, call: ast.Call):
        """match_call filtered by this method's own-protocol exemption."""
        hit = self.an.reg.match_call(call, self.cls)
        if hit is not None and hit[0].name in self.exempt:
            return None
        return hit

    def _release_keys_in(self, stmts) -> set[tuple[str, str]]:
        """(handle-key, resource) pairs a finally/except body releases,
        transfers, or hands off — the exception-edge protectors."""
        out: set[tuple[str, str]] = set()
        for stmt in stmts:
            for call in (
                n for n in ast.walk(stmt) if isinstance(n, ast.Call)
            ):
                hit = self._match(call)
                if hit is not None and hit[1] in ("release", "transfer"):
                    idx = hit[2] if isinstance(hit[2], int) else 1
                    if len(call.args) >= idx:
                        key = _name_chain(call.args[idx - 1])
                        if key:
                            out.add((key, hit[0].name))
                    continue
                for res in self.an.reg.transfers.get(
                    call.func.attr if isinstance(call.func, ast.Attribute)
                    else call.func.id if isinstance(call.func, ast.Name)
                    else "", ()
                ):
                    for a in call.args:
                        key = _name_chain(a)
                        if key:
                            out.add((key, res))
        return out

    def _bind(self, env, dead, key: str, h: _Handle) -> None:
        if key in env and env[key] is not h:
            self.leak(env[key], f"is overwritten at line {h.line} while "
                                "still live")
        env[key] = h
        dead.pop(key, None)
        self.peeked.pop(key, None)

    def _kill(self, env, dead, h: _Handle, kind: str, line: int) -> None:
        """Release/transfer/handoff: drop every alias of ``h``."""
        h.release_lines.append(line)
        for k in [k for k, v in env.items() if v is h]:
            del env[k]
            dead[k] = (h, kind)

    def _narrow(self, env, test: ast.expr, branch_true: bool) -> None:
        """Guard narrowing: in the branch where the acquire provably
        failed (`x is None`, `not x` / falsy), the handle never existed."""
        def drop(var: str, when_true: bool) -> None:
            if when_true != branch_true:
                return
            doomed = {
                id(h) for k, h in env.items()
                if k == var or h.guard == var
            }
            for k in [k for k, h in env.items() if id(h) in doomed]:
                del env[k]

        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            key = _name_chain(test.left)
            is_none = (
                isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None
            )
            if key and is_none:
                if isinstance(test.ops[0], ast.Is):
                    drop(key, True)
                elif isinstance(test.ops[0], ast.IsNot):
                    drop(key, False)
        elif isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            key = _name_chain(test.operand)
            if key:
                drop(key, True)
        else:
            key = _name_chain(test)
            if key:
                drop(key, False)

    # ---- call classification ----------------------------------------- #

    def _handle_args(self, call: ast.Call, env) -> list[tuple[str, _Handle]]:
        out = []
        for a in call.args + [kw.value for kw in call.keywords]:
            key = _name_chain(a)
            if key and key in env:
                out.append((key, env[key]))
        return out

    def _callee_name(self, call: ast.Call) -> str | None:
        if isinstance(call.func, ast.Attribute):
            return call.func.attr
        if isinstance(call.func, ast.Name):
            return call.func.id
        return None

    def _owns_mutation_attr(self, call: ast.Call) -> str | None:
        """``x.<attr>.append(...)`` -> attr when attr is owns-annotated."""
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _OWNS_MUTATORS
            and isinstance(f.value, ast.Attribute)
            and f.value.attr in self.an.reg.owns
        ):
            return f.value.attr
        return None

    def _risky(self, call: ast.Call) -> bool:
        if self._match(call) is not None:
            return False
        name = self._callee_name(call)
        if name in _SAFE_CALLS or name in _SAFE_METHODS:
            return False
        return True

    def process_calls(self, stmt, env, dead, consumed: set[int]) -> None:
        """Generic pass: nested acquires, transfers-callees, handoffs
        into owns state, and the exception-edge check — over every call
        in the statement not already consumed by the specific forms."""
        calls = [n for n in ast.walk(stmt) if isinstance(n, ast.Call)]
        for call in calls:
            if id(call) in consumed:
                continue
            # handoff: mutator on an owns attribute consuming handles
            # (or direct acquire-call arguments).
            owns_attr = self._owns_mutation_attr(call)
            if owns_attr is not None:
                owned = self.an.reg.owns[owns_attr]
                for key, h in self._handle_args(call, env):
                    if h.resource in owned:
                        self._kill(env, dead, h, "handoff", call.lineno)
                for a in call.args:
                    if isinstance(a, ast.Call):
                        hit = self._match(a)
                        if hit is not None and hit[1] == "acquire":
                            consumed.add(id(a))  # acquired-and-stored
            # handoff: keyword matching an owns attribute (dataclass /
            # constructor fields), e.g. Bundle(stream_ids=ids).
            for kw in call.keywords:
                if kw.arg and kw.arg in self.an.reg.owns:
                    key = _name_chain(kw.value)
                    if key and key in env and (
                        env[key].resource in self.an.reg.owns[kw.arg]
                    ):
                        self._kill(env, dead, env[key], "handoff", call.lineno)
                    if isinstance(kw.value, ast.Call):
                        hit = self._match(kw.value)
                        if hit is not None and hit[1] == "acquire":
                            consumed.add(id(kw.value))
            # handoff: transfers-marked callee consumes handle args.
            callee = self._callee_name(call)
            for res in self.an.reg.transfers.get(callee or "", ()):
                for key, h in self._handle_args(call, env):
                    if h.resource == res:
                        self._kill(env, dead, h, "handoff", call.lineno)
        for call in calls:
            if id(call) in consumed:
                continue
            hit = self._match(call)
            if hit is None:
                continue
            p, kind, mode = hit
            consumed.add(id(call))
            if kind == "acquire":
                if mode == "ret":
                    h = _Handle(p.name, call.lineno)
                    self.leak(h, "but the result is discarded")
                elif isinstance(mode, int) and len(call.args) >= mode:
                    key = _name_chain(call.args[mode - 1])
                    if key:
                        self._bind(env, dead, key,
                                   _Handle(p.name, call.lineno))
            else:
                idx = mode if isinstance(mode, int) else 1
                if len(call.args) < idx:
                    continue
                key = _name_chain(call.args[idx - 1])
                if key is None:
                    continue
                if key in env and env[key].resource == p.name:
                    self._kill(env, dead, env[key],
                               "released" if kind == "release" else
                               "transferred", call.lineno)
                elif key in dead and dead[key][1] == "released" \
                        and kind == "release":
                    self._file(
                        "release-pairing", "RL002", call.lineno,
                        f"double release of {p.name} handle `{key}` — "
                        f"already released at line "
                        f"{dead[key][0].release_lines[0]} on this path",
                    )
                elif self.peeked.get(key) == p.name and kind == "release":
                    self._file(
                        "release-pairing", "RL002", call.lineno,
                        f"release of {p.name} handle `{key}` that was "
                        "only peeked (assigned from a non-acquire "
                        "manager method), never acquired on this path",
                    )
        # Exception-edge: any risky call with live, unprotected handles
        # acquired on an EARLIER line (same-statement acquisition is the
        # acquire itself).
        for call in calls:
            if id(call) not in consumed and self._risky(call):
                seen: set[int] = set()
                for key, h in list(env.items()):
                    if id(h) in seen:
                        continue
                    seen.add(id(h))
                    if h.line < stmt.lineno and not self.protected(env, h):
                        self.leak(
                            h,
                            f"crosses an exception-capable call at line "
                            f"{call.lineno} with no covering finally",
                        )
                break

    # ---- statement walk ---------------------------------------------- #

    def walk_body(self, stmts, env, dead) -> bool:
        """Returns True when control cannot fall off the end."""
        terminated = False
        for stmt in stmts:
            if terminated:
                break
            terminated = self.walk_stmt(stmt, env, dead)
        return terminated

    def walk_stmt(self, stmt, env, dead) -> bool:
        reg = self.an.reg
        consumed: set[int] = set()
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            tname = (
                targets[0].id
                if len(targets) == 1 and isinstance(targets[0], ast.Name)
                else None
            )
            just_peeked = None
            if isinstance(value, ast.Call):
                hit = self._match(value)
                if hit is not None and hit[1] == "acquire":
                    consumed.add(id(value))
                    p, _, mode = hit
                    h = _Handle(p.name, value.lineno)
                    if mode == "ret":
                        if tname is not None:
                            self._bind(env, dead, tname, h)
                        else:
                            # stored straight into state
                            self._store(targets[0], h, env, dead,
                                        fresh=True)
                    elif isinstance(mode, int) and len(value.args) >= mode:
                        key = _name_chain(value.args[mode - 1])
                        if key:
                            h.guard = tname
                            self._bind(env, dead, key, h)
                elif hit is None and tname is not None:
                    res = reg.peek_call(value, self.cls)
                    if res is not None and res not in self.exempt:
                        self.peeked[tname] = res
                        just_peeked = tname
            elif tname is not None and isinstance(value, ast.Name) \
                    and value.id in env:
                # alias: both names refer to the same live handle
                env[tname] = env[value.id]
                dead.pop(tname, None)
                self.process_calls(stmt, env, dead, consumed)
                return False
            # stores of live handles into attributes / subscripts
            if value is not None:
                vkeys = [
                    _name_chain(v)
                    for v in ([value] + (
                        list(value.elts)
                        if isinstance(value, (ast.Tuple, ast.List)) else []
                    ))
                ]
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        for vk in vkeys:
                            if vk and vk in env:
                                self._store_into(t, env[vk], env, dead)
            # plain rebind of a tracked name to something else
            if tname is not None and not (
                isinstance(value, ast.Call) and id(value) in consumed
            ):
                if tname in env and not (
                    isinstance(value, ast.Name) and value.id in env
                    and env[value.id] is env[tname]
                ):
                    # rebound away: the alias is gone (under-flag —
                    # other aliases may still release it)
                    h = env.pop(tname)
                    if h.guard == tname:
                        h.guard = None
                dead.pop(tname, None)
                if tname != just_peeked:
                    self.peeked.pop(tname, None)
            self.process_calls(stmt, env, dead, consumed)
            return False
        if isinstance(stmt, ast.Expr):
            self.process_calls(stmt, env, dead, consumed)
            return False
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._handle_return_value(stmt, env, dead, consumed)
            self.process_calls(stmt, env, dead, consumed)
            self._exit(env, f"but not released on the return at line "
                            f"{stmt.lineno}")
            return True
        if isinstance(stmt, ast.Raise):
            self.process_calls(stmt, env, dead, consumed)
            self._exit(env, f"but not released on the raise at line "
                            f"{stmt.lineno}", exc=True)
            return True
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return True
        if isinstance(stmt, ast.If):
            # A (possibly negated) acquire call used AS the test belongs
            # to _apply_test — consume it so the generic pass does not
            # also bind the handle on the failure branch.
            tcall = stmt.test
            if isinstance(tcall, ast.UnaryOp) and isinstance(
                tcall.op, ast.Not
            ):
                tcall = tcall.operand
            if isinstance(tcall, ast.Call):
                hit = self._match(tcall)
                if hit is not None and hit[1] == "acquire":
                    consumed.add(id(tcall))
            self.process_calls(stmt.test, env, dead, consumed)
            env_t, dead_t = dict(env), dict(dead)
            env_f, dead_f = dict(env), dict(dead)
            self._apply_test(stmt.test, env_t, env_f, dead_t, dead_f)
            term_t = self.walk_body(stmt.body, env_t, dead_t)
            term_f = self.walk_body(stmt.orelse, env_f, dead_f)
            self._merge(env, dead, [
                (env_t, dead_t, term_t), (env_f, dead_f, term_f)
            ])
            return term_t and term_f
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self.process_calls(stmt.test, env, dead, consumed)
            else:
                self.process_calls(stmt.iter, env, dead, consumed)
            env_b, dead_b = dict(env), dict(dead)
            term = self.walk_body(stmt.body, env_b, dead_b)
            if not term:
                for key, h in env_b.items():
                    if key not in env and not self.protected(env_b, h):
                        self.leak(h, "but a loop iteration can end with "
                                     "it still live")
            # after the loop: keep the pre-loop view, honoring releases
            # the body performed (under-flag: the body may run 0 times)
            for key in list(env):
                if key not in env_b and key in dead_b:
                    dead[key] = dead_b[key]
                    del env[key]
            self.walk_body(stmt.orelse, env, dead)
            return False
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if isinstance(item.context_expr, ast.Call):
                    hit = self._match(item.context_expr)
                    if hit is not None and hit[1] == "acquire":
                        # context-manager form: release is structural
                        consumed.add(id(item.context_expr))
                self.process_calls(item.context_expr, env, dead, consumed)
            return self.walk_body(stmt.body, env, dead)
        if isinstance(stmt, ast.Try):
            fin = self._release_keys_in(stmt.finalbody)
            handler_rel: set = set()
            for handler in stmt.handlers:
                if handler.type is None or (
                    isinstance(handler.type, ast.Name)
                    and handler.type.id in ("Exception", "BaseException")
                ) or (
                    isinstance(handler.type, ast.Tuple)
                ):
                    handler_rel |= self._release_keys_in(handler.body)
            self.protectors.append((fin, handler_rel))
            env_entry, dead_entry = dict(env), dict(dead)
            term_b = self.walk_body(stmt.body, env, dead)
            term_b = self.walk_body(stmt.orelse, env, dead) or term_b
            self.protectors.pop()
            branches = [(env, dead, term_b)]
            for handler in stmt.handlers:
                env_h, dead_h = dict(env_entry), dict(dead_entry)
                term_h = self.walk_body(handler.body, env_h, dead_h)
                branches.append((env_h, dead_h, term_h))
            merged_env: dict = {}
            merged_dead: dict = {}
            self._merge(merged_env, merged_dead, branches)
            env.clear(); env.update(merged_env)
            dead.clear(); dead.update(merged_dead)
            term = all(t for _, _, t in branches)
            if stmt.finalbody:
                term_f = self.walk_body(stmt.finalbody, env, dead)
                term = term or term_f
            return term
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.an.walk_function(self.sf, stmt, self.cls, self.exempt)
            return False
        if isinstance(stmt, ast.ClassDef):
            return False
        self.process_calls(stmt, env, dead, consumed)
        return False

    def _apply_test(self, test, env_t, env_f, dead_t, dead_f) -> None:
        # acquire call used directly as a condition (`if take_probe(x):`
        # / `if not take_probe(x):`): the handle exists only in the
        # branch where the call returned truthy.
        call, negate = test, False
        if isinstance(call, ast.UnaryOp) and isinstance(call.op, ast.Not):
            call, negate = call.operand, True
        if isinstance(call, ast.Call):
            hit = self._match(call)
            if hit is not None and hit[1] == "acquire":
                p, _, mode = hit
                if isinstance(mode, int) and len(call.args) >= mode:
                    key = _name_chain(call.args[mode - 1])
                    if key:
                        h = _Handle(p.name, call.lineno)
                        target = env_f if negate else env_t
                        other_dead = dead_t if negate else dead_f
                        target[key] = h
                        other_dead.pop(key, None)
                return
        self._narrow(env_t, test, branch_true=True)
        self._narrow(env_f, test, branch_true=False)

    def _merge(self, env, dead, branches) -> None:
        """Live-on-any-surviving-path semantics."""
        live = [(e, d) for e, d, term in branches if not term]
        env.clear()
        dead.clear()
        for e, d in live:
            for k, v in d.items():
                dead.setdefault(k, v)
        for e, d in live:
            for k, h in e.items():
                env[k] = h
                dead.pop(k, None)

    def _store(self, target, h: _Handle, env, dead, fresh=False) -> None:
        self._store_into(target, h, env, dead)

    def _store_into(self, target, h: _Handle, env, dead) -> None:
        """Assignment of a live handle into an attribute/subscript:
        a declared handoff when the attribute is owns-annotated for the
        handle's resource, an RL003 escape otherwise."""
        attr = None
        t = target
        if isinstance(t, ast.Subscript):
            t = t.value
        if isinstance(t, ast.Attribute):
            attr = t.attr
        elif isinstance(t, ast.Name):
            attr = t.id
        owned = self.an.reg.owns.get(attr or "", ())
        if h.resource in owned:
            self._kill(env, dead, h, "handoff", target.lineno)
            return
        if "RL003" not in h.reported:
            h.reported.add("RL003")
            self._file(
                "escaping-handle", "RL003", target.lineno,
                f"{h.resource} handle (acquired at line {h.line}) stored "
                f"into `{attr}`, which is not annotated "
                f"`# llmd: owns({h.resource})` — ownership escapes the "
                "checked scope",
            )
        # escaped: stop tracking so the site gets exactly one finding
        self._kill(env, dead, h, "escaped", target.lineno)

    def _handle_return_value(self, stmt, env, dead, consumed) -> None:
        value = stmt.value
        if isinstance(value, (ast.Tuple, ast.List)):
            vals = list(value.elts)
        elif isinstance(value, ast.Dict):
            vals = list(value.values)
        else:
            vals = [value]
        for v in vals:
            key = _name_chain(v)
            h = env.get(key) if key else None
            if h is None and isinstance(v, ast.Call):
                hit = self._match(v)
                if hit is not None and hit[1] == "acquire" \
                        and hit[2] == "ret":
                    consumed.add(id(v))
                    h = _Handle(hit[0].name, v.lineno)
                    env["<ret>"] = h
                    key = "<ret>"
            if h is None:
                continue
            if h.resource in self.transfers:
                self._kill(env, dead, h, "handoff", stmt.lineno)
            elif "RL003" not in h.reported:
                h.reported.add("RL003")
                h.reported.add("RL001")  # the return IS the leak site
                self._file(
                    "escaping-handle", "RL003", stmt.lineno,
                    f"{h.resource} handle (acquired at line {h.line}) "
                    f"returned from {self.fn.name}, which is not marked "
                    f"`# llmd: transfers({h.resource})` — callers cannot "
                    "know they now own it",
                )
                self._kill(env, dead, h, "escaped", stmt.lineno)

    def _exit(self, env, why: str, exc: bool = False) -> None:
        seen: set[int] = set()
        for key, h in list(env.items()):
            if id(h) in seen or self.protected(env, h, exc=exc):
                continue
            seen.add(id(h))
            self.leak(h, why)


# ------------------------------------------------------------------ #
# analysis cache (three checkers share one pass)


class _Analysis:
    def __init__(self, repo: Repo) -> None:
        self.findings: list[Finding] = []
        self.reg, reg_findings = build_registry(repo)
        self.findings.extend(reg_findings)
        self._widen_registry(repo)
        for sf in repo.files:
            if not sf.is_python or sf.tree is None:
                continue
            self._walk_module(sf)
        self.findings.sort(key=lambda f: (f.path, f.line, f.code))

    def _widen_registry(self, repo: Repo) -> None:
        """A scoped scan (--changed-only, explicit paths) must still see
        protocol/owns/transfers declarations living in UNCHANGED files —
        a changed caller of PageAllocator.allocate is checkable only if
        the allocator's annotation is in the registry. Declarations are
        re-discovered from the default scan set under repo.root; the
        scoped files alone decide WHERE findings are reported."""
        from llmd_tpu.analysis.core import discover

        known = {sf.path for sf in repo.files}
        extra = [
            sf for sf in discover(repo.root)
            if sf.is_python and sf.path not in known
        ]
        if not extra:
            return
        wide, _ = build_registry(Repo(repo.root, extra))
        seen = {(p.path, p.line) for p in self.reg.protocols}
        for p in wide.protocols:
            if (p.path, p.line) not in seen:
                self.reg.add_protocol(p)
        for attr, names in wide.owns.items():
            self.reg.owns.setdefault(attr, set()).update(names)
        for fn, names in wide.transfers.items():
            self.reg.transfers.setdefault(fn, set()).update(names)

    def _walk_module(self, sf) -> None:
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                own = [
                    q for q in self.reg.protocols
                    if q.cls == node.name and q.path == sf.path
                ]
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        # A protocol method IS its resource's
                        # implementation — exempt from that ONE
                        # resource's rules, fully checked for every
                        # other resource it uses (apply_bundle releases
                        # `bundles` but must still balance `pages`).
                        exempt = {
                            q.name for q in own if item.name in q.methods
                        }
                        self.walk_function(sf, item, node.name, exempt)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.walk_function(sf, node, None)

    def walk_function(self, sf, fn, cls_name, exempt=None) -> None:
        w = _FnWalker(self, sf, fn, cls_name, exempt)
        env: dict = {}
        dead: dict = {}
        terminated = w.walk_body(fn.body, env, dead)
        if not terminated:
            end = getattr(fn, "end_lineno", fn.lineno)
            w._exit(env, f"but still live when {fn.name} falls off the "
                         f"end (line {end})")


def _analysis_for(repo: Repo) -> _Analysis:
    cached = getattr(repo, "_lifecycle_analysis", None)
    if cached is None:
        cached = repo._lifecycle_analysis = _Analysis(repo)
    return cached


class _LifecycleRule(Checker):
    def run(self, repo: Repo) -> list[Finding]:
        return [
            f for f in _analysis_for(repo).findings if f.rule == self.name
        ]


@register
class ReleaseOnAllPaths(_LifecycleRule):
    name = "release-on-all-paths"
    description = (
        "every declared-resource acquisition reaches a release/transfer "
        "or annotated handoff on every exit path, incl. exception edges "
        "(RL001)"
    )


@register
class ReleasePairing(_LifecycleRule):
    name = "release-pairing"
    description = (
        "no double-release and no release of a merely-peeked handle "
        "for declared resource protocols (RL002)"
    )


@register
class EscapingHandle(_LifecycleRule):
    name = "escaping-handle"
    description = (
        "handles stored into non-`owns` state or returned without a "
        "`transfers` marker leak ownership out of the checked scope "
        "(RL003)"
    )
