"""direct-clock: the control stack reads time through the clock seam.

The routing/control plane (``epp/``, ``autoscale/``, ``predictor/``,
``batch/``) is driven by the fleet simulator (``fleetsim/``, included
in scope) through a virtual-time event loop: every time-dependent
decision — breaker cooldowns, flow-control TTLs and EDF deadlines,
scrape freshness, session TTLs, WVA retention windows, batch job
deadlines/timestamps and gate freshness — must read
:func:`llmd_tpu.clock.monotonic` (wall-clock unix-seconds semantics:
:func:`llmd_tpu.clock.time`, the batch plane's timestamp seam) or an
injected clock callable, never ``time.time()`` / ``time.monotonic()``
directly. One stray direct call
silently splits the plane between real and simulated time: the soak
still *runs*, but cooldowns measured on the wall clock while sleeps run
on virtual time makes recovery bounds meaningless and the scoreboard
nondeterministic — a bug class invisible to runtime tests, which is why
it is pinned statically.

Flagged inside the scope dirs (call or bare reference, any import
alias):

- ``time.time`` / ``time.monotonic`` attribute access;
- ``from time import time`` / ``from time import monotonic``.

``time.sleep`` and friends stay legal — blocking is visible behavior,
not a clock read (and async code paths use ``asyncio.sleep``, which the
simulator virtualizes via the event loop). Genuinely wall-clock reads
(none today) take ``# llmd: allow(direct-clock) -- <reason>``.

Rule: CK001.
"""

from __future__ import annotations

import ast
from pathlib import Path

from llmd_tpu.analysis.core import Checker, Finding, Repo, register

SCOPE_PARTS = frozenset(
    {"epp", "autoscale", "predictor", "fleetsim", "batch"}
)

_CLOCK_ATTRS = frozenset({"time", "monotonic"})


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf) -> None:
        self.sf = sf
        self.findings: list[Finding] = []
        # Local names bound to the stdlib time module ("time", "_time"...).
        self.time_aliases: set[str] = set()

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "direct-clock", "CK001", self.sf.path, node.lineno,
            f"{what} bypasses the clock seam: read "
            "llmd_tpu.clock.monotonic() (or an injected clock callable) "
            "so the fleet simulator can drive this code on virtual time, "
            "or pragma `# llmd: allow(direct-clock) -- <reason>`",
        ))

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "time":
                self.time_aliases.add(alias.asname or "time")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for alias in node.names:
                if alias.name in _CLOCK_ATTRS:
                    self._flag(
                        node, f"`from time import {alias.name}`"
                    )
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id in self.time_aliases
            and node.attr in _CLOCK_ATTRS
        ):
            self._flag(node, f"`{node.value.id}.{node.attr}`")
        self.generic_visit(node)


@register
class ClockDisciplineChecker(Checker):
    name = "direct-clock"
    description = (
        "epp//autoscale//predictor//fleetsim/ read time via the "
        "llmd_tpu.clock seam (simulator-drivable), never time.time()/"
        "time.monotonic() directly"
    )

    def run(self, repo: Repo) -> list[Finding]:
        findings: list[Finding] = []
        for sf in repo.files:
            if not sf.is_python or sf.tree is None:
                continue
            if not SCOPE_PARTS.intersection(Path(sf.path).parts):
                continue
            v = _Visitor(sf)
            v.visit(sf.tree)
            findings.extend(v.findings)
        return findings
