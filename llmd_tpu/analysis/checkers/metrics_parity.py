"""metrics-parity: EngineStats ↔ /metrics exposition ↔ dashboards/docs.

PR 2 showed how a 1.6k-line change lets the three surfaces drift: a
counter lands on ``EngineStats``, the exposition page emits it, and no
dashboard or doc ever mentions it (or a dashboard keys on a name the
engine no longer emits — a silently-empty panel). This checker diffs
the three surfaces; an orphan in ANY direction is a finding.

Inputs (found by convention inside the scan set):

- exposition: a ``metrics.py`` defining ``render_metrics`` — emitted
  names are the ``gauges``/``counters`` dict keys + subscript
  assignments, ``(name, stats.field)`` tuples, and ``vllm:``/``llmd:``
  literals in the source.
- stats: a module defining a class named ``EngineStats`` — its
  dataclass fields.
- dashboards/alerts: ``*.json``/``*.yaml`` under a path containing
  ``observability`` — referenced names are the prefixed
  ``vllm:name``/``llmd:name`` tokens.
- docs: a markdown file named ``observability.md``.

Names are canonicalized (family prefix stripped, histogram
``_bucket``/``_sum``/``_count`` suffixes folded onto the base name).

Rules: MP001 emitted-but-on-no-dashboard, MP002 emitted-but-
undocumented, MP003 dashboard-references-unemitted, MP004 EngineStats
field the exposition never reads.
"""

from __future__ import annotations

import ast
import re

from llmd_tpu.analysis.core import Checker, Finding, Repo, register

_PREFIXED = re.compile(r"\b(?:vllm|llmd):([a-z][a-z0-9_]*)")
_HIST_SUFFIX = re.compile(r"_(bucket|sum|count)$")

# EngineStats fields that are inputs to emitted metrics rather than
# metrics themselves (label payloads, histogram raw form).
_STATS_LABEL_FIELDS = frozenset({
    "running_lora_adapters", "waiting_lora_adapters",
})


def _canon(name: str) -> str:
    return _HIST_SUFFIX.sub("", name)


def _emitted_names(sf) -> dict[str, int]:
    """{canonical metric name: lineno} emitted by render_metrics."""
    out: dict[str, int] = {}

    def add(name: str, line: int) -> None:
        out.setdefault(_canon(name), line)

    tree = sf.tree
    if tree is not None:
        for node in ast.walk(tree):
            # gauges = {...} / counters = {...}
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
                targets = {
                    t.id for t in node.targets if isinstance(t, ast.Name)
                }
                if targets & {"gauges", "counters"}:
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                            k.value, str
                        ):
                            add(k.value, k.lineno)
            # gauges["x"] = ... / counters["x"] = ...
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in ("gauges", "counters")
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)
                    ):
                        add(t.slice.value, t.lineno)
            # ("name", stats.field) emission tuples
            if (
                isinstance(node, ast.Tuple)
                and len(node.elts) == 2
                and isinstance(node.elts[0], ast.Constant)
                and isinstance(node.elts[0].value, str)
                and re.fullmatch(r"[a-z][a-z0-9_]*", node.elts[0].value or "")
                and isinstance(node.elts[1], ast.Attribute)
                and isinstance(node.elts[1].value, ast.Name)
                and node.elts[1].value.id == "stats"
            ):
                add(node.elts[0].value, node.lineno)
    for i, line in enumerate(sf.lines, 1):
        for m in _PREFIXED.finditer(line):
            add(m.group(1), i)
        # f-string emission under both families: f"{family}:name..."
        for m in re.finditer(r"\{family\}:([a-z][a-z0-9_]*)", line):
            add(m.group(1), i)
    return out


def _stats_fields(repo: Repo) -> dict[str, tuple[str, int]]:
    """{field: (path, lineno)} of the EngineStats dataclass."""
    for sf in repo.files:
        if not sf.is_python or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == "EngineStats":
                fields = {}
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name
                    ):
                        fields[stmt.target.id] = (sf.path, stmt.lineno)
                return fields
    return {}


def _stats_reads(sf) -> set[str]:
    if sf.tree is None:
        return set()
    return {
        node.attr
        for node in ast.walk(sf.tree)
        if isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "stats"
    }


@register
class MetricsParityChecker(Checker):
    name = "metrics-parity"
    description = (
        "EngineStats fields, /metrics exposition names, and dashboard/"
        "doc references must agree in all directions"
    )

    def run(self, repo: Repo) -> list[Finding]:
        metrics_files = [
            sf for sf in repo.named("metrics.py")
            if "def render_metrics" in sf.text
        ]
        if not metrics_files:
            return []
        msf = metrics_files[0]
        emitted = _emitted_names(msf)

        dash_files = [
            sf for sf in repo.files
            if "observability" in sf.path.split("/")
            and (sf.path.endswith(".json") or sf.path.endswith(".yaml"))
        ]
        referenced: dict[str, str] = {}  # canon name -> first referencing file
        for sf in dash_files:
            for m in _PREFIXED.finditer(sf.text):
                referenced.setdefault(_canon(m.group(1)), sf.path)

        docs = [sf for sf in repo.named("observability.md")]
        doc_text = docs[0].text if docs else None

        findings: list[Finding] = []
        for name, line in sorted(emitted.items()):
            if dash_files and name not in referenced:
                findings.append(Finding(
                    "metrics-parity", "MP001", msf.path, line,
                    f"metric {name!r} is emitted but referenced by no "
                    "dashboard or alert under observability/ — unobserved "
                    "telemetry rots; panel it or drop it",
                ))
            if doc_text is not None and not re.search(
                rf"\b{re.escape(name)}\b", doc_text
            ):
                findings.append(Finding(
                    "metrics-parity", "MP002", msf.path, line,
                    f"metric {name!r} is emitted but not mentioned in "
                    "observability.md's metric reference",
                ))
        for name, where in sorted(referenced.items()):
            if name not in emitted:
                findings.append(Finding(
                    "metrics-parity", "MP003", where, 1,
                    f"dashboard/alert references vllm:/llmd: metric "
                    f"{name!r} which the engine exposition "
                    "(serve/metrics.py) does not emit — the panel will "
                    "render empty forever",
                ))
        fields = _stats_fields(repo)
        if fields:
            reads = _stats_reads(msf)
            for field, (path, line) in sorted(fields.items()):
                if field in reads or field in _STATS_LABEL_FIELDS:
                    continue
                findings.append(Finding(
                    "metrics-parity", "MP004", path, line,
                    f"EngineStats.{field} is never read by render_metrics "
                    "— the stat is collected but unobservable",
                ))
        return findings
