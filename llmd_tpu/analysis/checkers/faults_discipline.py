"""broad-except (faults discipline): no silent failure swallows on the
serving stack.

PR 7's fault matrix only proves the degradations it knows about; the
degradations it can NEVER know about are the ones a broad ``except``
invents ad hoc — catch everything, log (or not), carry on. Inside the
serving-stack packages (``serve/``, ``engine/``, ``kvtransfer/``,
``epp/``, ``kvstore/``) every handler broader than a named-exception
tuple (bare ``except``, ``except Exception``, ``except BaseException``,
or a tuple containing either) must do one of:

- **re-raise** — the handler contains a ``raise`` (cleanup-then-
  propagate is not a swallow);
- **leave a metric trail** — the enclosing function assigns/increments
  a failure-ish counter (a target whose dotted/subscript path contains
  ``fail``/``failure``/``fallback``/``error``/``drop`` — the
  ``*_failures_total`` family and its raw-field forms), so the SLO
  layer can see the degradation happening;
- **carry a pragma** — ``# llmd: allow(broad-except) -- <reason>`` on
  the handler line (or the line above), for the genuinely-benign
  best-effort paths (``__del__``, log-only observer hooks), with the
  reason recorded.

Rule: FD001.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from llmd_tpu.analysis.core import Checker, Finding, Repo, register

# Package directories on the serving path (matched against path parts,
# so fixtures under tmp trees participate the same way). federation/
# and events/ joined with the concurrency rules: their publisher/
# subscriber threads are exactly where a swallowed failure goes
# permanently dark (the unused-pragma report caught federation/ pragmas
# blessing a rule that never ran there).
SCOPE_PARTS = frozenset({
    "serve", "engine", "kvtransfer", "epp", "kvstore", "federation",
    "events",
})

_BROAD_NAMES = {"Exception", "BaseException"}

_FAILURE_RE = re.compile(r"(fail|failure|fallback|error|drop)", re.I)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in _BROAD_NAMES:
            return True
    return False


def _target_path(node: ast.expr) -> str:
    """Flatten an assignment target into a dotted string for matching:
    ``self.transfer_failures[("a", "b")]`` -> ``self.transfer_failures``."""
    parts: list[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            break
        else:
            break
    return ".".join(reversed(parts))


def _has_failure_counter(fn: ast.AST) -> bool:
    """Does this function body assign/increment a failure-ish counter?"""
    for node in ast.walk(fn):
        targets: list[ast.expr] = []
        if isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.Assign):
            targets = list(node.targets)
        for t in targets:
            if _FAILURE_RE.search(_target_path(t)):
                return True
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf) -> None:
        self.sf = sf
        self.fn_stack: list[ast.AST] = []
        self.findings: list[Finding] = []

    def _visit_fn(self, node) -> None:
        self.fn_stack.append(node)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if _is_broad(node) and not _reraises(node):
            fn = self.fn_stack[-1] if self.fn_stack else None
            if fn is None or not _has_failure_counter(fn):
                what = (
                    "bare except" if node.type is None else
                    "except broader than a named-exception tuple"
                )
                self.findings.append(Finding(
                    "broad-except", "FD001", self.sf.path, node.lineno,
                    f"{what} swallows failures invisibly on the serving "
                    "stack: re-raise, increment a *_failures_total-style "
                    "counter in this function, or pragma "
                    "`# llmd: allow(broad-except) -- <reason>`",
                ))
        self.generic_visit(node)


@register
class BroadExceptChecker(Checker):
    name = "broad-except"
    description = (
        "broad excepts in serve//engine//kvtransfer//epp//kvstore/ must "
        "re-raise, leave a failure-counter trail, or carry a pragma"
    )

    def run(self, repo: Repo) -> list[Finding]:
        findings: list[Finding] = []
        for sf in repo.files:
            if not sf.is_python or sf.tree is None:
                continue
            if not SCOPE_PARTS.intersection(Path(sf.path).parts):
                continue
            v = _Visitor(sf)
            v.visit(sf.tree)
            findings.extend(v.findings)
        return findings
