"""host-sync: no host↔device synchronization outside the declared
readback sites.

The async step pipeline (docs/architecture/async-scheduling.md) exists
because each engine step makes exactly ONE coalesced host transfer —
``ModelRunner.wait_step``. Any other sync in a hot-path module
(``engine/``, ``ops/``, ``parallel/``) blocks the dispatching thread on
device completion and silently re-serializes the pipeline. The rule
flags the unambiguous sync primitives everywhere in hot-path modules,
and host coercions (``int``/``float``/``np.asarray``/…) when the
operand is provably a device array (annotated ``jax.Array`` or assigned
from a ``jnp.*``/``jax.*`` call).

Declared readback sites (everything else needs a pragma with a reason):

- ``ModelRunner.wait_step`` — the per-step coalesced token readback.
- ``ModelRunner.download_pages`` — KV staging download, runs on a
  staging thread off the step loop by contract.
- ``distributed.replicated_to_host`` — the multi-host local-replica
  read ``wait_step`` delegates to.
"""

from __future__ import annotations

import ast

from llmd_tpu.analysis.core import Checker, Finding, Repo, register

# (file basename, dotted qualname) pairs whose bodies may sync.
ALLOWED_SITES = frozenset({
    ("runner.py", "ModelRunner.wait_step"),
    ("runner.py", "ModelRunner.download_pages"),
    ("distributed.py", "replicated_to_host"),
})

_SYNC_PRIMITIVES = {
    "device_get": ("HS001", "jax.device_get blocks on device completion"),
    "block_until_ready": ("HS002", "block_until_ready is a host sync"),
    "item": ("HS003", ".item() forces a device->host transfer"),
}

_COERCERS_NP = {"asarray", "array", "ascontiguousarray"}
_COERCERS_BUILTIN = {"int", "float", "bool"}

_DEVICE_ROOTS = {"jnp", "jax"}

# jax.* calls whose results are HOST metadata, not device arrays
# (coercing them costs nothing and must not trip the coercion rule).
_HOST_RESULT_ATTRS = frozenset({
    "devices", "local_devices", "device_count", "local_device_count",
    "process_index", "process_count",
})


def _is_jax_array_annotation(node: ast.expr | None) -> bool:
    """``jax.Array`` / ``jnp.ndarray`` / ``jax.Array | None``-style."""
    if node is None:
        return False
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _is_jax_array_annotation(node.left) or _is_jax_array_annotation(
            node.right
        )
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return (node.value.id, node.attr) in (
            ("jax", "Array"), ("jnp", "ndarray")
        )
    return False


def _call_root(node: ast.expr) -> str | None:
    """Leftmost Name of a dotted callee: ``jax.lax.foo`` -> ``jax``."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_device_call(node: ast.expr) -> bool:
    """A call whose result lives on device: jnp.*, jax.* (minus the sync
    primitives, which are host results and flagged separately)."""
    if not isinstance(node, ast.Call):
        return False
    root = _call_root(node.func)
    if root not in _DEVICE_ROOTS:
        return False
    if isinstance(node.func, ast.Attribute) and node.func.attr in (
        {"device_get"} | _HOST_RESULT_ATTRS
    ):
        return False
    return True


class _FunctionScope:
    def __init__(self, qualname: str) -> None:
        self.qualname = qualname
        self.device_names: set[str] = set()


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf) -> None:
        self.sf = sf
        self.stack: list[_FunctionScope] = []
        self.findings: list[Finding] = []

    # -------------------------------------------------------------- #

    def _qual(self, name: str) -> str:
        if self.stack:
            return f"{self.stack[-1].qualname}.{name}"
        return name

    def _allowed(self) -> bool:
        return any(
            (self.sf.name, s.qualname) in ALLOWED_SITES for s in self.stack
        )

    def _device_like(self, node: ast.expr) -> bool:
        """Conservatively: is this expression a device array?"""
        # Peel subscripts/attribute reads: pooled[:n] is as device as pooled.
        while isinstance(node, (ast.Subscript, ast.Starred)):
            node = node.value
        if isinstance(node, ast.Name):
            return any(node.id in s.device_names for s in self.stack[-1:])
        if _is_device_call(node):
            return True
        return False

    def _flag(self, node: ast.AST, code: str, msg: str) -> None:
        if self._allowed():
            return
        self.findings.append(Finding(
            "host-sync", code, self.sf.path, node.lineno,
            f"{msg} in hot-path module (declared readback sites: "
            "ModelRunner.wait_step / download_pages / replicated_to_host; "
            "pragma `# llmd: allow(host-sync) -- <reason>` if this read "
            "is off the step loop by design)",
        ))

    # -------------------------------------------------------------- #

    def _enter_function(self, node) -> None:
        # Decorators evaluate in the enclosing scope.
        for d in node.decorator_list:
            self.visit(d)
        scope = _FunctionScope(self._qual(node.name))
        args = node.args
        for a in (
            *args.posonlyargs, *args.args, *args.kwonlyargs,
            *( [args.vararg] if args.vararg else [] ),
            *( [args.kwarg] if args.kwarg else [] ),
        ):
            if _is_jax_array_annotation(a.annotation):
                scope.device_names.add(a.arg)
        self.stack.append(scope)
        for stmt in node.body:
            self.visit(stmt)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_function(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_function(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        for d in node.decorator_list:
            self.visit(d)
        self.stack.append(_FunctionScope(self._qual(node.name)))
        for stmt in node.body:
            self.visit(stmt)
        self.stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        if self.stack and _is_device_call(node.value):
            for t in node.targets:
                names = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for n in names:
                    if isinstance(n, ast.Name):
                        self.stack[-1].device_names.add(n.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "device_get" and _call_root(func) == "jax":
                self._flag(node, *_SYNC_PRIMITIVES["device_get"])
            elif func.attr == "block_until_ready" and (
                # method form x.block_until_ready() OR the module-level
                # jax.block_until_ready(x) spelling
                not node.args or _call_root(func) == "jax"
            ):
                self._flag(node, *_SYNC_PRIMITIVES["block_until_ready"])
            elif func.attr == "item" and not node.args:
                self._flag(node, *_SYNC_PRIMITIVES["item"])
            elif (
                func.attr in _COERCERS_NP
                and isinstance(func.value, ast.Name)
                and func.value.id == "np"
                and node.args
                and self._device_like(node.args[0])
            ):
                self._flag(
                    node, "HS004",
                    f"np.{func.attr} of a device array is a blocking "
                    "device->host transfer",
                )
        elif (
            isinstance(func, ast.Name)
            and func.id in _COERCERS_BUILTIN
            and len(node.args) == 1
            and self._device_like(node.args[0])
        ):
            self._flag(
                node, "HS004",
                f"{func.id}() of a device array is a blocking "
                "device->host transfer",
            )
        self.generic_visit(node)


@register
class HostSyncChecker(Checker):
    name = "host-sync"
    description = (
        "host<->device syncs in engine/ops/parallel hot paths must flow "
        "through the declared coalesced readback sites"
    )

    def run(self, repo: Repo) -> list[Finding]:
        findings: list[Finding] = []
        for sf in repo.files:
            if not sf.is_python or not sf.hot_path or sf.tree is None:
                continue
            v = _Visitor(sf)
            v.visit(sf.tree)
            findings.extend(v.findings)
        return findings
