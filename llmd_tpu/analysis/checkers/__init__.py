"""Checker registry: importing this package registers every checker."""

from llmd_tpu.analysis.checkers import (  # noqa: F401
    clock_discipline,
    concurrency,
    config_parity,
    deploy_parity,
    envvars,
    faults_discipline,
    host_sync,
    lifecycle,
    lockstep,
    metrics_parity,
    trace,
)
