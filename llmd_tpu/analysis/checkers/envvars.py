"""envvars: shell scripts declare their environment-variable surface.

The contract from ``scripts/ENVVARS.md`` (previously enforced by the
standalone ``scripts/lint-envvars.py``, now a thin shim over this
checker): an all-caps variable may be read only if the script
(a) requires it with ``${VAR:?...}``, (b) defaults it with
``${VAR:-...}`` / ``${VAR:=...}``, (c) assigns it first, or
(d) declares it in an ``# env: VAR`` comment. (Role model: the
reference's scripts/lint-envvars.py env-declaration lint; independent
implementation.)

Scope: ``*.sh`` files, plus the shell embedded in ``deploy/**/*.yaml``
(``sh -c`` container blocks). The shell regexes are YAML-safe by
construction — Kubernetes' own ``$(VAR)`` substitution syntax never
matches ``$VAR``/``${VAR}`` shell reads, so a manifest with no
embedded shell produces no findings — which lets the whole file run
through :func:`lint_lines` with real line numbers.
"""

from __future__ import annotations

import re

from llmd_tpu.analysis.core import Checker, Finding, Repo, register

EXEMPT = {
    "PATH", "HOME", "PWD", "OLDPWD", "TMPDIR", "USER", "SHELL", "LANG",
    "LC_ALL", "TERM", "HOSTNAME", "RANDOM", "SECONDS", "LINENO", "OPTARG",
    "OPTIND", "IFS", "EUID", "UID", "PPID", "BASH_SOURCE", "FUNCNAME",
}

USE_RE = re.compile(r"\$\{?([A-Z][A-Z0-9_]*)\b")
DECL_RE = re.compile(r"^\s*#\s*env:\s*([A-Z0-9_ ,]+)")
GUARD_RE = re.compile(r"\$\{([A-Z][A-Z0-9_]*)(:?[-=?+])")
ASSIGN_RE = re.compile(r"^\s*(?:export\s+)?([A-Z][A-Z0-9_]*)=")
FOR_RE = re.compile(r"\bfor\s+([A-Z][A-Z0-9_]*)\b")


def lint_lines(lines: list[str]) -> list[tuple[int, str, str]]:
    """(lineno, var, message) per undeclared use — the shared core both
    the checker and the scripts/lint-envvars.py shim call."""
    declared: set[str] = set(EXEMPT)
    # Pass 1: collect declarations anywhere in the file — a guard at the
    # top blesses every later bare use of the same var.
    for line in lines:
        m = DECL_RE.match(line)
        if m:
            declared.update(v for v in re.split(r"[ ,]+", m.group(1)) if v)
        for m in GUARD_RE.finditer(line):
            declared.add(m.group(1))
        m = ASSIGN_RE.match(line)
        if m:
            declared.add(m.group(1))
        m = FOR_RE.search(line)
        if m:
            declared.add(m.group(1))
    # Pass 2: flag bare uses of anything never declared.
    errors: list[tuple[int, str, str]] = []
    for i, line in enumerate(lines, 1):
        code = line.split("#", 1)[0]  # ignore comments
        for m in USE_RE.finditer(code):
            var = m.group(1)
            if var not in declared:
                errors.append((
                    i, var,
                    f"{var} used without declaration/default "
                    "(see scripts/ENVVARS.md)",
                ))
                declared.add(var)  # one report per var per file
    return errors


@register
class EnvvarsChecker(Checker):
    name = "envvars"
    description = (
        "shell scripts declare every env var they read (guard, assign, "
        "or `# env: VAR` comment; scripts/ENVVARS.md)"
    )

    def run(self, repo: Repo) -> list[Finding]:
        findings: list[Finding] = []
        for sf in repo.files:
            if not (
                sf.path.endswith(".sh")
                or (
                    sf.path.endswith((".yaml", ".yml"))
                    and sf.path.startswith("deploy/")
                )
            ):
                continue
            for line, _var, msg in lint_lines(sf.lines):
                findings.append(
                    Finding("envvars", "EV001", sf.path, line, msg)
                )
        return findings
