"""deploy-parity: the rendered deploy surface ↔ the code it deploys.

The manifests under ``deploy/`` ARE the product surface, and every
probe path, container flag, env var, and port in them names something
the Python tree must actually provide. Nothing fails at render time
when they drift — a readiness probe against a route the binary never
registered just marks the pod unready forever, a misspelled flag
aborts at pod start, an env var nobody reads is dead configuration.
This checker renders the whole surface via
:mod:`llmd_tpu.analysis.manifests` (every kustomize root + the Helm
chart values matrix) and diffs the resolved objects against the code
inventories the other parity checkers already trust: ``add_argument``
flags, aiohttp GET routes, and env-var string constants.

Rules:

- DP001 **schema-shape** — the stdlib kubeconform stand-in: every
  object's kind is in the registry with the right apiVersion and its
  required fields present; Deployment selectors match their template
  labels; no duplicate (kind, name) within a unit; no duplicate
  container port names/numbers in a pod; render failures (a patch
  whose target moved, an unparseable template) are DP001 findings too.
- DP002 **flag-parity** — every ``--flag`` a container passes to an
  ``llmd_tpu.*`` module must exist in that module's CLI (dotted-file
  modules also accept their package ``__main__`` flags — the
  dp_supervisor hands post-``--`` args to serve).
- DP003 **env-parity** — both directions: every ``LLMD_*``/``VLLM_*``
  var a manifest sets must be read somewhere in the Python tree, and
  every such var the code reads must be settable/visible somewhere
  outside it (a manifest env stanza, docs, or a shell script) —
  orphans are configuration knowledge that exists only in the source.
- DP004 **probe-parity** — httpGet probe paths must be routes the
  target module actually serves (engine ``/ready``, routers
  ``/readyz`` — docs/architecture/fault-tolerance.md's probe
  contract); readiness must use the module's readiness route when it
  has one; probe ports must resolve to declared container ports (or a
  ``--port``/``--health-port`` arg); and the primary container of a
  routed pod (role-labeled or Service-backed) must declare liveness
  and readiness probes at all.
- DP005 **port/scrape-parity** — Service targetPort ↔ containerPort ↔
  ``--port`` arg; PodMonitor endpoints and ``prometheus.io/*`` scrape
  annotations must point at a declared port on a container whose
  module serves ``/metrics``.

Suppression uses the same pragma grammar as everywhere else, as a YAML
comment on the offending line or the line above::

    # llmd: allow(deploy-parity) -- <reason>
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from llmd_tpu.analysis import manifests
from llmd_tpu.analysis.core import Checker, Finding, Repo, register
from llmd_tpu.analysis.manifests import source_line

# kind -> (accepted apiVersions, required top-level dotted field paths).
# The stdlib kubeconform stand-in: enough schema to catch a pasted-in
# object with the wrong group or a gutted spec.
KIND_REGISTRY: dict[str, tuple[set[str], tuple[str, ...]]] = {
    "Deployment": ({"apps/v1"}, ("spec.selector", "spec.template")),
    "Service": ({"v1"}, ("spec.ports",)),
    "ConfigMap": ({"v1"}, ()),
    "Secret": ({"v1"}, ()),
    "ServiceAccount": ({"v1"}, ()),
    "Namespace": ({"v1"}, ()),
    "PersistentVolumeClaim": ({"v1"}, ("spec.accessModes",)),
    "Role": ({"rbac.authorization.k8s.io/v1"}, ("rules",)),
    "RoleBinding": (
        {"rbac.authorization.k8s.io/v1"}, ("roleRef", "subjects"),
    ),
    "LeaderWorkerSet": (
        {"leaderworkerset.x-k8s.io/v1"}, ("spec.leaderWorkerTemplate",),
    ),
    "Gateway": (
        {"gateway.networking.k8s.io/v1", "gateway.networking.k8s.io/v1beta1"},
        ("spec.gatewayClassName", "spec.listeners"),
    ),
    "HTTPRoute": (
        {"gateway.networking.k8s.io/v1", "gateway.networking.k8s.io/v1beta1"},
        ("spec.rules",),
    ),
    "InferencePool": (
        {"inference.networking.x-k8s.io/v1alpha2"},
        ("spec.selector", "spec.targetPortNumber"),
    ),
    "PodMonitor": (
        {"monitoring.coreos.com/v1"},
        ("spec.selector", "spec.podMetricsEndpoints"),
    ),
    "ScaledObject": ({"keda.sh/v1alpha1"}, ("spec.scaleTargetRef",)),
    "CustomResourceDefinition": (
        {"apiextensions.k8s.io/v1"},
        ("spec.group", "spec.names", "spec.versions"),
    ),
    "DestinationRule": (
        {"networking.istio.io/v1beta1", "networking.istio.io/v1"},
        ("spec.host",),
    ),
}

# Role-label values the EPP's k8s-selectors route to. Pods carrying
# other roles (e.g. decode-worker follower ranks, which serve no HTTP)
# are not admission-gated, so probes are validated but not required.
ROUTED_ROLES = frozenset({"prefill", "decode", "prefill-decode", "encode"})

ROLE_LABEL = "llm-d.ai/role"

_FLAG_RE = re.compile(r"--[A-Za-z][A-Za-z0-9-]*")
_MODULE_RE = re.compile(r"\bllmd_tpu(?:\.[A-Za-z_]\w*)+")
_ENV_VAR_RE = re.compile(r"\b(?:LLMD|VLLM)_[A-Z0-9_]+\b")

WORKLOAD_KINDS = ("Deployment", "LeaderWorkerSet", "StatefulSet", "DaemonSet")


# ------------------------------------------------------------------ #
# code inventories (built from the scan set, like config-parity, so
# fixture trees and --changed-only behave consistently)


def _module_of_path(path: str) -> str | None:
    parts = Path(path).parts
    if "llmd_tpu" not in parts:
        return None
    i = parts.index("llmd_tpu")
    rel = parts[i:]
    if not rel[-1].endswith(".py"):
        return None
    if rel[-1] == "__main__.py" or rel[-1] == "__init__.py":
        rel = rel[:-1]
    else:
        rel = rel[:-1] + (rel[-1][:-3],)
    return ".".join(rel)


def _package_of(module: str) -> str:
    return ".".join(module.split(".")[:2])


def _flag_inventory(repo: Repo) -> dict[str, set[str]]:
    """module -> {--flag} from every add_argument call in the tree."""
    inv: dict[str, set[str]] = {}
    for sf in repo.files:
        if not sf.is_python or "add_argument" not in sf.text:
            continue
        mod = _module_of_path(sf.path)
        if mod is None or sf.tree is None:
            continue
        flags = inv.setdefault(mod, set())
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
            ):
                for arg in node.args:
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ) and arg.value.startswith("--"):
                        flags.add(arg.value)
    return inv


def _endpoint_inventory(repo: Repo) -> dict[str, set[str]]:
    """package -> {GET route path} from web.get()/add_get() calls."""
    inv: dict[str, set[str]] = {}
    for sf in repo.files:
        if not sf.is_python or sf.tree is None:
            continue
        mod = _module_of_path(sf.path)
        if mod is None:
            continue
        pkg = _package_of(mod)
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "add_get")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("/")
            ):
                inv.setdefault(pkg, set()).add(node.args[0].value)
    return inv


def _env_read_inventory(repo: Repo) -> dict[str, tuple[str, int]]:
    """LLMD_*/VLLM_* string constants in the Python tree (exact-match
    constants are programmatic uses: environ.get, _env fallbacks, env
    dict keys — prose in docstrings never matches exactly). The
    linter's own package is excluded: rule text and exempt lists name
    vars without reading them."""
    out: dict[str, tuple[str, int]] = {}
    for sf in repo.files:
        if not sf.is_python or sf.tree is None:
            continue
        parts = Path(sf.path).parts
        if "llmd_tpu" not in parts or "analysis" in parts:
            continue
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and re.fullmatch(r"(?:LLMD|VLLM)_[A-Z0-9_]+", node.value)
            ):
                out.setdefault(node.value, (sf.path, node.lineno))
    return out


# ------------------------------------------------------------------ #
# manifest walking


def _pod_templates(obj: dict) -> list[dict]:
    spec = obj.get("spec") or {}
    out = []
    if obj.get("kind") in WORKLOAD_KINDS and isinstance(
        spec.get("template"), dict
    ):
        out.append(spec["template"])
    lwt = spec.get("leaderWorkerTemplate") or {}
    for key in ("leaderTemplate", "workerTemplate"):
        if isinstance(lwt.get(key), dict):
            out.append(lwt[key])
    return out


def _tmpl_labels(tmpl: dict) -> dict:
    return (tmpl.get("metadata") or {}).get("labels") or {}


def _containers(tmpl: dict, init: bool = False) -> list[dict]:
    spec = tmpl.get("spec") or {}
    key = "initContainers" if init else "containers"
    return [c for c in spec.get(key) or [] if isinstance(c, dict)]


def _command_text(c: dict) -> str:
    toks = list(c.get("command") or []) + list(c.get("args") or [])
    return " ".join(str(t) for t in toks)


def _container_module(c: dict) -> str | None:
    m = _MODULE_RE.search(_command_text(c))
    return m.group(0) if m else None


def _container_ports(c: dict) -> tuple[dict[str, int], set[int]]:
    names: dict[str, int] = {}
    numbers: set[int] = set()
    for p in c.get("ports") or []:
        if not isinstance(p, dict):
            continue
        num = p.get("containerPort")
        if isinstance(num, int):
            numbers.add(num)
            if p.get("name"):
                names[str(p["name"])] = num
    return names, numbers


def _arg_ports(text: str) -> set[int]:
    out = set()
    for m in re.finditer(r"--(?:port|health-port)[= ](\d+)", text):
        out.add(int(m.group(1)))
    return out


def _get_path(obj: dict, dotted: str):
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _selected(selector: dict, labels: dict) -> bool:
    return bool(selector) and all(
        labels.get(k) == v for k, v in selector.items()
    )


@register
class DeployParityChecker(Checker):
    name = "deploy-parity"
    description = (
        "rendered deploy/ + chart objects match the code they deploy: "
        "schema shape (DP001), container flags exist in the module CLI "
        "(DP002), env vars are read in-tree and settable somewhere "
        "(DP003), probes hit real routes on declared ports (DP004), "
        "Service/scrape ports line up with containerPorts and --port "
        "(DP005)"
    )

    def run(self, repo: Repo) -> list[Finding]:
        if manifests.load_yaml() is None:
            return []  # render layer gated off without pyyaml
        corpus = manifests.render_corpus(repo.root)
        if not corpus.objects and not corpus.errors:
            return []
        self._by_path = {sf.path: sf for sf in repo.files}
        self._seen: set[tuple] = set()
        self._findings: list[Finding] = []
        flags = _flag_inventory(repo)
        endpoints = _endpoint_inventory(repo)

        for src, msg in corpus.errors:
            self._emit("DP001", src, 1, f"deploy surface unrenderable: {msg}")

        by_unit = corpus.by_unit()
        for unit, ros in by_unit.items():
            self._check_unit_schema(unit, ros)
            services = [
                ro for ro in ros if ro.obj.get("kind") == "Service"
            ]
            for ro in ros:
                for tmpl in _pod_templates(ro.obj):
                    self._check_pod(
                        ro, tmpl, services, flags, endpoints,
                    )
            self._check_services(unit, ros)
            self._check_monitors(unit, ros, endpoints)

        self._check_env_parity(repo, corpus)
        return self._findings

    # -- plumbing -------------------------------------------------- #

    def _emit(self, code: str, src: str, line: int, msg: str) -> None:
        """Anchor a finding to a scanned source file; findings in files
        outside the scan set are dropped (--changed-only semantics)."""
        if src not in self._by_path:
            return
        key = (code, src, line, msg)
        if key in self._seen:
            return
        self._seen.add(key)
        self._findings.append(Finding("deploy-parity", code, src, line, msg))

    def _anchor(self, ro: manifests.RenderedObject, needle: str) -> int:
        sf = self._by_path.get(ro.source)
        return source_line(sf.text, needle) if sf else 1

    # -- DP001 ----------------------------------------------------- #

    def _check_unit_schema(
        self, unit: str, ros: list[manifests.RenderedObject]
    ) -> None:
        names: dict[tuple[str, str], str] = {}
        for ro in ros:
            obj = ro.obj
            kind = obj.get("kind")
            name = (obj.get("metadata") or {}).get("name")
            if not kind or not isinstance(kind, str):
                self._emit("DP001", ro.source, 1, "object without a kind")
                continue
            line = self._anchor(ro, f"name: {name}" if name else kind)
            if not name:
                self._emit(
                    "DP001", ro.source, line,
                    f"{kind} object has no metadata.name",
                )
            reg = KIND_REGISTRY.get(kind)
            if reg is None:
                self._emit(
                    "DP001", ro.source, line,
                    f"unknown kind {kind!r}: not in the deploy-parity "
                    "kind/apiVersion registry (add it with its required "
                    "fields if the kind is intentional)",
                )
                continue
            versions, required = reg
            api = obj.get("apiVersion")
            if api not in versions:
                self._emit(
                    "DP001", ro.source, line,
                    f"{kind}/{name}: apiVersion {api!r} is not the "
                    f"registered {sorted(versions)}",
                )
            for dotted in required:
                if _get_path(obj, dotted) is None:
                    self._emit(
                        "DP001", ro.source, line,
                        f"{kind}/{name}: required field {dotted} missing",
                    )
            if name:
                key = (kind, str(name))
                if key in names and names[key] == unit:
                    self._emit(
                        "DP001", ro.source, line,
                        f"duplicate {kind}/{name} in unit {unit}",
                    )
                names[key] = unit
            if kind == "Deployment":
                sel = _get_path(obj, "spec.selector.matchLabels") or {}
                tmpls = _pod_templates(obj)
                labels = _tmpl_labels(tmpls[0]) if tmpls else {}
                for k, v in sel.items():
                    if labels.get(k) != v:
                        self._emit(
                            "DP001", ro.source, line,
                            f"Deployment/{name}: selector {k}={v} does "
                            "not match the pod template labels — the "
                            "deployment can never adopt its own pods",
                        )
            for tmpl in _pod_templates(obj):
                for c in _containers(tmpl):
                    pnames: set[str] = set()
                    pnums: set[int] = set()
                    for p in c.get("ports") or []:
                        if not isinstance(p, dict):
                            continue
                        num = p.get("containerPort")
                        pname = p.get("name")
                        if isinstance(num, int):
                            if num in pnums:
                                self._emit(
                                    "DP001", ro.source,
                                    self._anchor(ro, str(num)),
                                    f"{kind}/{name} container "
                                    f"{c.get('name')}: duplicate "
                                    f"containerPort {num}",
                                )
                            pnums.add(num)
                        if pname:
                            if pname in pnames:
                                self._emit(
                                    "DP001", ro.source,
                                    self._anchor(ro, str(pname)),
                                    f"{kind}/{name} container "
                                    f"{c.get('name')}: duplicate port "
                                    f"name {pname!r}",
                                )
                            pnames.add(str(pname))

    # -- DP002 + DP004 (per pod) ----------------------------------- #

    def _check_pod(
        self,
        ro: manifests.RenderedObject,
        tmpl: dict,
        services: list[manifests.RenderedObject],
        flags: dict[str, set[str]],
        endpoints: dict[str, set[str]],
    ) -> None:
        labels = _tmpl_labels(tmpl)
        routed = labels.get(ROLE_LABEL) in ROUTED_ROLES or any(
            _selected((s.obj.get("spec") or {}).get("selector") or {}, labels)
            for s in services
        )
        primary_claimed = False
        for c in _containers(tmpl):
            text = _command_text(c)
            module = _container_module(c)
            if module is None:
                continue
            # DP002: every flag must exist in the module CLI (plus the
            # package __main__'s for dotted file modules: dp_supervisor
            # forwards post-`--` args to serve).
            allowed = set(flags.get(module, ()))
            if module.count(".") >= 2:
                allowed |= flags.get(_package_of(module), set())
            if flags and (module in flags or _package_of(module) in flags):
                for flag in sorted(set(_FLAG_RE.findall(text))):
                    if flag not in allowed:
                        self._emit(
                            "DP002", ro.source, self._anchor(ro, flag),
                            f"container {c.get('name')} passes {flag} "
                            f"but {module} declares no such flag — it "
                            "aborts at pod start",
                        )
            eps = endpoints.get(_package_of(module), set())
            self._check_probes(
                ro, c, module, eps, text,
                required=(
                    routed and not primary_claimed and bool(eps)
                ),
            )
            if routed and eps and not primary_claimed:
                primary_claimed = True

    def _check_probes(
        self,
        ro: manifests.RenderedObject,
        c: dict,
        module: str,
        eps: set[str],
        text: str,
        required: bool,
    ) -> None:
        names, numbers = _container_ports(c)
        argports = _arg_ports(text)
        ready_ep = (
            "/ready" if "/ready" in eps
            else "/readyz" if "/readyz" in eps
            else None
        )
        live_ep = next(
            (p for p in ("/health", "/healthz") if p in eps),
            sorted(eps)[0] if eps else "",
        )
        for probe_key in ("livenessProbe", "readinessProbe", "startupProbe"):
            probe = c.get(probe_key)
            if not isinstance(probe, dict):
                if required and probe_key in (
                    "livenessProbe", "readinessProbe"
                ):
                    self._emit(
                        "DP004", ro.source,
                        self._anchor(ro, f"name: {c.get('name')}"),
                        f"routed container {c.get('name')} ({module}) "
                        f"has no {probe_key} — "
                        + (
                            "the router admits traffic to pods that "
                            "never proved ready"
                            if probe_key == "readinessProbe"
                            else "a wedged process is never restarted"
                        )
                        + "; probe " + (
                            (ready_ep or live_ep)
                            if probe_key == "readinessProbe"
                            else live_ep
                        ),
                    )
                continue
            http = probe.get("httpGet")
            if not isinstance(http, dict):
                continue
            path = http.get("path")
            if eps and path not in eps:
                self._emit(
                    "DP004", ro.source, self._anchor(ro, f"path: {path}"),
                    f"{probe_key} of container {c.get('name')} probes "
                    f"{path} but {module} serves only "
                    f"{', '.join(sorted(eps))} — the probe can never "
                    "succeed",
                )
            elif (
                probe_key == "readinessProbe"
                and ready_ep is not None
                and path != ready_ep
            ):
                self._emit(
                    "DP004", ro.source, self._anchor(ro, f"path: {path}"),
                    f"readinessProbe of container {c.get('name')} probes "
                    f"{path}, but {module} has the dedicated readiness "
                    f"route {ready_ep} (fault-tolerance.md probe "
                    "contract) — liveness-style paths report alive, "
                    "not ready-to-admit",
                )
            port = http.get("port")
            if isinstance(port, str):
                if names and port not in names:
                    self._emit(
                        "DP004", ro.source,
                        self._anchor(ro, f"port: {port}"),
                        f"{probe_key} of container {c.get('name')} "
                        f"targets port name {port!r} which is not a "
                        "declared containerPort name "
                        f"({', '.join(sorted(names)) or 'none'})",
                    )
            elif isinstance(port, int):
                if (numbers or argports) and port not in (
                    numbers | argports
                ):
                    self._emit(
                        "DP004", ro.source,
                        self._anchor(ro, f"port: {port}"),
                        f"{probe_key} of container {c.get('name')} "
                        f"targets port {port}, matching no declared "
                        "containerPort or --port/--health-port arg",
                    )

    # -- DP005 ------------------------------------------------------ #

    def _check_services(
        self, unit: str, ros: list[manifests.RenderedObject]
    ) -> None:
        tmpl_index = []
        for ro in ros:
            for tmpl in _pod_templates(ro.obj):
                tmpl_index.append((ro, tmpl))
        for ro in ros:
            obj = ro.obj
            if obj.get("kind") != "Service":
                continue
            spec = obj.get("spec") or {}
            selector = spec.get("selector") or {}
            if not selector:
                continue
            name = (obj.get("metadata") or {}).get("name")
            matched = [
                (wro, tmpl) for wro, tmpl in tmpl_index
                if _selected(selector, _tmpl_labels(tmpl))
            ]
            if not matched:
                self._emit(
                    "DP005", ro.source,
                    self._anchor(ro, f"name: {name}"),
                    f"Service/{name}: selector "
                    f"{selector} matches no pod template in unit "
                    f"{unit} — the Service has no endpoints",
                )
                continue
            port_names: set[str] = set()
            port_numbers: set[int] = set()
            for _, tmpl in matched:
                for c in _containers(tmpl):
                    cn, cnum = _container_ports(c)
                    port_names |= set(cn)
                    port_numbers |= cnum
            for p in spec.get("ports") or []:
                if not isinstance(p, dict):
                    continue
                target = p.get("targetPort", p.get("port"))
                if isinstance(target, str) and target not in port_names:
                    self._emit(
                        "DP005", ro.source,
                        self._anchor(ro, str(target)),
                        f"Service/{name}: targetPort {target!r} names "
                        "no containerPort on the selected pods "
                        f"({', '.join(sorted(port_names)) or 'none'})",
                    )
                elif (
                    isinstance(target, int)
                    and port_numbers
                    and target not in port_numbers
                ):
                    self._emit(
                        "DP005", ro.source,
                        self._anchor(ro, str(target)),
                        f"Service/{name}: targetPort {target} matches "
                        "no containerPort on the selected pods "
                        f"({sorted(port_numbers)})",
                    )
        # --port/--health-port ↔ containerPort on every container that
        # declares ports.
        for ro in ros:
            for tmpl in _pod_templates(ro.obj):
                for c in _containers(tmpl):
                    if _container_module(c) is None:
                        continue
                    _, numbers = _container_ports(c)
                    if not numbers:
                        continue
                    for port in sorted(_arg_ports(_command_text(c))):
                        if port not in numbers:
                            self._emit(
                                "DP005", ro.source,
                                self._anchor(ro, str(port)),
                                f"container {c.get('name')} listens on "
                                f"--port/--health-port {port} but "
                                "declares containerPorts "
                                f"{sorted(numbers)} — the Service/probe "
                                "plumbing can't reach it",
                            )
        # prometheus.io scrape annotations.
        for ro in ros:
            for tmpl in _pod_templates(ro.obj):
                ann = (tmpl.get("metadata") or {}).get("annotations") or {}
                if str(ann.get("prometheus.io/scrape")).lower() != "true":
                    continue
                sport = ann.get("prometheus.io/port")
                spath = ann.get("prometheus.io/path", "/metrics")
                numbers: set[int] = set()
                for c in _containers(tmpl):
                    _, cnum = _container_ports(c)
                    numbers |= cnum
                line = self._anchor(ro, "prometheus.io/")
                try:
                    pnum = int(sport)
                except (TypeError, ValueError):
                    pnum = None
                if pnum is None or (numbers and pnum not in numbers):
                    self._emit(
                        "DP005", ro.source, line,
                        f"prometheus.io/scrape points at port {sport!r} "
                        "which is no declared containerPort "
                        f"({sorted(numbers)})",
                    )
                if spath != "/metrics":
                    self._emit(
                        "DP005", ro.source, line,
                        f"prometheus.io/path {spath!r}: the in-tree "
                        "servers export /metrics only",
                    )

    def _check_monitors(
        self,
        unit: str,
        ros: list[manifests.RenderedObject],
        endpoints: dict[str, set[str]],
    ) -> None:
        tmpls = [
            tmpl for ro in ros for tmpl in _pod_templates(ro.obj)
        ]
        for ro in ros:
            obj = ro.obj
            if obj.get("kind") != "PodMonitor":
                continue
            name = (obj.get("metadata") or {}).get("name")
            spec = obj.get("spec") or {}
            sel = _get_path(obj, "spec.selector.matchLabels") or {}
            matched = [
                t for t in tmpls if _selected(sel, _tmpl_labels(t))
            ]
            line = self._anchor(ro, f"name: {name}")
            if sel and not matched:
                self._emit(
                    "DP005", ro.source, line,
                    f"PodMonitor/{name}: selector matches no pod "
                    f"template in unit {unit} — nothing gets scraped",
                )
                continue
            for ep in spec.get("podMetricsEndpoints") or []:
                if not isinstance(ep, dict):
                    continue
                pname = ep.get("port")
                path = ep.get("path", "/metrics")
                owners = [
                    c
                    for t in matched
                    for c in _containers(t)
                    if pname in _container_ports(c)[0]
                ]
                if pname and not owners:
                    self._emit(
                        "DP005", ro.source,
                        self._anchor(ro, str(pname)),
                        f"PodMonitor/{name}: endpoint port {pname!r} "
                        "names no containerPort on the matched pods",
                    )
                    continue
                for c in owners:
                    module = _container_module(c)
                    if module is None:
                        continue
                    eps = endpoints.get(_package_of(module), set())
                    if eps and path not in eps:
                        self._emit(
                            "DP005", ro.source,
                            self._anchor(ro, str(path)),
                            f"PodMonitor/{name}: scrapes {path} but "
                            f"{module} serves only "
                            f"{', '.join(sorted(eps))}",
                        )

    # -- DP003 ------------------------------------------------------ #

    def _check_env_parity(
        self, repo: Repo, corpus: manifests.Corpus
    ) -> None:
        code_env = _env_read_inventory(repo)
        has_python = any(
            sf.is_python and "llmd_tpu" in Path(sf.path).parts
            for sf in repo.files
        )
        # Direction 1: every LLMD_/VLLM_ var a manifest sets is read.
        manifest_vars: set[str] = set()
        for ro in corpus.objects:
            for tmpl in _pod_templates(ro.obj):
                for c in _containers(tmpl) + _containers(tmpl, init=True):
                    for env in c.get("env") or []:
                        if not isinstance(env, dict):
                            continue
                        var = str(env.get("name", ""))
                        if not _ENV_VAR_RE.fullmatch(var):
                            continue
                        manifest_vars.add(var)
                        if has_python and var not in code_env:
                            self._emit(
                                "DP003", ro.source,
                                self._anchor(ro, var),
                                f"manifest sets {var} but nothing in "
                                "the Python tree reads it — dead "
                                "configuration",
                            )
        # Direction 2: every var the code reads is settable/documented
        # somewhere outside the Python tree.
        other_text = "\n".join(
            sf.text for sf in repo.files
            if sf.path.endswith((".md", ".sh", ".yaml"))
        )
        if not other_text:
            return
        visible = set(_ENV_VAR_RE.findall(other_text)) | manifest_vars
        for var, (path, line) in sorted(code_env.items()):
            if var not in visible:
                self._emit(
                    "DP003", path, line,
                    f"{var} is read here but set nowhere: no manifest "
                    "env stanza, doc, or script mentions it — operators "
                    "cannot discover it (document it or wire it into a "
                    "manifest)",
                )
