"""trace-discipline: jit call sites stay in one-time construction
contexts, and their static/donate metadata matches the traced function.

The engine compiles each traced shape family ONCE (module-level jit,
``_build_*`` factories, ``functools.cached_property``); a ``jax.jit``
call reached per step would retrace/recompile per call and silently
turn a bucketed shape family into a compile-per-shape path. Dispatchers
that broadcast the shape-family ops must also derive their batch shape
through the bucketing helpers (``pad_to_bucket``) or consume prestaged
arrays — an ad-hoc shape is a new compile per distinct batch size.

Rules:

- TD001: ``jax.jit``/``functools.partial(jax.jit, ...)`` called outside
  a construction context (module level, ``__init__``, ``_build_*`` /
  ``_alloc_*`` / ``_warm_*`` methods, ``cached_property`` bodies).
- TD002: ``static_argnames`` naming a parameter the wrapped function
  does not have (jit silently ignores it; the arg is then traced and
  every distinct value compiles a new program).
- TD003: ``donate_argnums`` index out of range for the wrapped function.
- TD004: a method dispatching a shape-family opcode (``_sync`` with
  ``_OP_PREFILL``/``_OP_DECODE``/``_OP_VERIFY``/``_OP_VERIFY_WINDOW``/
  ``_OP_UNIFIED``/``_OP_FLAT``/``_OP_EMBED``) that neither buckets its
  shapes (``pad_to_bucket``) nor consumes a prestaged ``Staged*`` batch
  nor is a declared warmup (``_warm_*``). The flattened-token family
  (``_OP_FLAT``) is shape-disciplined on its T axis alone: the stream
  must ride the fine-grained flat T buckets (staging derives it via
  ``pad_to_bucket`` over ``flat_t_buckets``) with the row-metadata
  width FIXED — an ad-hoc stream length would compile a new program per
  distinct step size, exactly what the one-shape-family design removes.
"""

from __future__ import annotations

import ast

from llmd_tpu.analysis.core import Checker, Finding, Repo, register

_CONSTRUCTION_PREFIXES = ("_build_", "_alloc_", "_warm_")
_CONSTRUCTION_NAMES = {"__init__"}
_SHAPE_FAMILY_OPS = {
    "_OP_PREFILL", "_OP_DECODE", "_OP_VERIFY", "_OP_VERIFY_WINDOW",
    "_OP_UNIFIED", "_OP_FLAT", "_OP_EMBED",
}


def _is_jax_jit(node: ast.expr) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    )


def _is_partial_jit(node: ast.Call) -> bool:
    """functools.partial(jax.jit, ...) / partial(jax.jit, ...)."""
    f = node.func
    name_ok = (
        isinstance(f, ast.Attribute) and f.attr == "partial"
    ) or (isinstance(f, ast.Name) and f.id == "partial")
    return name_ok and bool(node.args) and _is_jax_jit(node.args[0])


def _is_cached_property(deco: ast.expr) -> bool:
    if isinstance(deco, ast.Attribute):
        return deco.attr == "cached_property"
    return isinstance(deco, ast.Name) and deco.id == "cached_property"


def _const_strings(node: ast.expr | None) -> list[str] | None:
    """Names from a static_argnames value, or None when not statically
    resolvable (conditional expressions etc. are skipped, not guessed)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return out
    return None


def _const_ints(node: ast.expr | None) -> list[int] | None:
    """Indices from donate_argnums; conditional forms contribute every
    branch (a donated index must be valid whichever branch ran)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return out
    if isinstance(node, ast.IfExp):
        a = _const_ints(node.body)
        b = _const_ints(node.orelse)
        if a is None or b is None:
            return None
        return a + b
    return None


def _fn_params(fn: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda):
    a = fn.args
    positional = [p.arg for p in (*a.posonlyargs, *a.args)]
    keyword = positional + [p.arg for p in a.kwonlyargs]
    return positional, keyword


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf) -> None:
        self.sf = sf
        self.findings: list[Finding] = []
        # Stack of (function name, is construction context) frames.
        self.frames: list[tuple[str, bool]] = []
        self.module_defs: dict[str, ast.FunctionDef] = {}

    # -------------------------------------------------------------- #

    def _in_construction_context(self) -> bool:
        if not self.frames:
            return True  # module level (incl. decorator lists)
        return any(ok for _, ok in self.frames)

    def _flag(self, node: ast.AST, code: str, msg: str) -> None:
        self.findings.append(
            Finding("trace-discipline", code, self.sf.path, node.lineno, msg)
        )

    def _check_jit_meta(self, call: ast.Call, fn) -> None:
        """Validate static_argnames/donate_argnums against a visible def."""
        positional, keyword = _fn_params(fn)
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                names = _const_strings(kw.value)
                for n in names or ():
                    if n not in keyword:
                        self._flag(
                            call, "TD002",
                            f"static_argnames names {n!r} which is not a "
                            "parameter of the jitted function — jit ignores "
                            "it and the argument is traced (a new compile "
                            "per distinct value)",
                        )
            elif kw.arg == "donate_argnums":
                idxs = _const_ints(kw.value)
                for i in idxs or ():
                    if not (0 <= i < len(positional)):
                        self._flag(
                            call, "TD003",
                            f"donate_argnums index {i} out of range for the "
                            f"jitted function ({len(positional)} positional "
                            "parameters)",
                        )

    # -------------------------------------------------------------- #

    def visit_Module(self, node: ast.Module) -> None:
        for stmt in node.body:
            if isinstance(stmt, ast.FunctionDef):
                self.module_defs[stmt.name] = stmt
        self.generic_visit(node)

    def _enter_function(self, node) -> None:
        cached = any(_is_cached_property(d) for d in node.decorator_list)
        # Decorator expressions evaluate in the ENCLOSING scope; a
        # partial(jax.jit, ...) decorator on this def is checked against
        # this def's signature.
        for d in node.decorator_list:
            call = d if isinstance(d, ast.Call) else None
            if call is not None and (_is_partial_jit(call)):
                self._check_jit_meta(call, node)
            elif _is_jax_jit(d):
                pass  # plain @jax.jit: nothing to cross-check
            else:
                self.visit(d)
        construction = (
            cached
            or node.name in _CONSTRUCTION_NAMES
            or node.name.startswith(_CONSTRUCTION_PREFIXES)
        )
        self.frames.append((node.name, construction))
        for stmt in node.body:
            self.visit(stmt)
        self.frames.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_function(node)
        self._check_dispatch_bucketing(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_function(node)
        self._check_dispatch_bucketing(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # A jit(lambda: ...) at construction scope stays construction.
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        jit_call = _is_jax_jit(node.func) or _is_partial_jit(node)
        if jit_call and not self._in_construction_context():
            where = self.frames[-1][0] if self.frames else "<module>"
            self._flag(
                node, "TD001",
                f"jax.jit called inside {where!r}, which is not a one-time "
                "construction context (module scope, __init__, _build_*/"
                "_alloc_*/_warm_*, cached_property) — a per-call jit "
                "retraces instead of reusing the traced shape family",
            )
        if jit_call:
            # Call-form wrapping of a visible def or inline lambda. A
            # kwargs-only partial(jax.jit, ...) names no target here; its
            # metadata is checked at the decorator/apply site instead.
            if _is_partial_jit(node):
                target = node.args[1] if len(node.args) > 1 else None
            else:
                target = node.args[0] if node.args else None
            fn = None
            if isinstance(target, ast.Lambda):
                fn = target
            elif isinstance(target, ast.Name):
                fn = self.module_defs.get(target.id)
            if fn is not None:
                self._check_jit_meta(node, fn)
        self.generic_visit(node)

    # -------------------------------------------------------------- #

    def _check_dispatch_bucketing(self, fn) -> None:
        """TD004 over a completed function body."""
        if fn.name.startswith("_warm_"):
            return
        ops_dispatched = set()
        calls_pad_to_bucket = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id == "pad_to_bucket":
                calls_pad_to_bucket = True
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "_sync"
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in _SHAPE_FAMILY_OPS
            ):
                ops_dispatched.add(node.args[0].id)
        if not ops_dispatched or calls_pad_to_bucket:
            return
        for a in (*fn.args.posonlyargs, *fn.args.args, *fn.args.kwonlyargs):
            ann = a.annotation
            if isinstance(ann, ast.Name) and ann.id.startswith("Staged"):
                return  # consumes a prestaged (already bucketed) batch
            if (
                isinstance(ann, ast.Constant)
                and isinstance(ann.value, str)
                and ann.value.startswith("Staged")
            ):
                return
        self._flag(
            fn, "TD004",
            f"{fn.name!r} dispatches {sorted(ops_dispatched)} without "
            "deriving its batch shape via pad_to_bucket (or consuming a "
            "prestaged Staged* batch) — ad-hoc shapes compile a new "
            "program per distinct batch size",
        )


@register
class TraceDisciplineChecker(Checker):
    name = "trace-discipline"
    description = (
        "jit stays in one-time construction contexts; static/donate "
        "metadata matches the traced function; dispatches are bucketed"
    )

    def run(self, repo: Repo) -> list[Finding]:
        findings: list[Finding] = []
        for sf in repo.files:
            if not sf.is_python or not sf.hot_path or sf.tree is None:
                continue
            v = _Visitor(sf)
            v.visit(sf.tree)
            findings.extend(v.findings)
        return findings
