"""config-parity: serve CLI flags ↔ config dataclass fields ↔ docs.

The serve CLI promises vLLM-compatible flag names mapped 1:1 onto the
engine's config dataclasses (``serve/__main__.py`` docstring). Drift is
invisible at runtime: a flag whose dataclass field was renamed keeps
parsing and silently stops configuring anything, and an undocumented
flag is unusable knowledge. This checker pins the mapping.

Inputs (by convention inside the scan set): a ``__main__.py`` calling
``add_argument``, a ``config.py`` defining ``EngineConfig``, and the
markdown docs (``docs/**/*.md`` + ``README.md``).

Rules:

- CP001: a flag whose dest is neither a config dataclass field, nor in
  the declared rename map, nor a declared serving-layer-only flag.
- CP002: a rename-map entry pointing at a field that no longer exists.
- CP003: a flag never mentioned (as ``--flag-name``) anywhere in docs.
"""

from __future__ import annotations

import ast
import re

from llmd_tpu.analysis.core import Checker, Finding, Repo, register

# CLI dest -> config field, where the names intentionally differ (the
# vLLM-compatible flag name vs this engine's field name).
FLAG_FIELD_MAP = {
    "block_size": "page_size",
    "num_gpu_blocks_override": "num_blocks",
    "kv_cache_dtype": "dtype",
    "no_enable_prefix_caching": "enable_prefix_caching",
    "kv_swa_ring": "swa_ring",
    "tokenizer": "tokenizer_path",
    "kv_offload_chunks": "cpu_chunks",
    "kv_offload_fs_dir": "fs_dir",
    "kv_store_master_url": "store_master_url",
    "kv_store_segment_bytes": "store_segment_bytes",
    "kv_store_data_port": "store_data_port",
    "kv_publish_policy": "publish_policy",
    "kv_publish_min_hits": "publish_min_hits",
    "kv_decode_paging": "decode_paging",
    "kv_pager_horizon_tokens": "pager_horizon_tokens",
    "lora_adapters": "num_lora_adapters",
    "lora_pool_slots": "lora_dynamic",
    "kv_transfer_config": "kv_role",
}

# Flags that configure the serving process, not the engine config.
SERVING_ONLY = frozenset({
    "model", "served_model_name", "host", "port", "platform",
    "skip_warmup", "advertised_address", "data_parallel_rank",
    "distributed_coordinator", "distributed_num_processes",
    "distributed_process_id", "otlp_traces_endpoint", "trace_file",
    "trace_sample_ratio",
})


def _cli_flags(sf) -> dict[str, int]:
    """{--flag-name: lineno} from add_argument calls."""
    flags: dict[str, int] = {}
    if sf.tree is None:
        return flags
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("--")
        ):
            flags.setdefault(node.args[0].value, node.lineno)
    return flags


def _config_fields(sf) -> set[str]:
    """All dataclass field names across the config module's classes."""
    fields: set[str] = set()
    if sf.tree is None:
        return fields
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    fields.add(stmt.target.id)
    return fields


@register
class ConfigParityChecker(Checker):
    name = "config-parity"
    description = (
        "every serve CLI flag maps to a live config field (or is a "
        "declared serving-layer flag) and is mentioned in the docs"
    )

    def run(self, repo: Repo) -> list[Finding]:
        mains = [
            sf for sf in repo.named("__main__.py")
            if "add_argument" in sf.text and "EngineConfig" in sf.text
        ]
        configs = [
            sf for sf in repo.named("config.py")
            if "class EngineConfig" in sf.text
        ]
        if not mains or not configs:
            return []
        msf, csf = mains[0], configs[0]
        flags = _cli_flags(msf)
        fields = _config_fields(csf)
        doc_files = [
            sf for sf in repo.files
            if sf.path.endswith(".md")
            and (sf.path.startswith("docs/") or sf.path == "README.md")
        ]
        doc_text = "\n".join(sf.text for sf in doc_files)

        findings: list[Finding] = []
        for flag, line in sorted(flags.items()):
            dest = flag[2:].replace("-", "_")
            mapped = FLAG_FIELD_MAP.get(dest)
            if dest in SERVING_ONLY:
                pass
            elif mapped is not None:
                if mapped not in fields:
                    findings.append(Finding(
                        "config-parity", "CP002", msf.path, line,
                        f"flag {flag} maps to config field {mapped!r} "
                        "which no longer exists in config.py — the flag "
                        "parses but configures nothing",
                    ))
            elif dest not in fields:
                findings.append(Finding(
                    "config-parity", "CP001", msf.path, line,
                    f"flag {flag} matches no config dataclass field, no "
                    "FLAG_FIELD_MAP rename, and no declared serving-layer "
                    "flag — if the field was renamed, update the map; if "
                    "the flag is serving-only, declare it",
                ))
            if doc_files and not re.search(
                rf"(?<![\w-]){re.escape(flag)}(?![\w-])", doc_text
            ):
                findings.append(Finding(
                    "config-parity", "CP003", msf.path, line,
                    f"flag {flag} is not mentioned anywhere under docs/ "
                    "or README.md — undocumented flags are unusable "
                    "knowledge",
                ))
        return findings
