"""lockstep: every multi-host opcode has a follower dispatch arm.

Multi-host engines run leader + followers in lockstep: the leader
broadcasts a ``(op, B, QK, greedy)`` header (``ModelRunner._sync_locked``) and
every follower mirrors the dispatch in ``follower_loop``. An opcode
added without a follower arm makes every follower dispatch the WRONG
program (or none), desynchronizing the SPMD collective stream — the
group deadlocks or silently corrupts state. ``_OP_VERIFY`` introduced
exactly this hazard window; this checker closes it permanently.

Applies to any module that defines module-level ``_OP_*`` constants and
a ``follower_loop`` function. Rules:

- LS001: an ``_OP_*`` opcode never compared against ``op`` inside
  ``follower_loop`` (no follower dispatch arm).
- LS002: the follower dispatch chain does not terminate in an ``else``
  that raises — an unknown opcode would silently fall through (or run
  whatever the final branch does).
- LS003: an ``_OP_*`` opcode (other than ``_OP_STOP``, which rides a
  raw header broadcast in ``stop_followers``) that no ``_sync_locked`` call
  site ever broadcasts — dead opcode, or a dispatch path bypassing the
  broadcast.
- LS004: a ``_sync_locked`` call whose op argument is not a named ``_OP_*``
  constant (magic-number dispatch defeats this checker).
- LS005: a jitted step callable (an attribute ``__init__`` assigns from
  a ``_build_*`` factory) invoked outside an ``_exec_*`` method — the
  ``_exec_*`` family is what both the leader dispatch paths and the
  follower arms share; a direct call bypasses the lockstep broadcast.
"""

from __future__ import annotations

import ast

from llmd_tpu.analysis.core import Checker, Finding, Repo, register


def _module_opcodes(tree: ast.Module) -> dict[str, int]:
    """{_OP_name: lineno} for module-level (possibly tuple) assignments."""
    ops: dict[str, int] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for t in stmt.targets:
            names = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for n in names:
                if isinstance(n, ast.Name) and n.id.startswith("_OP_"):
                    ops[n.id] = stmt.lineno
    return ops


def _find_function(tree: ast.Module, name: str):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == name:
                return node
    return None


def _compared_ops(fn) -> set[str]:
    """_OP_* names compared (==/!=/in) anywhere inside ``fn``."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for expr in (node.left, *node.comparators):
                exprs = (
                    expr.elts if isinstance(expr, (ast.Tuple, ast.List))
                    else [expr]
                )
                for e in exprs:
                    if isinstance(e, ast.Name) and e.id.startswith("_OP_"):
                        out.add(e.id)
    return out


def _dispatch_chain_has_else_raise(fn) -> bool:
    """The longest if/elif chain comparing ``op`` must end in a raising
    else. Short guard ifs (``if op == _OP_STOP: return``) are fine."""
    best_len, best_tail = 0, None
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        # Walk this node's elif chain, counting op-comparisons.
        length, cur = 0, node
        while True:
            if any(
                isinstance(e, ast.Name) and e.id.startswith("_OP_")
                for c in ast.walk(cur.test)
                if isinstance(c, ast.Compare)
                for e in (c.left, *c.comparators)
            ):
                length += 1
            nxt = cur.orelse
            if len(nxt) == 1 and isinstance(nxt[0], ast.If):
                cur = nxt[0]
                continue
            break
        if length > best_len:
            best_len, best_tail = length, cur.orelse
    if best_len <= 1:
        return True  # no dispatch chain here (guard-only function)
    return bool(best_tail) and any(
        isinstance(n, ast.Raise)
        for stmt in best_tail
        for n in ast.walk(stmt)
    )


def _sync_op_args(tree: ast.Module) -> list[tuple[ast.expr, int]]:
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("_sync", "_sync_locked")
            and node.args
        ):
            out.append((node.args[0], node.lineno))
    return out


def _step_callables(tree: ast.Module) -> set[str]:
    """Attributes the follower-loop class's __init__ assigns from a
    self._build_*() factory call: the jitted step programs the lockstep
    contract covers. Scoped to THAT class — another class's __init__
    appearing first in the module must not hijack the search."""
    init = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and any(
            isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            and m.name == "follower_loop"
            for m in node.body
        ):
            for m in node.body:
                if isinstance(m, ast.FunctionDef) and m.name == "__init__":
                    init = m
            break
    if init is None:
        init = _find_function(tree, "__init__")
    if init is None:
        return set()
    out: set[str] = set()
    for node in ast.walk(init):
        if not isinstance(node, ast.Assign):
            continue
        has_build_call = any(
            isinstance(c, ast.Call)
            and isinstance(c.func, ast.Attribute)
            and c.func.attr.startswith("_build_")
            for c in ast.walk(node.value)
        )
        if not has_build_call:
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out.add(t.attr)
    return out


@register
class LockstepChecker(Checker):
    name = "lockstep"
    description = (
        "every _OP_* opcode has a follower dispatch arm, is broadcast "
        "via _sync_locked, and the jitted steps stay behind _exec_*"
    )

    def run(self, repo: Repo) -> list[Finding]:
        findings: list[Finding] = []
        for sf in repo.files:
            if not sf.is_python or sf.tree is None:
                continue
            ops = _module_opcodes(sf.tree)
            follower = _find_function(sf.tree, "follower_loop")
            if not ops or follower is None:
                continue
            findings.extend(self._check_module(sf, ops, follower))
        return findings

    def _check_module(self, sf, ops, follower) -> list[Finding]:
        findings: list[Finding] = []
        handled = _compared_ops(follower)
        for name, line in sorted(ops.items(), key=lambda kv: kv[1]):
            if name not in handled:
                findings.append(Finding(
                    "lockstep", "LS001", sf.path, line,
                    f"opcode {name} has no dispatch arm in follower_loop — "
                    "followers would mirror the wrong program and "
                    "desynchronize the lockstep collective stream",
                ))
        if not _dispatch_chain_has_else_raise(follower):
            findings.append(Finding(
                "lockstep", "LS002", sf.path, follower.lineno,
                "follower_loop's dispatch chain must end in an else that "
                "raises: an unrecognized opcode silently running the "
                "fallthrough branch is exactly the multi-host hang this "
                "rule exists to prevent",
            ))
        synced: set[str] = set()
        for arg, line in _sync_op_args(sf.tree):
            if isinstance(arg, ast.Name) and arg.id.startswith("_OP_"):
                synced.add(arg.id)
            else:
                findings.append(Finding(
                    "lockstep", "LS004", sf.path, line,
                    "_sync_locked op argument must be a named _OP_* constant "
                    "(magic-number dispatch defeats exhaustiveness "
                    "checking)",
                ))
        for name, line in sorted(ops.items(), key=lambda kv: kv[1]):
            if name == "_OP_STOP" or name in synced:
                continue
            findings.append(Finding(
                "lockstep", "LS003", sf.path, line,
                f"opcode {name} is never broadcast via _sync_locked — dead "
                "opcode, or a leader path dispatching it without the "
                "lockstep broadcast",
            ))
        step_attrs = _step_callables(sf.tree)
        if step_attrs:
            findings.extend(self._check_exec_only(sf, step_attrs))
        return findings

    def _check_exec_only(self, sf, step_attrs: set[str]) -> list[Finding]:
        findings: list[Finding] = []

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                self.fn_stack: list[str] = []

            def _enter(self, node) -> None:
                self.fn_stack.append(node.name)
                self.generic_visit(node)
                self.fn_stack.pop()

            visit_FunctionDef = visit_AsyncFunctionDef = _enter

            def visit_Call(self, node: ast.Call) -> None:
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f.attr in step_attrs
                    and not any(
                        name.startswith(("_exec_", "_build_", "__init__"))
                        for name in self.fn_stack
                    )
                ):
                    findings.append(Finding(
                        "lockstep", "LS005", sf.path, node.lineno,
                        f"jitted step self.{f.attr} called outside the "
                        "_exec_* family — this bypasses the lockstep "
                        "broadcast followers mirror",
                    ))
                self.generic_visit(node)

        V().visit(sf.tree)
        return findings
