"""Runtime lock sanitizer: the dynamic leg of the concurrency rules.

The static checker (:mod:`llmd_tpu.analysis.checkers.concurrency`) sees
lexical ``with`` nesting and one level of call edges; this module sees
what actually happened at runtime — in the mold of ThreadSanitizer's
dynamic lock-order (deadlock) detection. Armed (``LLMD_LOCKSAN=1``; the
``tests/conftest.py`` fixture arms it for the whole session), it
replaces ``threading.Lock`` / ``threading.RLock`` with instrumented
wrappers that

- record each acquisition's thread and stack (bounded) and maintain a
  per-thread held-lock stack;
- maintain the GLOBAL lock-order graph (edges held → acquired across
  all threads, per lock *instance*) and flag the first cycle — two
  threads that ever nest the same two locks in opposite orders can
  deadlock on the right interleaving, whether or not this run hit it;
- flag a sanitized lock still held when an asyncio callback returns
  control to the event loop (``Handle._run`` wrap): a lock held across
  an ``await`` serializes the loop against every thread contending for
  that lock — the runtime twin of rule CC003.

Violations are recorded (``drain_violations``) and — for lock-order
cycles, detected synchronously in the acquiring thread — raised as
:class:`LockOrderError` so the test fails at the acquisition site. The
conftest fixture additionally fails any test on whose watch a violation
was recorded (background threads and swallowed exceptions included) and
renders the report (nodes, edges, violations, peak held depth) to
``LLMD_LOCKSAN_REPORT`` at session teardown.

Locks created BEFORE arming (import-time module locks) are not
instrumented; the serving stack's locks are created in ``__init__``
methods during tests, which is the coverage that matters. Stdlib locks
created while armed (``queue.Queue``, executors) participate too —
they are real locks in the same graph.
"""

from __future__ import annotations

import _thread
import functools
import itertools
import json
import os
import threading
import traceback
import weakref

__all__ = [
    "LockOrderError",
    "HeldAcrossAwaitError",
    "arm",
    "disarm",
    "armed",
    "drain_violations",
    "violations",
    "report",
    "write_report",
    # leak sanitizer (LLMD_LEAKSAN)
    "LeakError",
    "leaksan_register",
    "arm_leaksan",
    "disarm_leaksan",
    "leaksan_armed",
    "leaksan_set_test",
    "leaksan_outstanding",
    "leaksan_check_test",
    "leaksan_drain_violations",
    "leaksan_report",
    "write_leaksan_report",
]

_STACK_DEPTH = 12


class LockOrderError(AssertionError):
    """A lock acquisition closed a cycle in the global lock-order graph."""


class HeldAcrossAwaitError(AssertionError):
    """A sanitized lock was still held when an asyncio callback yielded
    control back to the event loop."""


def _own_frame(f) -> bool:
    return "sanitize" in f.filename and "analysis" in f.filename


def _site() -> str:
    """Creation/acquisition site: innermost non-sanitizer frame."""
    for f in reversed(traceback.extract_stack()):
        if not _own_frame(f):
            return f"{f.filename}:{f.lineno}"
    return "<unknown>"


def _stack() -> list[str]:
    frames = [f for f in traceback.extract_stack() if not _own_frame(f)]
    return [
        f"{f.filename}:{f.lineno} in {f.name}"
        for f in frames[-_STACK_DEPTH:]
    ]


class _State:
    """Global sanitizer state. Internal synchronization uses a RAW
    ``_thread`` lock — the sanitizer must never instrument itself."""

    def __init__(self) -> None:
        self.mu = _thread.allocate_lock()
        self.tls = threading.local()
        # lock token -> creation site (node names for the report).
        # Tokens are monotonic per-instance ids (never reused), NOT
        # id(): a freed lock's address can be recycled for a new lock,
        # and an id-keyed graph would alias the new lock onto the dead
        # lock's edges — a spurious, nondeterministic cycle report.
        self.names: dict[int, str] = {}
        # lock-order graph over lock tokens: a -> {b}
        self.graph: dict[int, set[int]] = {}
        # (a, b) -> (thread name, stack) of the first time we saw it
        self.edge_sites: dict[tuple[int, int], tuple[str, list[str]]] = {}
        # Pending (drained per-test by the conftest gate) vs. the
        # session-cumulative log the teardown report renders — draining
        # for per-test blame must not empty the uploaded artifact.
        self.violations: list[dict] = []
        self.all_violations: list[dict] = []
        self.max_held = 0
        self.locks_created = 0
        self.acquisitions = 0

    # -- per-thread held stack: list of [lock_token, recursion_count] - #

    def held(self) -> list:
        h = getattr(self.tls, "held", None)
        if h is None:
            h = self.tls.held = []
        return h

    def held_ids(self) -> list[int]:
        return [e[0] for e in self.held()]

    # -- events ------------------------------------------------------- #

    def on_create(self, lock, kind: str) -> None:
        with self.mu:
            self.locks_created += 1
            self.names[lock._tok] = f"{kind}@{_site()}"

    def on_acquired(self, lock) -> str | None:
        """Post-acquire bookkeeping + cycle check. Returns a violation
        message when this acquisition closed a cycle in the global
        lock-order graph (the wrapper releases and raises — raising
        with the lock still held would wedge ``with`` callers)."""
        held = self.held()
        lid = lock._tok
        for e in held:
            if e[0] == lid:  # RLock re-entry: no new edges
                e[1] += 1
                return None
        cycle_with = None
        with self.mu:
            self.acquisitions += 1
            held_ids = [e[0] for e in held]
            # Would held -> lid close a cycle? (lid already reaches a
            # held lock in the established graph.) Check BEFORE adding:
            # the graph stays acyclic, so one inversion reports every
            # time it happens without poisoning the established order.
            if held_ids:
                seen: set[int] = set()
                frontier = list(self.graph.get(lid, ()))
                while frontier:
                    n = frontier.pop()
                    if n in seen:
                        continue
                    seen.add(n)
                    if n in held_ids:
                        cycle_with = n
                        break
                    frontier.extend(self.graph.get(n, ()))
            if cycle_with is None:
                for a in held_ids:
                    self.graph.setdefault(a, set()).add(lid)
                    self.edge_sites.setdefault(
                        (a, lid),
                        (threading.current_thread().name, _stack()),
                    )
            else:
                v = {
                    "kind": "lock-order-cycle",
                    "thread": threading.current_thread().name,
                    "acquired": self.names.get(lid, str(lid)),
                    "while_holding": [
                        self.names.get(h, str(h)) for h in held_ids
                    ],
                    "reverse_edge_thread": self.edge_sites.get(
                        (lid, cycle_with), ("?", []),
                    )[0],
                    "stack": _stack(),
                }
                self.violations.append(v)
                self.all_violations.append(v)
        if cycle_with is not None:
            return (
                f"lock-order cycle: acquiring {v['acquired']} while "
                f"holding {v['while_holding']} — the opposite nesting "
                f"was seen on thread {v['reverse_edge_thread']!r}"
            )
        held.append([lid, 1])
        if len(held) > self.max_held:
            self.max_held = len(held)
        return None

    def on_released(self, lock) -> None:
        held = self.held()
        lid = lock._tok
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == lid:
                held[i][1] -= 1
                if held[i][1] <= 0:
                    del held[i]
                return

    def on_loop_boundary(self, before_ids: set[int], what: str) -> None:
        leaked = [e for e in self.held() if e[0] not in before_ids]
        if not leaked:
            return
        with self.mu:
            v = {
                "kind": "held-across-await",
                "thread": threading.current_thread().name,
                "locks": [
                    self.names.get(e[0], str(e[0])) for e in leaked
                ],
                "callback": what,
                "stack": _stack(),
            }
            self.violations.append(v)
            self.all_violations.append(v)


_state: _State | None = None
_orig: dict[str, object] = {}
# Thread-safe in CPython (C-level next); survives disarm/re-arm cycles
# so tokens stay unique across _State generations too.
_tok_counter = itertools.count(1)


# ------------------------------------------------------------------ #
# the instrumented wrapper


class SanLock:
    """Instrumented stand-in for ``threading.Lock`` / ``RLock``.

    Supports the full lock protocol plus the private RLock methods
    ``threading.Condition`` captures (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``), so Conditions built over a
    sanitized lock keep exact held-set bookkeeping across ``wait()``.
    """

    __slots__ = ("_inner", "_kind", "_tok")

    def __init__(self, inner, kind: str) -> None:
        self._inner = inner
        self._kind = kind
        # Monotonic, never-reused identity (id() can be recycled after
        # GC, aliasing a new lock onto a dead lock's graph edges).
        self._tok = next(_tok_counter)
        st = _state
        if st is not None:
            st.on_create(self, kind)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        st = _state
        if got and st is not None:
            msg = st.on_acquired(self)
            if msg is not None:
                # Release before raising: a raise out of __enter__ means
                # __exit__ never runs, and a still-held lock would wedge
                # every other contender behind the violation.
                self._inner.release()
                raise LockOrderError(msg)
        return got

    # Condition passes blocking positionally or not at all; RLock's
    # C implementation also accepts keyword form — both covered above.

    def release(self) -> None:
        st = _state
        if st is not None:
            st.on_released(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        if hasattr(self._inner, "locked"):
            return self._inner.locked()
        # _thread.RLock grows .locked() only in 3.14: probe instead —
        # owned by us, or contended by someone, both mean locked.
        if self._inner._is_owned():
            return True
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _at_fork_reinit(self) -> None:
        # stdlib modules (concurrent.futures) reinit module locks in
        # forked children; delegate so a sanitized lock forks cleanly.
        self._inner._at_fork_reinit()

    # -- Condition integration (RLock protocol) ------------------------ #

    def _release_save(self):
        st = _state
        if st is not None:
            # Fully releases regardless of recursion depth: drop the
            # whole held entry, restore on _acquire_restore.
            held = st.held()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] == self._tok:
                    saved_count = held[i][1]
                    del held[i]
                    break
            else:
                saved_count = 1
        else:
            saved_count = 1
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), saved_count)
        self._inner.release()
        return (None, saved_count)

    def _acquire_restore(self, saved) -> None:
        inner_saved, count = saved
        if inner_saved is not None:
            self._inner._acquire_restore(inner_saved)
        else:
            self._inner.acquire()
        st = _state
        if st is not None:
            msg = st.on_acquired(self)
            held = st.held()
            if msg is not None:
                # Condition re-acquire closed a cycle: record stands
                # (conftest fails the test), but wait() must return
                # with the lock held and counted — never raise here.
                held.append([self._tok, count])
            elif held and held[-1][0] == self._tok:
                held[-1][1] = count


    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # Plain-lock heuristic, mirroring threading.Condition's own.
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self) -> str:
        st = _state
        name = st.names.get(self._tok, "?") if st is not None else "?"
        return f"<SanLock {self._kind} {name}>"


def _san_lock():
    return SanLock(_orig["Lock"](), "Lock")


def _san_rlock():
    return SanLock(_orig["RLock"](), "RLock")


# ------------------------------------------------------------------ #
# asyncio boundary: a sanitized lock held when a loop callback returns
# is a lock held across an await (or leaked from a callback) — the
# event loop thread now owns a threading lock while parked.


def _wrap_handle_run(orig_run):
    def _san_run(handle):
        st = _state
        if st is None:
            return orig_run(handle)
        before = {e[0] for e in st.held()}
        try:
            return orig_run(handle)
        finally:
            st.on_loop_boundary(before, repr(handle))

    return _san_run


# ------------------------------------------------------------------ #
# public surface


def armed() -> bool:
    return _state is not None


def arm() -> None:
    """Instrument lock creation + the asyncio callback boundary.
    Idempotent. Locks created while disarmed stay uninstrumented."""
    global _state
    if _state is not None:
        return
    import asyncio.events

    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    _orig["Handle._run"] = asyncio.events.Handle._run
    _state = _State()
    threading.Lock = _san_lock
    threading.RLock = _san_rlock
    asyncio.events.Handle._run = _wrap_handle_run(
        asyncio.events.Handle._run
    )


def disarm() -> None:
    """Restore the originals. Already-created SanLocks keep working
    (their hooks no-op once ``_state`` is gone)."""
    global _state
    if _state is None:
        return
    import asyncio.events

    threading.Lock = _orig.pop("Lock")
    threading.RLock = _orig.pop("RLock")
    asyncio.events.Handle._run = _orig.pop("Handle._run")
    _state = None


def violations() -> list[dict]:
    if _state is None:
        return []
    with _state.mu:
        return list(_state.violations)


def drain_violations() -> list[dict]:
    """Return and clear recorded violations (per-test accounting)."""
    if _state is None:
        return []
    with _state.mu:
        out, _state.violations = _state.violations, []
        return out


def report() -> dict:
    """The teardown report: nodes, edges (with first-seen thread),
    violations, and aggregate counters."""
    if _state is None:
        return {"armed": False}
    with _state.mu:
        names = dict(_state.names)
        edges = [
            {
                "outer": names.get(a, str(a)),
                "inner": names.get(b, str(b)),
                "thread": _state.edge_sites.get((a, b), ("?",))[0],
            }
            for a, targets in sorted(_state.graph.items())
            for b in sorted(targets)
        ]
        return {
            "armed": True,
            "locks_created": _state.locks_created,
            "acquisitions": _state.acquisitions,
            "max_held_depth": _state.max_held,
            "edges": edges,
            # Session-cumulative: per-test draining (the conftest gate's
            # blame accounting) must not empty the uploaded artifact.
            "violations": list(_state.all_violations),
        }


def write_report(path: str | None = None) -> str:
    path = path or os.environ.get(
        "LLMD_LOCKSAN_REPORT", "locksan_report.json"
    )
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report(), f, indent=2, default=str)
    return path


# ================================================================== #
# Runtime LEAK sanitizer (LLMD_LEAKSAN): the dynamic leg of the
# resource-lifecycle rules (RL001-RL003). The static checker proves
# acquire/release pairing lexically; this module mirrors what actually
# happened — every handle a registered resource manager hands out
# (KV pages, adapter slots, admission leases, half-open probe grants,
# flow-control admission tokens, staged KV bundles) is tracked in a
# per-instance outstanding map with a bounded acquisition backtrace and
# the pytest nodeid on whose watch it was acquired (background threads
# included). The conftest gate asserts zero newly-outstanding handles
# at every test teardown and the session renders a cumulative JSON
# report (LLMD_LEAKSAN_REPORT, default leaksan_report.json).
#
# Managers self-describe at import time via :func:`leaksan_register`
# (the runtime twin of their `# llmd: resource(...)` annotation); the
# registration is a no-op until :func:`arm_leaksan` wraps the named
# methods. Modes:
#   counted — refcount-style handles (pages, slots, leases): each
#             acquire +1, each release -1; below zero is a
#             double-release violation.
#   set     — idempotent grants (probe grants, staged bundles): acquire
#             marks outstanding, re-acquire refreshes, release of an
#             unknown handle is quiet (success on a closed circuit /
#             idempotent release_bundle are normal).
#   anon    — handleless tokens (flow-control admission): acquire
#             pushes a synthetic token, release pops LIFO; popping an
#             empty stack is a violation.
# `transfer` methods move a handle from outstanding to a transferred
# set (slot published into residency): quiet, and a later release of a
# transferred handle (unload refunding a resident slot) is quiet too.


class LeakError(AssertionError):
    """A registered resource manager leaked handles on a test's watch."""


class _Spec:
    __slots__ = (
        "cls", "resource", "mode", "acquire", "release", "transfer",
        "live", "wrapped",
    )

    def __init__(self, cls, resource, mode, acquire, release, transfer,
                 live) -> None:
        self.cls = cls
        self.resource = resource
        self.mode = mode
        self.acquire = acquire or {}
        self.release = release or {}
        self.transfer = transfer or {}
        self.live = live
        self.wrapped: dict[str, object] = {}


_LEAKSAN_SPECS: list[_Spec] = []
_leak_state = None
_instance_tok = itertools.count(1)


class _LeakState:
    """Global leak-sanitizer state (raw _thread lock: the sanitizer
    must never instrument or contend with itself)."""

    def __init__(self) -> None:
        self.mu = _thread.allocate_lock()
        self.current_test = "<no-test>"
        # instance token -> weakref (purge callback drops the records:
        # handles die with their manager).
        self.instances: dict[int, weakref.ref] = {}
        self.instance_meta: dict[int, str] = {}  # token -> "Cls@site"
        # (token, resource, handle) -> record dict
        self.outstanding: dict[tuple, dict] = {}
        # (token, resource) -> set of transferred handles
        self.transferred: dict[tuple, set] = {}
        # (token, resource) -> list of anon-token records (LIFO)
        self.anon: dict[tuple, list] = {}
        self.violations: list[dict] = []
        self.all_violations: list[dict] = []
        self.counters: dict[str, dict] = {}
        # Running per-resource outstanding totals (records + anon
        # stacks): peak tracking must be O(1) per acquisition, not a
        # scan of every outstanding record under the global lock.
        self.live: dict[str, int] = {}
        self.leaks_by_test: dict[str, int] = {}

    def _purge(self, tok: int) -> None:
        with self.mu:
            self.instances.pop(tok, None)
            self.instance_meta.pop(tok, None)
            for key in [k for k in self.outstanding if k[0] == tok]:
                res = key[1]
                self.live[res] = (
                    self.live.get(res, 0) - self.outstanding[key]["count"]
                )
                del self.outstanding[key]
            for key in [k for k in self.transferred if k[0] == tok]:
                del self.transferred[key]
            for key in [k for k in self.anon if k[0] == tok]:
                res = key[1]
                self.live[res] = self.live.get(res, 0) - len(self.anon[key])
                del self.anon[key]

    def token_of(self, obj) -> int:
        tok = getattr(obj, "_leaksan_tok", None)
        if tok is None:
            tok = next(_instance_tok)
            try:
                object.__setattr__(obj, "_leaksan_tok", tok)
            except (AttributeError, TypeError):
                return -1  # slots-only object: untracked
            with self.mu:
                try:
                    self.instances[tok] = weakref.ref(
                        obj, lambda _r, t=tok: self._purge(t)
                    )
                except TypeError:
                    pass  # not weakref-able: records live for the session
                self.instance_meta[tok] = (
                    f"{type(obj).__name__}@{_site()}"
                )
        return tok

    def counter(self, resource: str) -> dict:
        c = self.counters.get(resource)
        if c is None:
            c = self.counters[resource] = {
                "acquired": 0, "released": 0, "transferred": 0,
                "peak_outstanding": 0,
            }
        return c

    # -- events -------------------------------------------------------- #

    def on_acquire(self, spec: _Spec, obj, handles) -> None:
        tok = self.token_of(obj)
        with self.mu:
            c = self.counter(spec.resource)
            res = spec.resource
            for h in handles:
                c["acquired"] += 1
                if spec.mode == "anon":
                    self.anon.setdefault((tok, res), []).append({
                        "stack": _stack(),
                        "test": self.current_test,
                        "thread": threading.current_thread().name,
                    })
                    self.live[res] = self.live.get(res, 0) + 1
                    continue
                self.transferred.get((tok, res), set()).discard(h)
                key = (tok, res, h)
                rec = self.outstanding.get(key)
                if rec is None or spec.mode == "set":
                    if rec is None:
                        self.live[res] = self.live.get(res, 0) + 1
                    # set-mode re-acquire replaces (refreshes) the
                    # record: net outstanding unchanged.
                    self.outstanding[key] = {
                        "count": 1,
                        "stack": _stack(),
                        "test": self.current_test,
                        "thread": threading.current_thread().name,
                    }
                else:
                    rec["count"] += 1
                    rec["stack"] = _stack()
                    self.live[res] = self.live.get(res, 0) + 1
            c["peak_outstanding"] = max(
                c["peak_outstanding"], self.live.get(res, 0)
            )

    def on_release(self, spec: _Spec, obj, handles, kind: str) -> None:
        tok = self.token_of(obj)
        with self.mu:
            c = self.counter(spec.resource)
            for h in handles:
                if spec.mode == "anon":
                    stackq = self.anon.get((tok, spec.resource))
                    if stackq:
                        stackq.pop()
                        c["released"] += 1
                        self.live[spec.resource] = (
                            self.live.get(spec.resource, 0) - 1
                        )
                    else:
                        self._violate({
                            "kind": "release-without-acquire",
                            "resource": spec.resource,
                            "manager": self.instance_meta.get(tok, "?"),
                            "handle": None,
                            "test": self.current_test,
                            "thread": threading.current_thread().name,
                            "stack": _stack(),
                        })
                    continue
                key = (tok, spec.resource, h)
                rec = self.outstanding.get(key)
                if rec is not None:
                    if kind == "transfer":
                        c["transferred"] += 1
                        self.live[spec.resource] = (
                            self.live.get(spec.resource, 0) - rec["count"]
                        )
                        del self.outstanding[key]
                        self.transferred.setdefault(
                            (tok, spec.resource), set()
                        ).add(h)
                        continue
                    c["released"] += 1
                    rec["count"] -= 1
                    self.live[spec.resource] = (
                        self.live.get(spec.resource, 0) - 1
                    )
                    if rec["count"] <= 0:
                        del self.outstanding[key]
                    continue
                if h in self.transferred.get((tok, spec.resource), ()):
                    # releasing a previously-published handle (unload of
                    # a resident slot): a legitimate lifecycle arc.
                    if kind == "release":
                        self.transferred[(tok, spec.resource)].discard(h)
                        c["released"] += 1
                    continue
                if spec.mode == "set" or kind == "transfer":
                    continue  # idempotent grants: quiet
                self._violate({
                    "kind": "double-release",
                    "resource": spec.resource,
                    "manager": self.instance_meta.get(tok, "?"),
                    "handle": repr(h),
                    "test": self.current_test,
                    "thread": threading.current_thread().name,
                    "stack": _stack(),
                })

    def _violate(self, v: dict) -> None:
        self.violations.append(v)
        self.all_violations.append(v)


def _leak_wrap(spec: _Spec, method: str, kind: str, extract):
    orig = getattr(spec.cls, method)

    @functools.wraps(orig)
    def wrapper(self, *a, **k):
        result = orig(self, *a, **k)
        st = _leak_state
        if st is not None:
            try:
                handles = list(extract(self, a, k, result) or ())
            except Exception:
                handles = []
            if handles:
                if kind == "acquire":
                    st.on_acquire(spec, self, handles)
                else:
                    st.on_release(spec, self, handles, kind)
        return result

    wrapper._leaksan_orig = orig
    return wrapper


def leaksan_register(
    cls,
    resource: str,
    *,
    mode: str = "counted",
    acquire=None,
    release=None,
    transfer=None,
    live=None,
) -> None:
    """Declare a resource manager class for the leak sanitizer (the
    runtime twin of its ``# llmd: resource(...)`` annotation).

    ``acquire``/``release``/``transfer`` map method names to extractors
    ``fn(self, args, kwargs, result) -> iterable-of-handles`` (return
    an empty iterable for "this call minted/ended nothing"). ``live``
    is an optional ``fn(self, handle) -> bool`` teardown filter for
    protocols with designed expiry (probe grants)."""
    spec = _Spec(cls, resource, mode, acquire, release, transfer, live)
    _LEAKSAN_SPECS.append(spec)
    if _leak_state is not None:
        _instrument(spec)


def _instrument(spec: _Spec) -> None:
    if spec.wrapped:
        return
    for kind, table in (
        ("acquire", spec.acquire),
        ("release", spec.release),
        ("transfer", spec.transfer),
    ):
        for method, extract in table.items():
            spec.wrapped[method] = getattr(spec.cls, method)
            setattr(spec.cls, method, _leak_wrap(spec, method, kind, extract))


def leaksan_armed() -> bool:
    return _leak_state is not None


def arm_leaksan() -> None:
    """Wrap every registered manager's protocol methods. Idempotent;
    managers registered after arming are instrumented on registration."""
    global _leak_state
    if _leak_state is not None:
        return
    _leak_state = _LeakState()
    for spec in _LEAKSAN_SPECS:
        _instrument(spec)


def disarm_leaksan() -> None:
    global _leak_state
    if _leak_state is None:
        return
    for spec in _LEAKSAN_SPECS:
        for method, orig in spec.wrapped.items():
            setattr(spec.cls, method, orig)
        spec.wrapped.clear()
    _leak_state = None


def leaksan_set_test(nodeid: str) -> None:
    st = _leak_state
    if st is not None:
        with st.mu:
            st.current_test = nodeid


def _live_records(st: _LeakState):
    """(key, record, spec-live-filtered) snapshot under the lock."""
    live_by_cls = {
        (id(s.cls), s.resource): s.live for s in _LEAKSAN_SPECS if s.live
    }
    out = []
    for key, rec in list(st.outstanding.items()):
        tok, resource, handle = key
        ref = st.instances.get(tok)
        obj = ref() if ref is not None else None
        if obj is not None:
            live = live_by_cls.get((id(type(obj)), resource))
            if live is not None:
                try:
                    if not live(obj, handle):
                        st.live[resource] = (
                            st.live.get(resource, 0) - rec["count"]
                        )
                        del st.outstanding[key]
                        continue
                except Exception:
                    pass
        out.append((key, rec))
    for key, stackq in st.anon.items():
        tok, resource = key
        for rec in stackq:
            out.append(((tok, resource, None), rec))
    return out


def leaksan_outstanding() -> list[dict]:
    """Snapshot of currently-outstanding handles (live managers only,
    designed-expiry grants filtered)."""
    st = _leak_state
    if st is None:
        return []
    import gc

    gc.collect()  # dead managers must not count as leaks
    with st.mu:
        return [
            {
                "resource": key[1],
                "manager": st.instance_meta.get(key[0], "?"),
                "handle": repr(key[2]),
                "count": rec.get("count", 1),
                "test": rec["test"],
                "thread": rec["thread"],
                "stack": rec["stack"],
            }
            for key, rec in _live_records(st)
        ]


def leaksan_check_test(nodeid: str, record: bool = False) -> list[dict]:
    """Handles acquired on ``nodeid``'s watch and still outstanding —
    the per-test teardown gate (background threads included).

    ``record=True`` (the conftest gate) additionally charges the leaks
    to the session report's per-test blame ledger; mid-test probes
    (regression pins asserting a handle IS outstanding right now) leave
    the ledger alone so the uploaded artifact only blames real
    teardown-time leaks."""
    leaks = [r for r in leaksan_outstanding() if r["test"] == nodeid]
    st = _leak_state
    if record and st is not None and leaks:
        with st.mu:
            st.leaks_by_test[nodeid] = (
                st.leaks_by_test.get(nodeid, 0) + len(leaks)
            )
    return leaks


def leaksan_drain_violations() -> list[dict]:
    st = _leak_state
    if st is None:
        return []
    with st.mu:
        out, st.violations = st.violations, []
        return out


def leaksan_report() -> dict:
    """Session-cumulative report: per-resource counters, violations,
    per-test leak blame, and whatever is still outstanding now."""
    st = _leak_state
    if st is None:
        return {"armed": False}
    outstanding = leaksan_outstanding()
    with st.mu:
        return {
            "armed": True,
            "resources": {
                res: dict(c, outstanding=sum(
                    r["count"] for r in outstanding if r["resource"] == res
                ))
                for res, c in sorted(st.counters.items())
            },
            "outstanding": outstanding,
            "outstanding_total": sum(r["count"] for r in outstanding),
            # Session-cumulative: the per-test drain (conftest blame
            # accounting) must not empty the uploaded artifact.
            "violations": list(st.all_violations),
            "leaks_by_test": dict(st.leaks_by_test),
        }


def write_leaksan_report(path: str | None = None) -> str:
    path = path or os.environ.get(
        "LLMD_LEAKSAN_REPORT", "leaksan_report.json"
    )
    with open(path, "w", encoding="utf-8") as f:
        json.dump(leaksan_report(), f, indent=2, default=str)
    return path
