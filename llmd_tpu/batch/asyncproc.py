"""Async Processor: queue → gate → dispatch worker pool.

Reference behavior (async-processor.md):
  1. Poll — workers pull requests from one or more message queues.
  2. Gate — each request passes a dispatch gate; closed gate (budget 0)
     means wait.
  3. Dispatch — HTTP to the router with deadline propagation.
  4. Result — success lands on a result queue; retryable failure (429/5xx,
     connection errors) re-queues with exponential backoff (base 2s, max
     60s, jitter); fatal errors (4xx payload) are not retried.

Gates (async-processor.md "Dispatch Gates"): `constant` (always open),
`budget-file` (reads an externally-written budget number — the Redis-key
budget gate, with the key on the filesystem so no Redis is required;
a Redis backend can layer on the same interface), `saturation` (polls a
/metrics endpoint and opens while a saturation gauge is below threshold —
the prometheus-saturation gate), `budget-metrics` (capacity − inflight
from downstream metrics — the prometheus-budget gate).

Queue: DeadlineQueue, a priority queue ordered by deadline (the Redis
sorted-set analogue) with optional sqlite persistence.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import json
import logging
import random
import sqlite3
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Awaitable, Callable

import aiohttp

log = logging.getLogger(__name__)


@dataclass(order=True)
class QueuedRequest:
    deadline: float
    seq: int = field(compare=True)
    payload: dict = field(compare=False, default_factory=dict)
    url_path: str = field(compare=False, default="/v1/completions")
    request_id: str = field(compare=False, default="")
    attempts: int = field(compare=False, default=0)
    not_before: float = field(compare=False, default=0.0)


class DeadlineQueue:
    """Deadline-ordered priority queue; optionally persisted to sqlite so
    queued work survives restarts (the Redis sorted set is persisted too).
    """

    def __init__(self, db_path: str | Path | None = None) -> None:
        self._heap: list[QueuedRequest] = []
        self._seq = itertools.count()
        # put() replaces-and-sets this so every parked getter wakes and
        # re-checks immediately — a backoff sleep must not delay fresh work.
        self._new_item = asyncio.Event()
        self._db: sqlite3.Connection | None = None
        self._db_lock = threading.Lock()
        if db_path is not None:
            self._db = sqlite3.connect(str(db_path), check_same_thread=False)
            with self._db_lock, self._db:
                self._db.execute(
                    "CREATE TABLE IF NOT EXISTS q (request_id TEXT PRIMARY "
                    "KEY, deadline REAL, url_path TEXT, payload TEXT, "
                    "attempts INTEGER)"
                )
            for row in self._db.execute("SELECT * FROM q"):
                heapq.heappush(
                    self._heap,
                    QueuedRequest(
                        deadline=row[1], seq=next(self._seq),
                        payload=json.loads(row[3]), url_path=row[2],
                        request_id=row[0], attempts=row[4],
                    ),
                )

    def _persist(self, req: QueuedRequest) -> None:
        if self._db is None:
            return
        with self._db_lock, self._db:
            self._db.execute(
                "INSERT OR REPLACE INTO q VALUES (?,?,?,?,?)",
                (req.request_id, req.deadline, req.url_path,
                 json.dumps(req.payload), req.attempts),
            )

    def _unpersist(self, request_id: str) -> None:
        if self._db is None:
            return
        with self._db_lock, self._db:
            self._db.execute("DELETE FROM q WHERE request_id=?", (request_id,))

    async def put(
        self,
        payload: dict,
        deadline: float,
        url_path: str = "/v1/completions",
        request_id: str = "",
        attempts: int = 0,
        not_before: float = 0.0,
    ) -> None:
        req = QueuedRequest(
            deadline=deadline, seq=next(self._seq), payload=payload,
            url_path=url_path, request_id=request_id or f"areq-{next(self._seq)}",
            attempts=attempts, not_before=not_before,
        )
        self._persist(req)
        heapq.heappush(self._heap, req)
        ev, self._new_item = self._new_item, asyncio.Event()
        ev.set()

    async def get(self) -> QueuedRequest:
        """Earliest-deadline request whose backoff delay has elapsed.

        Single-threaded asyncio: heap mutations happen between awaits, so
        no lock is needed; wakeups ride the put() event.
        """
        while True:
            now = time.monotonic()
            ready = [r for r in self._heap if r.not_before <= now]
            if ready:
                req = min(ready)
                self._heap.remove(req)
                heapq.heapify(self._heap)
                return req
            ev = self._new_item
            if self._heap:
                wait = min(r.not_before for r in self._heap) - now
                try:
                    await asyncio.wait_for(ev.wait(), max(wait, 0.01))
                except asyncio.TimeoutError:
                    pass
            else:
                await ev.wait()

    def ack(self, req: QueuedRequest) -> None:
        self._unpersist(req.request_id)

    def __len__(self) -> int:
        return len(self._heap)


# ---- dispatch gates ----


class ConstantGate:
    """Always open (async-processor.md: `constant`)."""

    async def acquire(self) -> None:
        return None

    def release(self) -> None:
        return None


class BudgetFileGate:
    """External budget number in a file (the Redis-key budget gate shape:
    an outside controller writes how many in-flight dispatches are allowed;
    0 closes the gate)."""

    def __init__(self, path: str | Path, poll_interval_s: float = 0.5) -> None:
        self.path = Path(path)
        self.poll_interval_s = poll_interval_s
        self._inflight = 0

    def _budget(self) -> int:
        try:
            return int(float(self.path.read_text().strip()))
        except (OSError, ValueError):
            return 0

    async def acquire(self) -> None:
        while self._inflight >= self._budget():
            await asyncio.sleep(self.poll_interval_s)
        self._inflight += 1

    def release(self) -> None:
        self._inflight = max(0, self._inflight - 1)


async def _scrape_gauge(session: aiohttp.ClientSession, url: str,
                        metric: str) -> float | None:
    try:
        async with session.get(url) as r:
            text = await r.text()
    except Exception:
        return None
    total, n = 0.0, 0
    for line in text.splitlines():
        if line.startswith(metric) and not line.startswith("#"):
            try:
                total += float(line.rsplit(None, 1)[-1])
                n += 1
            except ValueError:
                continue
    return (total / n) if n else None


class SaturationGate:
    """Open while a saturation gauge scraped from /metrics is below a
    threshold (async-processor.md: `prometheus-saturation`). Fail-open on
    scrape outage after `outage_grace_s` so a dead monitoring stack doesn't
    wedge the batch plane."""

    def __init__(
        self,
        metrics_url: str,
        metric: str = "llmd_kv_cache_utilization",
        threshold: float = 0.8,
        poll_interval_s: float = 1.0,
        outage_grace_s: float = 30.0,
    ) -> None:
        self.metrics_url = metrics_url
        self.metric = metric
        self.threshold = threshold
        self.poll_interval_s = poll_interval_s
        self.outage_grace_s = outage_grace_s
        self._session: aiohttp.ClientSession | None = None
        self._last_ok = time.monotonic()

    async def acquire(self) -> None:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5)
            )
        while True:
            val = await _scrape_gauge(self._session, self.metrics_url, self.metric)
            now = time.monotonic()
            if val is None:
                if now - self._last_ok > self.outage_grace_s:
                    return  # fail open
            else:
                self._last_ok = now
                if val < self.threshold:
                    return
            await asyncio.sleep(self.poll_interval_s)

    def release(self) -> None:
        return None

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()


class BudgetMetricsGate(SaturationGate):
    """budget = capacity_metric − inflight_metric; dispatch while our own
    in-flight count stays under it (async-processor.md: `prometheus-budget`).
    """

    def __init__(self, metrics_url: str,
                 capacity_metric: str = "llmd_max_running_requests",
                 inflight_metric: str = "llmd_running_requests",
                 **kw) -> None:
        super().__init__(metrics_url, metric=inflight_metric, **kw)
        self.capacity_metric = capacity_metric
        self._inflight = 0

    async def acquire(self) -> None:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5)
            )
        while True:
            cap = await _scrape_gauge(self._session, self.metrics_url,
                                      self.capacity_metric)
            used = await _scrape_gauge(self._session, self.metrics_url,
                                       self.metric)
            now = time.monotonic()
            if cap is None or used is None:
                if now - self._last_ok > self.outage_grace_s:
                    self._inflight += 1
                    return
            else:
                self._last_ok = now
                if self._inflight < cap - used:
                    self._inflight += 1
                    return
            await asyncio.sleep(self.poll_interval_s)

    def release(self) -> None:
        self._inflight = max(0, self._inflight - 1)


# ---- the processor ----


@dataclass
class AsyncProcessorConfig:
    router_url: str
    workers: int = 8  # async-processor.md: default 8
    backoff_base_s: float = 2.0
    backoff_max_s: float = 60.0
    max_attempts: int = 8
    request_timeout_s: float = 300.0


RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})


class AsyncProcessor:
    """Worker pool pulling from a DeadlineQueue through a gate."""

    def __init__(
        self,
        queue: DeadlineQueue,
        cfg: AsyncProcessorConfig,
        gate=None,
        on_result: Callable[[QueuedRequest, dict], Awaitable[None]] | None = None,
    ) -> None:
        self.queue = queue
        self.cfg = cfg
        self.gate = gate or ConstantGate()
        self.on_result = on_result
        self.results: asyncio.Queue = asyncio.Queue()
        self._stop = asyncio.Event()
        self._session: aiohttp.ClientSession | None = None
        self.stats = {
            "dispatched": 0, "succeeded": 0, "failed": 0, "retried": 0,
            "deadline_exceeded": 0, "shedded": 0,
        }

    async def run(self) -> None:
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.cfg.request_timeout_s)
        )
        workers = [
            asyncio.create_task(self._worker(i)) for i in range(self.cfg.workers)
        ]
        await self._stop.wait()
        for w in workers:
            w.cancel()
        await asyncio.gather(*workers, return_exceptions=True)
        await self._session.close()
        if hasattr(self.gate, "close"):
            await self.gate.close()

    def stop(self) -> None:
        self._stop.set()

    async def _worker(self, idx: int) -> None:
        while True:
            req = await self.queue.get()
            try:
                # Deadline enforcement: abandon work that can't finish.
                if time.time() >= req.deadline:
                    self.stats["deadline_exceeded"] += 1
                    self.queue.ack(req)
                    await self._emit(req, {"error": "deadline_exceeded"})
                    continue
                await self.gate.acquire()
                try:
                    await self._dispatch(req)
                finally:
                    self.gate.release()
            except asyncio.CancelledError:
                raise
            except Exception:
                # A single bad request/response must not shrink the pool.
                log.exception("worker %d: dispatch of %s failed", idx,
                              req.request_id)
                self.stats["failed"] += 1
                self.queue.ack(req)
                await self._emit(req, {"error": "internal", "detail": "worker"})

    async def _dispatch(self, req: QueuedRequest) -> None:
        url = self.cfg.router_url.rstrip("/") + req.url_path
        remaining = max(req.deadline - time.time(), 0.1)
        headers = {
            # Deadline propagation to the router/engine.
            "x-llm-d-deadline-ms": str(int(remaining * 1000)),
            "x-request-id": req.request_id,
        }
        self.stats["dispatched"] += 1
        try:
            async with self._session.post(
                url, json=req.payload, headers=headers,
                timeout=aiohttp.ClientTimeout(total=remaining),
            ) as r:
                if r.status < 400:
                    try:
                        body = await r.json()
                    except (json.JSONDecodeError, aiohttp.ContentTypeError):
                        body = {"raw": (await r.text())[:2000]}
                    self.stats["succeeded"] += 1
                    self.queue.ack(req)
                    await self._emit(req, {"status": r.status, "body": body})
                    return
                retryable = r.status in RETRYABLE_STATUSES
                err = {"status": r.status, "body": (await r.text())[:1000]}
        except asyncio.TimeoutError:
            retryable, err = True, {"error": "timeout"}
        except aiohttp.ClientError as e:
            retryable, err = True, {"error": f"connection: {e}"}

        if not retryable or req.attempts + 1 >= self.cfg.max_attempts:
            self.stats["failed" if not retryable else "shedded"] += 1
            self.queue.ack(req)
            await self._emit(req, {"error": "fatal", **err})
            return
        # Exponential backoff with jitter: 2s -> 60s.
        delay = min(
            self.cfg.backoff_base_s * (2 ** req.attempts),
            self.cfg.backoff_max_s,
        ) * (0.5 + random.random())
        self.stats["retried"] += 1
        self.queue.ack(req)
        await self.queue.put(
            req.payload, req.deadline, req.url_path, req.request_id,
            attempts=req.attempts + 1,
            not_before=time.monotonic() + delay,
        )

    async def _emit(self, req: QueuedRequest, result: dict) -> None:
        if self.on_result is not None:
            await self.on_result(req, result)
        else:
            await self.results.put((req, result))

    def metrics_text(self) -> str:
        lines = [
            f"llmd_async_{k}_total {v}" for k, v in self.stats.items()
        ]
        lines.append(f"llmd_async_queue_depth {len(self.queue)}")
        return "\n".join(lines) + "\n"
