"""Async Processor: queue → gate → dispatch worker pool.

Reference behavior (async-processor.md):
  1. Poll — workers pull requests from one or more message queues.
  2. Gate — each request passes a dispatch gate; closed gate (budget 0)
     means wait.
  3. Dispatch — HTTP to the router with deadline propagation.
  4. Result — success lands on a result queue; retryable failure (429/5xx,
     connection errors) re-queues with exponential backoff (base 2s, max
     60s, jitter); fatal errors (4xx payload) are not retried.

Gates (async-processor.md "Dispatch Gates"): `constant` (always open),
`budget-file` (reads an externally-written budget number — the Redis-key
budget gate, with the key on the filesystem so no Redis is required;
a Redis backend can layer on the same interface), `saturation` (polls a
/metrics endpoint and opens while a saturation gauge is below threshold —
the prometheus-saturation gate), `budget-metrics` (capacity − inflight
from downstream metrics — the prometheus-budget gate).

Queue: DeadlineQueue, a priority queue ordered by deadline (the Redis
sorted-set analogue) with optional sqlite persistence.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import json
import logging
import math
import random
import sqlite3
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Awaitable, Callable

import aiohttp

from llmd_tpu import clock

log = logging.getLogger(__name__)


@dataclass(order=True)
class QueuedRequest:
    deadline: float
    seq: int = field(compare=True)
    payload: dict = field(compare=False, default_factory=dict)
    url_path: str = field(compare=False, default="/v1/completions")
    request_id: str = field(compare=False, default="")
    attempts: int = field(compare=False, default=0)
    not_before: float = field(compare=False, default=0.0)


class DeadlineQueue:
    """Deadline-ordered priority queue; optionally persisted to sqlite so
    queued work survives restarts (the Redis sorted set is persisted too).
    """

    def __init__(self, db_path: str | Path | None = None) -> None:
        # Event-loop-thread owned (single-threaded asyncio: mutations
        # happen between awaits) — no lock; the DB connection below is
        # the one cross-thread surface (sqlite check_same_thread=False).
        self._heap: list[QueuedRequest] = []
        self._seq = itertools.count()
        # put() replaces-and-sets this so every parked getter wakes and
        # re-checks immediately — a backoff sleep must not delay fresh work.
        self._new_item = asyncio.Event()
        self._db: sqlite3.Connection | None = None  # llmd: guarded_by(_db_lock)
        self._db_lock = threading.Lock()
        if db_path is not None:
            self._db = sqlite3.connect(str(db_path), check_same_thread=False)
            with self._db_lock, self._db:
                self._db.execute(
                    "CREATE TABLE IF NOT EXISTS q (request_id TEXT PRIMARY "
                    "KEY, deadline REAL, url_path TEXT, payload TEXT, "
                    "attempts INTEGER)"
                )
            for row in self._db.execute("SELECT * FROM q"):
                heapq.heappush(
                    self._heap,
                    QueuedRequest(
                        deadline=row[1], seq=next(self._seq),
                        payload=json.loads(row[3]), url_path=row[2],
                        request_id=row[0], attempts=row[4],
                    ),
                )

    def _persist(self, req: QueuedRequest) -> None:
        with self._db_lock:
            if self._db is None:
                return
            with self._db:
                self._db.execute(
                    "INSERT OR REPLACE INTO q VALUES (?,?,?,?,?)",
                    (req.request_id, req.deadline, req.url_path,
                     json.dumps(req.payload), req.attempts),
                )

    def _unpersist(self, request_id: str) -> None:
        with self._db_lock:
            if self._db is None:
                return
            with self._db:
                self._db.execute(
                    "DELETE FROM q WHERE request_id=?", (request_id,)
                )

    async def put(
        self,
        payload: dict,
        deadline: float,
        url_path: str = "/v1/completions",
        request_id: str = "",
        attempts: int = 0,
        not_before: float = 0.0,
    ) -> None:
        req = QueuedRequest(
            deadline=deadline, seq=next(self._seq), payload=payload,
            url_path=url_path, request_id=request_id or f"areq-{next(self._seq)}",
            attempts=attempts, not_before=not_before,
        )
        self._persist(req)
        heapq.heappush(self._heap, req)
        ev, self._new_item = self._new_item, asyncio.Event()
        ev.set()

    async def get(self) -> QueuedRequest:
        """Earliest-deadline request whose backoff delay has elapsed.

        Single-threaded asyncio: heap mutations happen between awaits, so
        no lock is needed; wakeups ride the put() event.
        """
        while True:
            now = clock.monotonic()
            ready = [r for r in self._heap if r.not_before <= now]
            if ready:
                req = min(ready)
                self._heap.remove(req)
                heapq.heapify(self._heap)
                return req
            ev = self._new_item
            if self._heap:
                wait = min(r.not_before for r in self._heap) - now
                try:
                    await asyncio.wait_for(ev.wait(), max(wait, 0.01))
                except asyncio.TimeoutError:
                    pass
            else:
                await ev.wait()

    def ack(self, req: QueuedRequest) -> None:
        self._unpersist(req.request_id)

    def __len__(self) -> int:
        return len(self._heap)


# ---- dispatch gates ----


class ConstantGate:
    """Always open (async-processor.md: `constant`)."""

    async def acquire(self) -> None:
        return None

    def release(self) -> None:
        return None


class BudgetFileGate:
    """External budget number in a file (the Redis-key budget gate shape:
    an outside controller writes how many in-flight dispatches are allowed;
    0 closes the gate)."""

    def __init__(self, path: str | Path, poll_interval_s: float = 0.5) -> None:
        self.path = Path(path)
        self.poll_interval_s = poll_interval_s
        # Event-loop-thread owned (acquire/release run on the worker
        # pool's loop; increments sit between awaits) — no lock.
        self._inflight = 0

    def _budget(self) -> int:
        try:
            return int(float(self.path.read_text().strip()))
        except (OSError, ValueError):
            return 0

    async def acquire(self) -> None:
        while self._inflight >= self._budget():
            await asyncio.sleep(self.poll_interval_s)
        self._inflight += 1

    def release(self) -> None:
        self._inflight = max(0, self._inflight - 1)


async def _scrape_gauge(session: aiohttp.ClientSession, url: str,
                        metric: str) -> float | None:
    try:
        async with session.get(url) as r:
            text = await r.text()
    except Exception:
        return None
    total, n = 0.0, 0
    for line in text.splitlines():
        if line.startswith(metric) and not line.startswith("#"):
            try:
                total += float(line.rsplit(None, 1)[-1])
                n += 1
            except ValueError:
                continue
    return (total / n) if n else None


class SaturationGate:
    """Open while a saturation gauge scraped from /metrics is below a
    threshold (async-processor.md: `prometheus-saturation`). Fail-open on
    scrape outage after `outage_grace_s` so a dead monitoring stack doesn't
    wedge the batch plane."""

    def __init__(
        self,
        metrics_url: str,
        metric: str = "llmd_kv_cache_utilization",
        threshold: float = 0.8,
        poll_interval_s: float = 1.0,
        outage_grace_s: float = 30.0,
    ) -> None:
        self.metrics_url = metrics_url
        self.metric = metric
        self.threshold = threshold
        self.poll_interval_s = poll_interval_s
        self.outage_grace_s = outage_grace_s
        # Event-loop-thread owned (every acquire() runs on the worker
        # pool's loop; the session is created lazily there) — no lock.
        self._session: aiohttp.ClientSession | None = None
        self._last_ok = clock.monotonic()

    async def acquire(self) -> None:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5)
            )
        while True:
            val = await _scrape_gauge(self._session, self.metrics_url, self.metric)
            now = clock.monotonic()
            if val is None:
                if now - self._last_ok > self.outage_grace_s:
                    return  # fail open
            else:
                self._last_ok = now
                if val < self.threshold:
                    return
            await asyncio.sleep(self.poll_interval_s)

    def release(self) -> None:
        return None

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()


class BudgetMetricsGate(SaturationGate):
    """budget = capacity_metric − inflight_metric; dispatch while our own
    in-flight count stays under it (async-processor.md: `prometheus-budget`).
    """

    def __init__(self, metrics_url: str,
                 capacity_metric: str = "llmd_max_running_requests",
                 inflight_metric: str = "llmd_running_requests",
                 **kw) -> None:
        super().__init__(metrics_url, metric=inflight_metric, **kw)
        self.capacity_metric = capacity_metric
        self._inflight = 0

    async def acquire(self) -> None:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5)
            )
        while True:
            cap = await _scrape_gauge(self._session, self.metrics_url,
                                      self.capacity_metric)
            used = await _scrape_gauge(self._session, self.metrics_url,
                                       self.metric)
            now = clock.monotonic()
            if cap is None or used is None:
                if now - self._last_ok > self.outage_grace_s:
                    self._inflight += 1
                    return
            else:
                self._last_ok = now
                if self._inflight < cap - used:
                    self._inflight += 1
                    return
            await asyncio.sleep(self.poll_interval_s)

    def release(self) -> None:
        self._inflight = max(0, self._inflight - 1)


# ---- the processor ----


@dataclass
class AsyncProcessorConfig:
    router_url: str
    workers: int = 8  # async-processor.md: default 8
    backoff_base_s: float = 2.0
    backoff_max_s: float = 60.0
    max_attempts: int = 8
    request_timeout_s: float = 300.0


RETRYABLE_STATUSES = frozenset({429, 500, 502, 503, 504})


class AsyncProcessor:
    """Worker pool pulling from a DeadlineQueue through a gate."""

    def __init__(
        self,
        queue: DeadlineQueue,
        cfg: AsyncProcessorConfig,
        gate=None,
        on_result: Callable[[QueuedRequest, dict], Awaitable[None]] | None = None,
    ) -> None:
        self.queue = queue
        self.cfg = cfg
        self.gate = gate or ConstantGate()
        self.on_result = on_result
        self.results: asyncio.Queue = asyncio.Queue()
        self._stop = asyncio.Event()
        self._session: aiohttp.ClientSession | None = None
        self.stats = {
            "dispatched": 0, "succeeded": 0, "failed": 0, "retried": 0,
            "deadline_exceeded": 0, "shedded": 0,
        }

    async def run(self) -> None:
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.cfg.request_timeout_s)
        )
        workers = [
            asyncio.create_task(self._worker(i)) for i in range(self.cfg.workers)
        ]
        await self._stop.wait()
        for w in workers:
            w.cancel()
        await asyncio.gather(*workers, return_exceptions=True)
        await self._session.close()
        if hasattr(self.gate, "close"):
            await self.gate.close()

    def stop(self) -> None:
        self._stop.set()

    async def _worker(self, idx: int) -> None:
        while True:
            req = await self.queue.get()
            try:
                # Deadline enforcement: abandon work that can't finish.
                if clock.time() >= req.deadline:
                    self.stats["deadline_exceeded"] += 1
                    self.queue.ack(req)
                    await self._emit(req, {"error": "deadline_exceeded"})
                    continue
                await self.gate.acquire()
                try:
                    await self._dispatch(req)
                finally:
                    self.gate.release()
            except asyncio.CancelledError:
                raise
            except Exception:
                # A single bad request/response must not shrink the pool.
                log.exception("worker %d: dispatch of %s failed", idx,
                              req.request_id)
                self.stats["failed"] += 1
                self.queue.ack(req)
                await self._emit(req, {"error": "internal", "detail": "worker"})

    async def _dispatch(self, req: QueuedRequest) -> None:
        url = self.cfg.router_url.rstrip("/") + req.url_path
        remaining = max(req.deadline - clock.time(), 0.1)
        headers = {
            # Deadline propagation to the router/engine.
            "x-llm-d-deadline-ms": str(int(remaining * 1000)),
            "x-request-id": req.request_id,
        }
        self.stats["dispatched"] += 1
        try:
            async with self._session.post(
                url, json=req.payload, headers=headers,
                timeout=aiohttp.ClientTimeout(total=remaining),
            ) as r:
                if r.status < 400:
                    try:
                        body = await r.json()
                    except (json.JSONDecodeError, aiohttp.ContentTypeError):
                        body = {"raw": (await r.text())[:2000]}
                    self.stats["succeeded"] += 1
                    self.queue.ack(req)
                    await self._emit(req, {"status": r.status, "body": body})
                    return
                retryable = r.status in RETRYABLE_STATUSES
                err = {"status": r.status, "body": (await r.text())[:1000]}
        except asyncio.TimeoutError:
            retryable, err = True, {"error": "timeout"}
        except aiohttp.ClientError as e:
            retryable, err = True, {"error": f"connection: {e}"}

        if not retryable or req.attempts + 1 >= self.cfg.max_attempts:
            self.stats["failed" if not retryable else "shedded"] += 1
            self.queue.ack(req)
            await self._emit(req, {"error": "fatal", **err})
            return
        # Exponential backoff with jitter: 2s -> 60s.
        delay = min(
            self.cfg.backoff_base_s * (2 ** req.attempts),
            self.cfg.backoff_max_s,
        ) * (0.5 + random.random())
        self.stats["retried"] += 1
        self.queue.ack(req)
        await self.queue.put(
            req.payload, req.deadline, req.url_path, req.request_id,
            attempts=req.attempts + 1,
            not_before=clock.monotonic() + delay,
        )

    async def _emit(self, req: QueuedRequest, result: dict) -> None:
        if self.on_result is not None:
            await self.on_result(req, result)
        else:
            await self.results.put((req, result))

    def metrics_text(self) -> str:
        lines = [
            f"llmd_async_{k}_total {v}" for k, v in self.stats.items()
        ]
        lines.append(f"llmd_async_queue_depth {len(self.queue)}")
        return "\n".join(lines) + "\n"


# ---- standalone deployment surface ----


def build_asyncproc_app(queue: DeadlineQueue, proc: AsyncProcessor):
    """Tiny HTTP surface for the standalone processor Deployment
    (deploy/guides/asynchronous-processing): enqueue + stats."""
    from aiohttp import web

    async def enqueue(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return web.json_response({"error": str(e)}, status=400)
        if not isinstance(body, dict):
            return web.json_response(
                {"error": "body must be a JSON object"}, status=400
            )
        payload = body.get("payload")
        if not isinstance(payload, dict):
            return web.json_response(
                {"error": "payload (object) is required"}, status=400
            )
        try:
            deadline_s = float(body.get("deadline_s", 600.0))
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "deadline_s must be a number"}, status=400
            )
        if not math.isfinite(deadline_s):
            # json.loads accepts literal NaN/Infinity; a NaN deadline
            # breaks the heap invariant for EVERY queued request.
            return web.json_response(
                {"error": "deadline_s must be finite"}, status=400
            )
        rid = body.get("request_id") or ""
        await queue.put(
            payload,
            deadline=clock.time() + deadline_s,
            url_path=body.get("url_path", "/v1/completions"),
            request_id=rid,
        )
        return web.json_response({"queued": True, "depth": len(queue)})

    async def metrics(request: web.Request) -> web.Response:
        return web.Response(text=proc.metrics_text())

    app = web.Application()
    app.router.add_post("/enqueue", enqueue)
    app.router.add_get("/metrics", metrics)
    return app


def _build_gate(args):
    if args.gate == "constant":
        return ConstantGate()
    if args.gate == "budget-file":
        return BudgetFileGate(args.budget_file)
    if args.gate == "saturation":
        return SaturationGate(
            args.metrics_url, threshold=args.gate_threshold
        )
    if args.gate == "budget":
        return BudgetMetricsGate(args.metrics_url)
    raise SystemExit(f"unknown gate {args.gate!r}")


def main(argv=None) -> None:
    """Standalone async processor: queue+gate+workers with an HTTP
    enqueue surface; results append to a JSONL file."""
    import argparse

    from aiohttp import web

    p = argparse.ArgumentParser(prog="llmd-asyncproc")
    p.add_argument("--router-url", required=True)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8210)
    p.add_argument("--queue-db", default=None,
                   help="sqlite path; persisted queue survives restarts")
    p.add_argument("--results-file", default=None, help="JSONL results log")
    p.add_argument("--workers", type=int, default=8)
    p.add_argument(
        "--gate", default="constant",
        choices=["constant", "budget-file", "saturation", "budget"],
    )
    p.add_argument("--gate-threshold", type=float, default=0.8)
    p.add_argument("--budget-file", default=None)
    p.add_argument("--metrics-url", default=None,
                   help="router /metrics URL for the saturation/budget gates")
    args = p.parse_args(argv)
    if args.gate in ("saturation", "budget") and not args.metrics_url:
        args.metrics_url = args.router_url.rstrip("/") + "/metrics"
    if args.gate == "budget-file" and not args.budget_file:
        p.error("--gate budget-file requires --budget-file")

    logging.basicConfig(level=logging.INFO)
    queue = DeadlineQueue(args.queue_db)

    async def amain() -> None:
        results_fh = open(args.results_file, "a") if args.results_file else None

        async def on_result(req: QueuedRequest, result: dict) -> None:
            if results_fh is not None:
                line = json.dumps({"request_id": req.request_id, **result})

                def write() -> None:
                    results_fh.write(line + "\n")
                    results_fh.flush()

                # Off-loop: a slow results disk must not stall the worker
                # pool / enqueue surface on every flush.
                await asyncio.get_running_loop().run_in_executor(None, write)

        proc = AsyncProcessor(
            queue,
            AsyncProcessorConfig(router_url=args.router_url,
                                 workers=args.workers),
            gate=_build_gate(args),
            on_result=on_result if results_fh else None,
        )
        app = build_asyncproc_app(queue, proc)
        runner = web.AppRunner(app)
        await runner.setup()
        await web.TCPSite(runner, args.host, args.port).start()
        log.info("asyncproc on %s:%d -> %s (gate=%s, %d workers)",
                 args.host, args.port, args.router_url, args.gate,
                 args.workers)
        try:
            await proc.run()
        finally:
            await runner.cleanup()
            if results_fh is not None:
                results_fh.close()

    asyncio.run(amain())


if __name__ == "__main__":
    main()
