"""Batch plane: OpenAI-compatible Batch Gateway + Async Processor.

Re-implements the reference's batch components TPU-side-agnostically
(they sit above the engine):
  - Batch Gateway (docs/architecture/advanced/batch/batch-gateway.md:12-87):
    API server (/v1/files, /v1/batches), metadata store, SLO-priority queue,
    file store, batch processor with two-level concurrency, crash recovery,
    GC, tenant isolation.
  - Async Processor (docs/architecture/advanced/batch/async-processor.md:5-39):
    queue -> gate -> dispatch worker pool with deadline propagation and
    exponential backoff.

Backends: sqlite3 (stdlib) plays the PostgreSQL role for metadata and the
Redis sorted-set role for the priority queue (single-node, durable);
filesystem file store with tenant-hashed paths plays S3. Redis/S3 proper
are multi-replica deployment options gated behind optional imports.
"""

from llmd_tpu.batch.store import BatchStore, FileStore, now_s
from llmd_tpu.batch.gateway import build_gateway_app
from llmd_tpu.batch.processor import BatchProcessor, ProcessorConfig
from llmd_tpu.batch.asyncproc import (
    AsyncProcessor,
    AsyncProcessorConfig,
    ConstantGate,
    BudgetFileGate,
    SaturationGate,
    DeadlineQueue,
)

__all__ = [
    "BatchStore",
    "FileStore",
    "now_s",
    "build_gateway_app",
    "BatchProcessor",
    "ProcessorConfig",
    "AsyncProcessor",
    "AsyncProcessorConfig",
    "ConstantGate",
    "BudgetFileGate",
    "SaturationGate",
    "DeadlineQueue",
]
