"""Run the Batch Gateway: API server + processor + GC in one process.

    python -m llmd_tpu.batch --router-url http://localhost:8080 \
        --port 8200 --data-dir /var/lib/llmd-batch

Single-node deployment shape (sqlite metadata + FS file store). For
multi-replica, run N API servers against shared storage and M processors;
the queue claim UPDATE keeps job pickup exclusive.
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import logging
import os
import signal
from pathlib import Path

from aiohttp import web

from llmd_tpu.batch.gateway import build_gateway_app
from llmd_tpu.batch.processor import BatchProcessor, GarbageCollector, ProcessorConfig
from llmd_tpu.batch.store import BatchStore, FileStore


async def amain(args: argparse.Namespace) -> None:
    """Serve with the PR 7 probe contract (mirrors epp/__main__._serve):
    SIGTERM/SIGINT flips /readyz to 503 WHILE the socket still serves
    (so the platform probe observes it and new jobs route away), stops
    the processor from claiming new queue jobs, lets the in-flight
    job's rows finish, waits ``LLMD_BATCH_DRAIN_GRACE_S`` (default 5s)
    for routing to move, and only then tears the runner down."""
    data = Path(args.data_dir)
    data.mkdir(parents=True, exist_ok=True)
    store = BatchStore(data / "batch.db")
    files = FileStore(data / "files")
    app = build_gateway_app(store, files)
    proc = BatchProcessor(
        store, files,
        ProcessorConfig(
            router_url=args.router_url,
            global_concurrency=args.global_concurrency,
            per_model_concurrency=args.per_model_concurrency,
        ),
    )
    gc = GarbageCollector(store, files)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, args.host, args.port)
    await site.start()
    logging.info("batch gateway on %s:%d -> router %s",
                 args.host, args.port, args.router_url)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_signal() -> None:
        # Phase 1: flip readiness + stop accepting new work, socket up.
        app["gateway"].begin_drain()
        proc.stop()  # finishes the in-flight job's rows, then exits
        gc.stop()
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        with contextlib.suppress(NotImplementedError):
            loop.add_signal_handler(sig, _on_signal)
    worker = asyncio.gather(proc.run(), gc.run())
    stopper = asyncio.ensure_future(stop.wait())
    try:
        # A worker crash must propagate (readiness staying green over a
        # dead processor would silently strand every queued job): wait
        # for EITHER the signal or the worker ending, and re-raise the
        # latter's exception immediately.
        await asyncio.wait(
            [stopper, worker], return_when=asyncio.FIRST_COMPLETED
        )
        if worker.done():
            worker.result()  # raises if proc.run()/gc.run() failed
        # Phase 2: in-flight rows drain (proc.run returns only after the
        # current job completes), then the probe-visibility grace.
        await worker
        grace = float(os.environ.get("LLMD_BATCH_DRAIN_GRACE_S", "5"))
        if grace > 0:
            await asyncio.sleep(grace)
    finally:
        stopper.cancel()
        worker.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await worker
        await runner.cleanup()
        store.close()


def main() -> None:
    p = argparse.ArgumentParser(description="llmd-tpu batch gateway")
    p.add_argument("--router-url", required=True)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--data-dir", default="/tmp/llmd-batch")
    p.add_argument("--global-concurrency", type=int, default=64)
    p.add_argument("--per-model-concurrency", type=int, default=16)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
