"""Run the Batch Gateway: API server + processor + GC in one process.

    python -m llmd_tpu.batch --router-url http://localhost:8080 \
        --port 8200 --data-dir /var/lib/llmd-batch

Single-node deployment shape (sqlite metadata + FS file store). For
multi-replica, run N API servers against shared storage and M processors;
the queue claim UPDATE keeps job pickup exclusive.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from pathlib import Path

from aiohttp import web

from llmd_tpu.batch.gateway import build_gateway_app
from llmd_tpu.batch.processor import BatchProcessor, GarbageCollector, ProcessorConfig
from llmd_tpu.batch.store import BatchStore, FileStore


async def amain(args: argparse.Namespace) -> None:
    data = Path(args.data_dir)
    data.mkdir(parents=True, exist_ok=True)
    store = BatchStore(data / "batch.db")
    files = FileStore(data / "files")
    app = build_gateway_app(store, files)
    proc = BatchProcessor(
        store, files,
        ProcessorConfig(
            router_url=args.router_url,
            global_concurrency=args.global_concurrency,
            per_model_concurrency=args.per_model_concurrency,
        ),
    )
    gc = GarbageCollector(store, files)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, args.host, args.port)
    await site.start()
    logging.info("batch gateway on %s:%d -> router %s",
                 args.host, args.port, args.router_url)
    try:
        await asyncio.gather(proc.run(), gc.run())
    finally:
        await runner.cleanup()


def main() -> None:
    p = argparse.ArgumentParser(description="llmd-tpu batch gateway")
    p.add_argument("--router-url", required=True)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--data-dir", default="/tmp/llmd-batch")
    p.add_argument("--global-concurrency", type=int, default=64)
    p.add_argument("--per-model-concurrency", type=int, default=16)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
