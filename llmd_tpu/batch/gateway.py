"""Batch Gateway API server: OpenAI-compatible /v1/files + /v1/batches.

Endpoint surface per batch-gateway.md "API Server":
  POST /v1/files            upload JSONL input (multipart or raw body)
  GET  /v1/files            list
  GET  /v1/files/{id}       metadata
  GET  /v1/files/{id}/content
  DELETE /v1/files/{id}
  POST /v1/batches          create job from an uploaded input file
  GET  /v1/batches/{id}     status + request_counts + output file ids
  POST /v1/batches/{id}/cancel
  GET  /v1/batches          list

Auth/tenancy: tenant id comes from a configurable header (default
`x-llm-d-tenant`, falling back to "default") — the gateway authenticates,
the inference route authorizes (batch-gateway.md "Authentication and
Multi-Tenancy"). Every query is tenant-filtered; file content paths are
tenant-hashed in the FileStore.
"""

from __future__ import annotations

import json
import logging
import re

from aiohttp import web

from llmd_tpu.batch.store import TERMINAL, BatchStore, FileStore, now_s

log = logging.getLogger(__name__)

TENANT_HEADER = "x-llm-d-tenant"
SUPPORTED_ENDPOINTS = ("/v1/completions", "/v1/chat/completions", "/v1/embeddings")
MAX_FILE_BYTES = 512 * 1024 * 1024
MAX_REQUESTS_PER_FILE = 50_000


def _err(status: int, message: str, code: str = "invalid_request_error") -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": code}}, status=status
    )


def parse_completion_window(s: str | float | int) -> float:
    """'24h' | '30m' | '90s' | number-of-seconds -> seconds."""
    if isinstance(s, (int, float)):
        return float(s)
    m = re.fullmatch(r"(\d+)([smhd])", s.strip())
    if not m:
        raise ValueError(f"bad completion_window {s!r}")
    mult = {"s": 1, "m": 60, "h": 3600, "d": 86400}[m.group(2)]
    return int(m.group(1)) * mult


def validate_batch_lines(data: bytes, endpoint_hint: str | None = None) -> int:
    """Validate JSONL input file; returns request count.

    Each line must be {"custom_id": str, "method": "POST", "url": <supported
    endpoint>, "body": {...}} with unique custom_ids (the OpenAI batch input
    contract the reference gateway validates on upload).
    """
    count = 0
    seen: set[str] = set()
    for ln, raw in enumerate(data.splitlines(), 1):
        if not raw.strip():
            continue
        try:
            obj = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(f"line {ln}: not valid JSON ({e})") from None
        cid = obj.get("custom_id")
        if not isinstance(cid, str) or not cid:
            raise ValueError(f"line {ln}: missing custom_id")
        if cid in seen:
            raise ValueError(f"line {ln}: duplicate custom_id {cid!r}")
        seen.add(cid)
        if obj.get("method", "POST") != "POST":
            raise ValueError(f"line {ln}: method must be POST")
        url = obj.get("url")
        if url not in SUPPORTED_ENDPOINTS:
            raise ValueError(
                f"line {ln}: url {url!r} not in {SUPPORTED_ENDPOINTS}"
            )
        if endpoint_hint and url != endpoint_hint:
            raise ValueError(
                f"line {ln}: url {url!r} != batch endpoint {endpoint_hint!r}"
            )
        if not isinstance(obj.get("body"), dict):
            raise ValueError(f"line {ln}: missing body object")
        if count >= MAX_REQUESTS_PER_FILE:
            raise ValueError(f"more than {MAX_REQUESTS_PER_FILE} requests")
        count += 1
    if count == 0:
        raise ValueError("empty batch input file")
    return count


class Gateway:
    def __init__(
        self,
        store: BatchStore,
        files: FileStore,
        tenant_header: str = TENANT_HEADER,
        file_expiry_s: float | None = 30 * 86400,
    ) -> None:
        self.store = store
        self.files = files
        self.tenant_header = tenant_header
        self.file_expiry_s = file_expiry_s
        # Probe contract (mirrors epp/__main__._serve): /health stays
        # liveness (200 while the process works), /readyz flips 503 the
        # moment drain begins — WHILE the socket still serves — so the
        # platform's readiness probe observes it and routes new work
        # away before teardown. Draining also refuses new uploads and
        # batch creations with a retryable 503.
        self.draining = False

    def begin_drain(self) -> None:
        self.draining = True

    def _refuse_draining(self) -> web.Response | None:
        if self.draining:
            return _err(503, "gateway draining; retry another replica",
                        "shutting_down")
        return None

    def _tenant(self, request: web.Request) -> str:
        return request.headers.get(self.tenant_header, "default")

    # ---- files ----

    async def upload_file(self, request: web.Request) -> web.Response:
        refused = self._refuse_draining()
        if refused is not None:
            return refused
        tenant = self._tenant(request)
        filename, purpose, data = "upload.jsonl", "batch", b""
        if request.content_type == "multipart/form-data":
            async for part in await request.multipart():
                if part.name == "file":
                    filename = part.filename or filename
                    data = await part.read(decode=False)
                elif part.name == "purpose":
                    purpose = (await part.text()).strip()
        else:
            data = await request.read()
            purpose = request.query.get("purpose", "batch")
            filename = request.query.get("filename", filename)
        if len(data) > MAX_FILE_BYTES:
            return _err(413, f"file exceeds {MAX_FILE_BYTES} bytes")
        if purpose == "batch":
            try:
                validate_batch_lines(data)
            except ValueError as e:
                return _err(400, f"invalid batch input file: {e}")
        expires = now_s() + self.file_expiry_s if self.file_expiry_s else None
        meta = self.store.create_file(
            tenant, filename, purpose, len(data), expires_at=expires
        )
        self.files.write(tenant, meta.id, data)
        return web.json_response(meta.to_openai())

    async def list_files(self, request: web.Request) -> web.Response:
        tenant = self._tenant(request)
        metas = self.store.list_files(tenant)
        return web.json_response(
            {"object": "list", "data": [m.to_openai() for m in metas]}
        )

    async def get_file(self, request: web.Request) -> web.Response:
        tenant = self._tenant(request)
        meta = self.store.get_file(tenant, request.match_info["id"])
        if meta is None:
            return _err(404, "file not found", "not_found_error")
        return web.json_response(meta.to_openai())

    async def file_content(self, request: web.Request) -> web.Response:
        tenant = self._tenant(request)
        fid = request.match_info["id"]
        meta = self.store.get_file(tenant, fid)
        if meta is None or not self.files.exists(tenant, fid):
            return _err(404, "file not found", "not_found_error")
        return web.Response(
            body=self.files.read(tenant, fid),
            content_type="application/jsonl",
        )

    async def delete_file(self, request: web.Request) -> web.Response:
        tenant = self._tenant(request)
        fid = request.match_info["id"]
        if not self.store.delete_file(tenant, fid):
            return _err(404, "file not found", "not_found_error")
        self.files.delete(tenant, fid)
        return web.json_response({"id": fid, "object": "file", "deleted": True})

    # ---- batches ----

    async def create_batch(self, request: web.Request) -> web.Response:
        refused = self._refuse_draining()
        if refused is not None:
            return refused
        tenant = self._tenant(request)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _err(400, "body must be JSON")
        input_file_id = body.get("input_file_id")
        endpoint = body.get("endpoint")
        if endpoint not in SUPPORTED_ENDPOINTS:
            return _err(400, f"endpoint must be one of {SUPPORTED_ENDPOINTS}")
        meta = self.store.get_file(tenant, input_file_id or "")
        if meta is None:
            return _err(404, f"input file {input_file_id!r} not found",
                        "not_found_error")
        try:
            window = parse_completion_window(body.get("completion_window", "24h"))
        except ValueError as e:
            return _err(400, str(e))
        job = self.store.create_batch(
            tenant, endpoint, input_file_id, window,
            metadata=body.get("metadata") or {},
        )
        return web.json_response(job.to_openai())

    async def get_batch(self, request: web.Request) -> web.Response:
        tenant = self._tenant(request)
        job = self.store.get_batch(tenant, request.match_info["id"])
        if job is None:
            return _err(404, "batch not found", "not_found_error")
        return web.json_response(job.to_openai())

    async def list_batches(self, request: web.Request) -> web.Response:
        tenant = self._tenant(request)
        jobs = self.store.list_batches(tenant)
        return web.json_response(
            {"object": "list", "data": [j.to_openai() for j in jobs]}
        )

    async def cancel_batch(self, request: web.Request) -> web.Response:
        tenant = self._tenant(request)
        job = self.store.get_batch(tenant, request.match_info["id"])
        if job is None:
            return _err(404, "batch not found", "not_found_error")
        if job.status in TERMINAL:
            return _err(409, f"batch already {job.status}", "conflict_error")
        if job.status in ("validating",):
            # Not picked up yet: cancel immediately and drop from the queue.
            self.store.remove_from_queue(job.id)
            self.store.update_batch(
                job.id, status="cancelled", cancelling_at=now_s(),
                cancelled_at=now_s(),
            )
        else:
            self.store.update_batch(job.id, cancelling_at=now_s(),
                                    status="cancelling")
            self.store.request_cancel(job.id)
        job = self.store.get_batch(tenant, job.id)
        return web.json_response(job.to_openai())

    async def health(self, request: web.Request) -> web.Response:
        # Liveness: 200 even while draining (the process is healthy; it
        # is readiness that must flip — restarting a draining pod would
        # abandon its in-flight rows).
        return web.json_response({"status": "ok", "queue_depth": self.store.queue_depth()})

    async def readyz(self, request: web.Request) -> web.Response:
        if self.draining:
            return web.json_response(
                {"status": "draining"}, status=503,
                headers={"retry-after": "1"},
            )
        return web.json_response(
            {"status": "ready", "queue_depth": self.store.queue_depth()}
        )


def build_gateway_app(
    store: BatchStore, files: FileStore, tenant_header: str = TENANT_HEADER
) -> web.Application:
    gw = Gateway(store, files, tenant_header)
    app = web.Application(client_max_size=MAX_FILE_BYTES + 1024)
    app["gateway"] = gw
    app.add_routes(
        [
            web.post("/v1/files", gw.upload_file),
            web.get("/v1/files", gw.list_files),
            web.get("/v1/files/{id}", gw.get_file),
            web.get("/v1/files/{id}/content", gw.file_content),
            web.delete("/v1/files/{id}", gw.delete_file),
            web.post("/v1/batches", gw.create_batch),
            web.get("/v1/batches", gw.list_batches),
            web.get("/v1/batches/{id}", gw.get_batch),
            web.post("/v1/batches/{id}/cancel", gw.cancel_batch),
            web.get("/health", gw.health),
            web.get("/readyz", gw.readyz),
        ]
    )
    return app
