"""Batch Gateway storage layer: metadata DB, priority queue, file store.

Reference storage split (batch-gateway.md "Storage Layer" table):
  - jobs/files metadata: PostgreSQL (prod) -> sqlite3 here (stdlib, durable,
    single-node; the schema/queries keep the same shape so a PG driver can
    be swapped in).
  - priority queue: Redis sorted set with SLO-based priority -> a sqlite
    table ordered by (priority, enqueue time); pop is atomic via a claim
    UPDATE.
  - event channels: Redis pub/sub for cancellation -> in-process
    asyncio subscriptions + a `cancel_requested` column so cancellation
    survives restarts and crosses processes via polling.
  - file storage: S3 or filesystem -> filesystem with tenant-hashed
    directories (batch-gateway.md "File paths are hashed by tenant ID").

All timestamps are unix seconds (ints in the OpenAI API surface).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import sqlite3
import threading
import uuid
from dataclasses import dataclass
from pathlib import Path

from llmd_tpu import clock

__all__ = ["BatchStore", "FileStore", "FileMeta", "BatchJob", "now_s"]


def now_s() -> float:
    """Unix-seconds wall clock through the llmd_tpu.clock seam: batch
    timestamps/deadlines replay on the fleet simulator's virtual axis
    (CK001 covers batch/ — no direct time.time() here)."""
    return clock.time()


def _new_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


# Terminal batch statuses (OpenAI Batch object `status` values).
TERMINAL = frozenset({"completed", "failed", "expired", "cancelled"})


@dataclass
class FileMeta:
    id: str
    tenant: str
    filename: str
    purpose: str
    bytes: int
    created_at: float
    expires_at: float | None = None

    def to_openai(self) -> dict:
        return {
            "id": self.id,
            "object": "file",
            "bytes": self.bytes,
            "created_at": int(self.created_at),
            "filename": self.filename,
            "purpose": self.purpose,
            **({"expires_at": int(self.expires_at)} if self.expires_at else {}),
        }


@dataclass
class BatchJob:
    id: str
    tenant: str
    endpoint: str
    input_file_id: str
    completion_window_s: float
    status: str
    created_at: float
    metadata: dict
    output_file_id: str | None = None
    error_file_id: str | None = None
    total: int = 0
    completed: int = 0
    failed: int = 0
    in_progress_at: float | None = None
    finalizing_at: float | None = None
    completed_at: float | None = None
    failed_at: float | None = None
    expired_at: float | None = None
    cancelling_at: float | None = None
    cancelled_at: float | None = None
    cancel_requested: bool = False
    owner: str | None = None  # processor instance id while in_progress
    errors: list | None = None
    # Liveness lease: the owning processor refreshes this while executing;
    # recovery only reclaims jobs whose heartbeat went stale.
    heartbeat_at: float | None = None

    @property
    def deadline(self) -> float:
        return self.created_at + self.completion_window_s

    def to_openai(self) -> dict:
        def ts(v):
            return int(v) if v else None

        return {
            "id": self.id,
            "object": "batch",
            "endpoint": self.endpoint,
            "errors": {"object": "list", "data": self.errors or []},
            "input_file_id": self.input_file_id,
            "completion_window": f"{int(self.completion_window_s)}s",
            "status": self.status,
            "output_file_id": self.output_file_id,
            "error_file_id": self.error_file_id,
            "created_at": int(self.created_at),
            "in_progress_at": ts(self.in_progress_at),
            "expires_at": int(self.deadline),
            "finalizing_at": ts(self.finalizing_at),
            "completed_at": ts(self.completed_at),
            "failed_at": ts(self.failed_at),
            "expired_at": ts(self.expired_at),
            "cancelling_at": ts(self.cancelling_at),
            "cancelled_at": ts(self.cancelled_at),
            "request_counts": {
                "total": self.total,
                "completed": self.completed,
                "failed": self.failed,
            },
            "metadata": self.metadata or {},
        }


_SCHEMA = """
CREATE TABLE IF NOT EXISTS files (
    id TEXT PRIMARY KEY, tenant TEXT NOT NULL, filename TEXT, purpose TEXT,
    bytes INTEGER, created_at REAL, expires_at REAL
);
CREATE TABLE IF NOT EXISTS batches (
    id TEXT PRIMARY KEY, tenant TEXT NOT NULL, endpoint TEXT,
    input_file_id TEXT, completion_window_s REAL, status TEXT,
    created_at REAL, metadata TEXT, output_file_id TEXT, error_file_id TEXT,
    total INTEGER DEFAULT 0, completed INTEGER DEFAULT 0,
    failed INTEGER DEFAULT 0,
    in_progress_at REAL, finalizing_at REAL, completed_at REAL,
    failed_at REAL, expired_at REAL, cancelling_at REAL, cancelled_at REAL,
    cancel_requested INTEGER DEFAULT 0, owner TEXT, errors TEXT,
    heartbeat_at REAL
);
CREATE TABLE IF NOT EXISTS queue (
    batch_id TEXT PRIMARY KEY, priority REAL, enqueued_at REAL,
    claimed_by TEXT
);
CREATE INDEX IF NOT EXISTS idx_batches_tenant ON batches (tenant, created_at);
CREATE INDEX IF NOT EXISTS idx_queue_prio ON queue (priority, enqueued_at);
"""

_BATCH_COLS = (
    "id tenant endpoint input_file_id completion_window_s status created_at "
    "metadata output_file_id error_file_id total completed failed "
    "in_progress_at finalizing_at completed_at failed_at expired_at "
    "cancelling_at cancelled_at cancel_requested owner errors"
).split()


class BatchStore:
    """Metadata + SLO-priority queue + cancellation events.

    Thread-safe (one connection guarded by a lock; sqlite serializes writes
    anyway). Queue priority = job deadline (created_at + completion_window),
    i.e. earliest-deadline-first — the reference's "sorted set with
    SLO-based priority".
    """

    def __init__(self, path: str | Path = ":memory:") -> None:
        self._db = sqlite3.connect(str(path), check_same_thread=False)  # llmd: guarded_by(_lock)
        self._db.row_factory = sqlite3.Row
        self._lock = threading.Lock()
        with self._lock, self._db:
            self._db.executescript(_SCHEMA)
        # In-process cancellation fan-out (the Redis pub/sub analogue).
        # Event-loop-thread owned (asyncio.Events): no lock needed.
        self._cancel_subs: dict[str, list[asyncio.Event]] = {}

    # ---- files ----

    def create_file(
        self,
        tenant: str,
        filename: str,
        purpose: str,
        nbytes: int,
        expires_at: float | None = None,
        file_id: str | None = None,
    ) -> FileMeta:
        meta = FileMeta(
            id=file_id or _new_id("file"),
            tenant=tenant,
            filename=filename,
            purpose=purpose,
            bytes=nbytes,
            created_at=now_s(),
            expires_at=expires_at,
        )
        with self._lock, self._db:
            self._db.execute(
                "INSERT INTO files VALUES (?,?,?,?,?,?,?)",
                (meta.id, tenant, filename, purpose, nbytes, meta.created_at,
                 expires_at),
            )
        return meta

    def get_file(self, tenant: str, file_id: str) -> FileMeta | None:
        with self._lock:
            row = self._db.execute(
                "SELECT * FROM files WHERE id=? AND tenant=?", (file_id, tenant)
            ).fetchone()
        return FileMeta(**dict(row)) if row else None

    def list_files(self, tenant: str, limit: int = 100) -> list[FileMeta]:
        with self._lock:
            rows = self._db.execute(
                "SELECT * FROM files WHERE tenant=? ORDER BY created_at DESC "
                "LIMIT ?",
                (tenant, limit),
            ).fetchall()
        return [FileMeta(**dict(r)) for r in rows]

    def delete_file(self, tenant: str, file_id: str) -> bool:
        with self._lock, self._db:
            cur = self._db.execute(
                "DELETE FROM files WHERE id=? AND tenant=?", (file_id, tenant)
            )
        return cur.rowcount > 0

    def expired_files(self, now: float, limit: int = 100) -> list[FileMeta]:
        with self._lock:
            rows = self._db.execute(
                "SELECT * FROM files WHERE expires_at IS NOT NULL AND "
                "expires_at < ? LIMIT ?",
                (now, limit),
            ).fetchall()
        return [FileMeta(**dict(r)) for r in rows]

    # ---- batches ----

    def create_batch(
        self,
        tenant: str,
        endpoint: str,
        input_file_id: str,
        completion_window_s: float,
        metadata: dict | None = None,
    ) -> BatchJob:
        job = BatchJob(
            id=_new_id("batch"),
            tenant=tenant,
            endpoint=endpoint,
            input_file_id=input_file_id,
            completion_window_s=completion_window_s,
            status="validating",
            created_at=now_s(),
            metadata=metadata or {},
        )
        with self._lock, self._db:
            self._db.execute(
                "INSERT INTO batches (id, tenant, endpoint, input_file_id, "
                "completion_window_s, status, created_at, metadata) "
                "VALUES (?,?,?,?,?,?,?,?)",
                (job.id, tenant, endpoint, input_file_id, completion_window_s,
                 "validating", job.created_at, json.dumps(job.metadata)),
            )
            # Enqueue with SLO priority = deadline (earliest first).
            self._db.execute(
                "INSERT INTO queue VALUES (?,?,?,NULL)",
                (job.id, job.deadline, job.created_at),
            )
        return job

    def _job_from_row(self, row: sqlite3.Row) -> BatchJob:
        d = dict(row)
        d["metadata"] = json.loads(d["metadata"] or "{}")
        d["errors"] = json.loads(d["errors"]) if d["errors"] else None
        d["cancel_requested"] = bool(d["cancel_requested"])
        return BatchJob(**d)

    def get_batch(self, tenant: str | None, batch_id: str) -> BatchJob | None:
        q = "SELECT * FROM batches WHERE id=?"
        args: tuple = (batch_id,)
        if tenant is not None:  # tenant=None = internal (processor) access
            q += " AND tenant=?"
            args = (batch_id, tenant)
        with self._lock:
            row = self._db.execute(q, args).fetchone()
        return self._job_from_row(row) if row else None

    def list_batches(self, tenant: str, limit: int = 100) -> list[BatchJob]:
        with self._lock:
            rows = self._db.execute(
                "SELECT * FROM batches WHERE tenant=? ORDER BY created_at "
                "DESC LIMIT ?",
                (tenant, limit),
            ).fetchall()
        return [self._job_from_row(r) for r in rows]

    def update_batch(self, batch_id: str, **fields) -> None:
        if "metadata" in fields:
            fields["metadata"] = json.dumps(fields["metadata"])
        if "errors" in fields and fields["errors"] is not None:
            fields["errors"] = json.dumps(fields["errors"])
        cols = ", ".join(f"{k}=?" for k in fields)
        with self._lock, self._db:
            self._db.execute(
                f"UPDATE batches SET {cols} WHERE id=?",
                (*fields.values(), batch_id),
            )

    def add_progress(self, batch_id: str, completed: int = 0, failed: int = 0) -> None:
        with self._lock, self._db:
            self._db.execute(
                "UPDATE batches SET completed=completed+?, failed=failed+? "
                "WHERE id=?",
                (completed, failed, batch_id),
            )

    def jobs_with_status(self, status: str) -> list[BatchJob]:
        with self._lock:
            rows = self._db.execute(
                "SELECT * FROM batches WHERE status=?", (status,)
            ).fetchall()
        return [self._job_from_row(r) for r in rows]

    def expired_jobs(self, now: float, limit: int = 100) -> list[BatchJob]:
        """Terminal jobs whose deadline passed `grace` ago — GC candidates."""
        with self._lock:
            rows = self._db.execute(
                "SELECT * FROM batches WHERE created_at + completion_window_s "
                "< ? AND status IN ('completed','failed','expired','cancelled')"
                " LIMIT ?",
                (now, limit),
            ).fetchall()
        return [self._job_from_row(r) for r in rows]

    def delete_batch(self, batch_id: str) -> None:
        with self._lock, self._db:
            self._db.execute("DELETE FROM batches WHERE id=?", (batch_id,))
            self._db.execute("DELETE FROM queue WHERE batch_id=?", (batch_id,))

    # ---- priority queue ----

    def pop_job(self, owner: str) -> BatchJob | None:
        """Atomically claim the highest-priority (earliest-deadline) job."""
        with self._lock, self._db:
            row = self._db.execute(
                "SELECT batch_id FROM queue WHERE claimed_by IS NULL "
                "ORDER BY priority, enqueued_at LIMIT 1"
            ).fetchone()
            if row is None:
                return None
            bid = row["batch_id"]
            self._db.execute(
                "UPDATE queue SET claimed_by=? WHERE batch_id=?", (owner, bid)
            )
        job = self.get_batch(None, bid)
        return job

    def requeue_job(self, batch_id: str, priority: float) -> None:
        with self._lock, self._db:
            self._db.execute(
                "INSERT OR REPLACE INTO queue VALUES (?,?,?,NULL)",
                (batch_id, priority, now_s()),
            )

    def remove_from_queue(self, batch_id: str) -> None:
        with self._lock, self._db:
            self._db.execute("DELETE FROM queue WHERE batch_id=?", (batch_id,))

    def queue_depth(self) -> int:
        with self._lock:
            return self._db.execute(
                "SELECT COUNT(*) FROM queue WHERE claimed_by IS NULL"
            ).fetchone()[0]

    # ---- cancellation events (pub/sub analogue) ----

    def request_cancel(self, batch_id: str) -> None:
        self.update_batch(batch_id, cancel_requested=1)
        for ev in self._cancel_subs.get(batch_id, []):
            ev.set()

    def subscribe_cancel(self, batch_id: str) -> asyncio.Event:
        ev = asyncio.Event()
        self._cancel_subs.setdefault(batch_id, []).append(ev)
        # Replay: cancellation may have landed before the subscription
        # (or in another process via the DB column).
        job = self.get_batch(None, batch_id)
        if job is not None and job.cancel_requested:
            ev.set()
        return ev

    def unsubscribe_cancel(self, batch_id: str) -> None:
        self._cancel_subs.pop(batch_id, None)

    def close(self) -> None:
        with self._lock:
            self._db.close()


class FileStore:
    """Filesystem file store with tenant-hashed paths.

    batch-gateway.md "File paths are hashed by tenant ID to prevent
    enumeration": content lives at <root>/<sha256(tenant)[:16]>/<file_id>.
    S3 is the multi-replica option; this single-node FS layout mirrors the
    reference's PVC mode.
    """

    def __init__(self, root: str | Path) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _dir(self, tenant: str) -> Path:
        h = hashlib.sha256(tenant.encode()).hexdigest()[:16]
        d = self.root / h
        d.mkdir(parents=True, exist_ok=True)
        return d

    def path(self, tenant: str, file_id: str) -> Path:
        return self._dir(tenant) / file_id

    def write(self, tenant: str, file_id: str, data: bytes) -> int:
        p = self.path(tenant, file_id)
        p.write_bytes(data)
        return len(data)

    def append_line(self, tenant: str, file_id: str, line: str) -> None:
        with open(self.path(tenant, file_id), "a") as f:
            f.write(line.rstrip("\n") + "\n")

    def read(self, tenant: str, file_id: str) -> bytes:
        return self.path(tenant, file_id).read_bytes()

    def exists(self, tenant: str, file_id: str) -> bool:
        return self.path(tenant, file_id).exists()

    def size(self, tenant: str, file_id: str) -> int:
        return self.path(tenant, file_id).stat().st_size

    def delete(self, tenant: str, file_id: str) -> None:
        try:
            os.unlink(self.path(tenant, file_id))
        except FileNotFoundError:
            pass
