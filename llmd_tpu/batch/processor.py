"""Batch Processor: poll → ingest → execute → finalize, with crash recovery.

Implements the reference's processor loop (batch-gateway.md "Batch
Processor"):
  1. Poll the SLO-priority queue for the next job.
  2. Ingest the input JSONL — parse model ids, group requests by model,
     build per-model execution plans.
  3. Execute plans concurrently: per-model workers send individual
     inference requests to the router under two-level concurrency control
     (global cap + per-model cap) and append results to the output file.
  4. Track progress and listen for cancellation events.
  5. Finalize: register output/error files, flip terminal status.

Crash recovery (batch-gateway.md "Crash Recovery"): on startup, scan for
jobs left `in_progress` by a dead instance — if a partial output file
exists, register it and mark the job failed; otherwise re-enqueue for a
full retry. Recovery concurrency is capped.
"""

from __future__ import annotations

import asyncio
import json
import logging
import uuid
from dataclasses import dataclass, field

import aiohttp

from llmd_tpu.batch.store import TERMINAL, BatchStore, FileStore, now_s

log = logging.getLogger(__name__)


@dataclass
class ProcessorConfig:
    router_url: str  # base URL of the llm-d router (OpenAI surface)
    global_concurrency: int = 64
    per_model_concurrency: int = 16
    recovery_concurrency: int = 4
    poll_interval_s: float = 0.5
    # Liveness lease: processors heartbeat every lease/4 while executing a
    # job; recovery reclaims only jobs whose heartbeat is older than this.
    lease_s: float = 120.0
    request_timeout_s: float = 600.0
    # Headers forwarded verbatim from batch metadata to inference requests
    # so the router can authorize the end user per-request.
    passthrough_headers: tuple[str, ...] = ("authorization", "x-llm-d-fairness-id")
    # Watermark-admission retry (docs/architecture/batch-processing.md):
    # the EPP's batch-saturation-filter answers 503 while no replica has
    # headroom — batch work WAITS for a trough instead of displacing
    # interactive traffic, so retryable statuses re-offer the line with
    # exponential backoff, bounded by the job's completion deadline.
    dispatch_max_attempts: int = 6
    dispatch_backoff_base_s: float = 1.0
    dispatch_backoff_max_s: float = 30.0


@dataclass
class _Plan:
    model: str
    lines: list[dict] = field(default_factory=list)


class BatchProcessor:
    def __init__(
        self, store: BatchStore, files: FileStore, cfg: ProcessorConfig
    ) -> None:
        self.store = store
        self.files = files
        self.cfg = cfg
        self.instance_id = f"proc-{uuid.uuid4().hex[:8]}"
        self._global_sem = asyncio.Semaphore(cfg.global_concurrency)
        self._session: aiohttp.ClientSession | None = None
        self._stop = asyncio.Event()
        self._tasks: set[asyncio.Task] = set()

    async def _client(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.cfg.request_timeout_s)
            )
        return self._session

    # ---- lifecycle ----

    async def run(self) -> None:
        """Recovery scan, then the poll loop. Cancel-safe."""
        await self.recover()
        try:
            while not self._stop.is_set():
                job = self.store.pop_job(self.instance_id)
                if job is None:
                    try:
                        await asyncio.wait_for(
                            self._stop.wait(), self.cfg.poll_interval_s
                        )
                    except asyncio.TimeoutError:
                        pass
                    continue
                try:
                    await self.process_job(job.id)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # A malformed job must not kill the processor loop.
                    log.exception("job %s failed unexpectedly", job.id)
                    self.store.update_batch(
                        job.id, status="failed", failed_at=now_s(),
                        errors=[{"code": "processor_error",
                                 "message": "internal processing error"}],
                    )
                    self.store.remove_from_queue(job.id)
        finally:
            if self._session and not self._session.closed:
                await self._session.close()

    def stop(self) -> None:
        self._stop.set()

    async def recover(self) -> None:
        """Reference crash-recovery semantics, capped concurrency.

        Only reclaims jobs whose owner's heartbeat went stale — a live peer
        processor (multi-processor deployment) keeps its lease fresh and is
        left alone.
        """
        cutoff = now_s() - self.cfg.lease_s
        stale = [
            j
            for status in ("in_progress", "finalizing")
            for j in self.store.jobs_with_status(status)
            if j.owner != self.instance_id
            and (j.heartbeat_at is None or j.heartbeat_at < cutoff)
        ]
        sem = asyncio.Semaphore(self.cfg.recovery_concurrency)

        async def _one(job):
            async with sem:
                out_id = job.output_file_id
                if out_id and self.files.exists(job.tenant, out_id):
                    # Partial output survives: surface it, fail the job.
                    nbytes = self.files.size(job.tenant, out_id)
                    self.store.create_file(
                        job.tenant, f"{job.id}_output.jsonl", "batch_output",
                        nbytes, file_id=out_id,
                    )
                    self.store.update_batch(
                        job.id, status="failed", failed_at=now_s(),
                        errors=[{"code": "processor_crash",
                                 "message": "processor crashed mid-job; "
                                            "partial output preserved"}],
                    )
                    self.store.remove_from_queue(job.id)
                    log.warning("recovered %s as failed (partial output)", job.id)
                else:
                    self.store.update_batch(
                        job.id, status="validating", owner=None,
                        completed=0, failed=0, output_file_id=None,
                    )
                    self.store.requeue_job(job.id, job.deadline)
                    log.warning("re-enqueued crashed job %s", job.id)

        await asyncio.gather(*map(_one, stale))

    # ---- single job ----

    async def process_job(self, batch_id: str) -> None:
        job = self.store.get_batch(None, batch_id)
        if job is None:
            return
        if job.status in TERMINAL:
            # e.g. cancelled via the gateway fast path between queue pop and
            # here — must not resurrect a terminal job.
            self.store.remove_from_queue(batch_id)
            return
        if job.cancel_requested or job.status == "cancelling":
            self._finish_cancelled(batch_id)
            return
        if now_s() > job.deadline:
            self.store.update_batch(batch_id, status="expired",
                                    expired_at=now_s())
            self.store.remove_from_queue(batch_id)
            return

        # Ingest: parse + group by model into execution plans.
        try:
            raw = self.files.read(job.tenant, job.input_file_id)
        except FileNotFoundError:
            self.store.update_batch(
                batch_id, status="failed", failed_at=now_s(),
                errors=[{"code": "input_missing",
                         "message": "input file content not found"}],
            )
            self.store.remove_from_queue(batch_id)
            return
        # Re-validate at ingest: create_batch only checks the file exists;
        # purpose!='batch' uploads skip the gateway-side format check.
        plans: dict[str, _Plan] = {}
        total = 0
        try:
            for raw_line in raw.splitlines():
                if not raw_line.strip():
                    continue
                line = json.loads(raw_line)
                if not isinstance(line, dict):
                    raise ValueError("line is not a JSON object")
                if not isinstance(line.get("custom_id"), str) or not isinstance(
                    line.get("body"), dict
                ) or not isinstance(line.get("url"), str):
                    raise ValueError("line missing custom_id/url/body")
                model = line.get("body", {}).get("model", "")
                plans.setdefault(model, _Plan(model)).lines.append(line)
                total += 1
            if total == 0:
                raise ValueError("empty input file")
        except (json.JSONDecodeError, ValueError) as e:
            self.store.update_batch(
                batch_id, status="failed", failed_at=now_s(),
                errors=[{"code": "invalid_input",
                         "message": f"input file invalid: {e}"[:500]}],
            )
            self.store.remove_from_queue(batch_id)
            return

        output_file_id = f"file-{uuid.uuid4().hex[:24]}"
        self.store.update_batch(
            batch_id, status="in_progress", in_progress_at=now_s(),
            total=total, owner=self.instance_id, output_file_id=output_file_id,
            heartbeat_at=now_s(),
        )
        cancel_ev = self.store.subscribe_cancel(batch_id)
        out_lock = asyncio.Lock()

        async def heartbeat() -> None:
            while True:
                await asyncio.sleep(self.cfg.lease_s / 4)
                self.store.update_batch(batch_id, heartbeat_at=now_s())

        hb_task = asyncio.create_task(heartbeat())

        async def run_plan(plan: _Plan) -> None:
            model_sem = asyncio.Semaphore(self.cfg.per_model_concurrency)

            async def one(line: dict) -> None:
                if cancel_ev.is_set():
                    return
                async with model_sem, self._global_sem:
                    if cancel_ev.is_set():
                        return
                    rec = await self._dispatch(job, line)
                    async with out_lock:
                        self.files.append_line(
                            job.tenant, output_file_id, json.dumps(rec)
                        )
                    ok = rec.get("error") is None and (
                        rec["response"]["status_code"] < 400
                    )
                    self.store.add_progress(
                        batch_id, completed=int(ok), failed=int(not ok)
                    )

            await asyncio.gather(*(one(l) for l in plan.lines))

        # Per-model plans run concurrently (reference: per-model goroutines).
        try:
            await asyncio.gather(*(run_plan(p) for p in plans.values()))
        finally:
            hb_task.cancel()
            self.store.unsubscribe_cancel(batch_id)

        # Finalize.
        if self.files.exists(job.tenant, output_file_id):
            nbytes = self.files.size(job.tenant, output_file_id)
            self.store.create_file(
                job.tenant, f"{batch_id}_output.jsonl", "batch_output",
                nbytes, file_id=output_file_id,
            )
        else:
            self.store.update_batch(batch_id, output_file_id=None)
            output_file_id = None
        if cancel_ev.is_set():
            self._finish_cancelled(batch_id)
            return
        self.store.update_batch(
            batch_id, status="finalizing", finalizing_at=now_s()
        )
        final = self.store.get_batch(None, batch_id)
        self.store.update_batch(
            batch_id,
            status="completed" if final.failed < final.total else "failed",
            completed_at=now_s(),
        )
        self.store.remove_from_queue(batch_id)
        log.info("batch %s done: %d ok / %d failed / %d total",
                 batch_id, final.completed, final.failed, final.total)

    def _finish_cancelled(self, batch_id: str) -> None:
        self.store.update_batch(
            batch_id, status="cancelled", cancelled_at=now_s()
        )
        self.store.remove_from_queue(batch_id)

    async def _dispatch(self, job, line: dict) -> dict:
        """One inference request -> one output JSONL record.

        Every request carries ``x-llmd-priority: batch``: the EPP clamps
        it to the backfill band (flow-control band below every
        interactive priority, watermark admission via the
        batch-saturation-filter) and the engine scheduler backfills it
        into idle step headroom — the router's 503 while no replica has
        headroom is an expected WAIT signal, retried with bounded
        exponential backoff until the job deadline.
        """
        url = self.cfg.router_url.rstrip("/") + line["url"]
        headers = {
            h: v for h, v in (job.metadata.get("headers") or {}).items()
            if h.lower() in self.cfg.passthrough_headers
        }
        headers["x-llm-d-tenant"] = job.tenant
        headers["x-llmd-priority"] = "batch"
        rec = {
            "id": f"batch_req_{uuid.uuid4().hex[:16]}",
            "custom_id": line["custom_id"],
            "response": None,
            "error": None,
        }
        retryable = frozenset({429, 500, 502, 503, 504})
        attempt = 0
        while True:
            attempt += 1
            try:
                sess = await self._client()
                async with sess.post(
                    url, json=line["body"], headers=headers
                ) as r:
                    try:
                        body = await r.json()
                    except Exception:
                        body = {"raw": (await r.text())[:2000]}
                    rec["response"] = {
                        "status_code": r.status,
                        "request_id": r.headers.get("x-request-id", ""),
                        "body": body,
                    }
                    rec["error"] = None
                    if r.status not in retryable:
                        return rec
            except Exception as e:  # network-level failure
                rec["response"] = {
                    "status_code": 0, "request_id": "", "body": None,
                }
                rec["error"] = {
                    "code": "connection_error", "message": str(e)[:500],
                }
            delay = min(
                self.cfg.dispatch_backoff_base_s * (2 ** (attempt - 1)),
                self.cfg.dispatch_backoff_max_s,
            )
            if (
                attempt >= self.cfg.dispatch_max_attempts
                or now_s() + delay >= job.deadline
            ):
                return rec  # out of budget: surface the last outcome
            await asyncio.sleep(delay)


class GarbageCollector:
    """Removes expired jobs + files on an interval, bounded deletions/cycle
    (batch-gateway.md "Garbage Collector")."""

    def __init__(
        self,
        store: BatchStore,
        files: FileStore,
        interval_s: float = 300.0,
        max_deletions: int = 100,
        retention_s: float = 7 * 86400,
    ) -> None:
        self.store = store
        self.files = files
        self.interval_s = interval_s
        self.max_deletions = max_deletions
        self.retention_s = retention_s
        self._stop = asyncio.Event()

    def collect_once(self, now: float | None = None) -> int:
        now = now_s() if now is None else now
        deleted = 0
        for job in self.store.expired_jobs(now - self.retention_s,
                                           limit=self.max_deletions):
            # Only files this batch produced: the input file may be shared by
            # other batches and has its own expires_at lifecycle.
            for fid in (job.output_file_id, job.error_file_id):
                if fid:
                    self.files.delete(job.tenant, fid)
                    self.store.delete_file(job.tenant, fid)
            self.store.delete_batch(job.id)
            deleted += 1
        for meta in self.store.expired_files(now,
                                             limit=self.max_deletions - deleted):
            if deleted >= self.max_deletions:
                break
            self.files.delete(meta.tenant, meta.id)
            self.store.delete_file(meta.tenant, meta.id)
            deleted += 1
        return deleted

    async def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.collect_once()
            except Exception:
                log.exception("gc cycle failed")
            try:
                await asyncio.wait_for(self._stop.wait(), self.interval_s)
            except asyncio.TimeoutError:
                pass

    def stop(self) -> None:
        self._stop.set()
