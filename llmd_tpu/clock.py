"""The routing/control stack's monotonic-clock seam.

Every time-dependent decision in the EPP / autoscaler / predictor plane
(breaker cooldowns, flow-control TTLs and EDF deadlines, scrape
freshness, session-affinity TTLs, WVA retention windows) reads the
clock through :func:`monotonic` instead of calling ``time.monotonic()``
directly. In production the seam is a one-attribute indirection over
``time.monotonic``; under the fleet simulator
(:mod:`llmd_tpu.fleetsim`) the simulator installs its virtual-time
event loop's clock, so minutes of fleet time elapse in CI milliseconds
and the same trace + seed replays to a byte-identical scoreboard.

The discipline is machine-checked: the ``direct-clock`` static-analysis
rule (CK001) flags any ``time.time()`` / ``time.monotonic()`` reference
inside ``epp/``, ``autoscale/``, ``predictor/``, ``batch/`` (whose
unix-seconds timestamps/deadlines read the :func:`time` wall seam) or
``fleetsim/`` —
a direct call there silently splits the control plane between real and
simulated time, which is exactly the bug class that makes a soak
nondeterministic.

The seam is process-global on purpose: the control stack runs on one
event loop, and the simulator owns the whole process while a scenario
runs (it restores the real clock in a ``finally``). Engine/device code
does NOT route through this seam — wall-clock there measures real
hardware, which a simulator must never fake.
"""

from __future__ import annotations

import time as _time
from typing import Callable

_REAL: Callable[[], float] = _time.monotonic
_impl: Callable[[], float] = _REAL

_REAL_WALL: Callable[[], float] = _time.time
_impl_wall: Callable[[], float] = _REAL_WALL


def monotonic() -> float:
    """Seconds on the installed monotonic clock (real by default)."""
    return _impl()


def time() -> float:
    """Seconds on the installed WALL clock (real ``time.time`` by
    default). The batch plane's timestamp seam: OpenAI Batch object
    timestamps, job deadlines and queue priorities are unix-seconds
    semantics, so they read this rather than :func:`monotonic` — and
    the fleet simulator installs its virtual axis here too (epoch 0),
    so batch deadlines and GC cycles replay deterministically."""
    return _impl_wall()


def install(fn: Callable[[], float]) -> None:
    """Install a clock source (the fleet simulator's virtual time)."""
    global _impl
    _impl = fn


def install_wall(fn: Callable[[], float]) -> None:
    """Install a wall-clock source (virtual epoch under the simulator)."""
    global _impl_wall
    _impl_wall = fn


def reset() -> None:
    """Restore the real ``time.monotonic`` / ``time.time`` clocks."""
    global _impl, _impl_wall
    _impl = _REAL
    _impl_wall = _REAL_WALL


def installed() -> bool:
    """True when a non-real clock source is active."""
    return _impl is not _REAL or _impl_wall is not _REAL_WALL
