"""Tokenizer loading: HF tokenizers when available, byte-level fallback.

The reference delegates tokenization to vLLM (and exposes it back to the
router via the /v1/*/render endpoints, reference
docs/architecture/advanced/kv-management/kv-indexer.md:104-113). Here the
engine owns a tokenizer directly; the byte-level fallback keeps every test
and random-weight deployment hermetic (no downloads).
"""

from __future__ import annotations

from collections.abc import Sequence


class ByteTokenizer:
    """Deterministic UTF-8 byte tokenizer: id = byte + 3; 0/1/2 = pad/bos/eos.

    Vocabulary of 259 fits any model config with vocab_size >= 259.
    """

    pad_token_id = 0
    bos_token_id = 1
    eos_token_id = 2
    _OFFSET = 3

    @property
    def vocab_size(self) -> int:
        return 256 + self._OFFSET

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        ids = [b + self._OFFSET for b in text.encode("utf-8")]
        if add_special_tokens:
            ids = [self.bos_token_id] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        data = bytes(
            i - self._OFFSET for i in ids if i >= self._OFFSET and i < 256 + self._OFFSET
        )
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(
        self,
        messages: list[dict],
        add_generation_prompt: bool = True,
        tokenize: bool = False,
    ):
        """Minimal role-tagged template (stable across processes)."""
        parts = []
        for m in messages:
            content = m.get("content") or ""
            if isinstance(content, list):  # OpenAI content-part arrays
                content = "".join(
                    p.get("text", "") for p in content if isinstance(p, dict)
                )
            parts.append(f"<|{m.get('role', 'user')}|>{content}</s>")
        if add_generation_prompt:
            parts.append("<|assistant|>")
        text = "".join(parts)
        if tokenize:
            return self.encode(text)
        return text


class HFTokenizerWrapper:
    """Uniform surface over a transformers tokenizer."""

    def __init__(self, tok) -> None:
        self._tok = tok
        self.pad_token_id = tok.pad_token_id or 0
        self.bos_token_id = tok.bos_token_id
        self.eos_token_id = tok.eos_token_id

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens)

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def apply_chat_template(
        self,
        messages: list[dict],
        add_generation_prompt: bool = True,
        tokenize: bool = False,
    ):
        try:
            return self._tok.apply_chat_template(
                messages,
                add_generation_prompt=add_generation_prompt,
                tokenize=tokenize,
            )
        except Exception:
            fallback = ByteTokenizer()
            text = fallback.apply_chat_template(messages, add_generation_prompt, False)
            return self.encode(text) if tokenize else text


def load_tokenizer(path: str | None):
    """Load a tokenizer: HF (local path or hub name) or the byte fallback."""
    if not path or path == "byte":
        return ByteTokenizer()
    from transformers import AutoTokenizer

    return HFTokenizerWrapper(AutoTokenizer.from_pretrained(path))
