"""Prometheus metrics in the model-server protocol the router scrapes.

The EPP↔engine metrics contract (reference
docs/architecture/core/model-servers.md:38-52): TotalQueuedRequests,
TotalRunningRequests, KVCacheUtilization (+ optional BlockSize /
NumGPUBlocks), resolved through a per-engine metric-name mapping. We emit
BOTH the vLLM names (so a stock llm-d EPP scrapes us unchanged with the
vllm mapping) and `llmd:` canonical names.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from llmd_tpu import faults

if TYPE_CHECKING:
    # Annotation-only: importing EngineStats at runtime drags the whole
    # jax engine in, and this module's scrape-side half
    # (parse_prometheus) serves accelerator-free consumers — the EPP
    # data layer and the fleet simulator's control-plane imports.
    from llmd_tpu.engine.engine import EngineStats


def render_metrics(
    stats: EngineStats, model_name: str, lora_adapters: dict | None = None
) -> str:
    label = f'{{model_name="{model_name}"}}'
    gauges = {
        "num_requests_waiting": stats.num_waiting,
        "num_requests_running": stats.num_running,
        # Routing-visible utilization is the BINDING pool: with a SWA ring
        # pool the ring (not the main table) is often the admission
        # constraint under P/D preload bursts, and a scorer reading only
        # main-pool usage would keep sending work to an exhausted engine.
        "gpu_cache_usage_perc": round(
            max(stats.kv_usage, stats.swa_ring_usage), 6
        ),
        "prefix_cache_hit_rate": round(stats.prefix_hit_ratio, 6),
        # Step-pipeline observability (async stepping): the per-step
        # host time the device idles for. Async mode shrinks it to the
        # reconcile/patch sliver; the *_total counters let a scraper (or
        # bench.py --parts async_step) compute a mean over any interval.
        "step_host_gap_ms": round(stats.step_host_gap_ms, 3),
        # Decode dispatches per generated token: the fused-window
        # headline ratio — plain decode windows and fused verify
        # windows both push it down by amortizing dispatch RTT over
        # more emitted tokens per device program.
        "dispatches_per_emitted_token": round(
            stats.dispatches_per_emitted_token, 6
        ),
    }
    # Batch serving tier (docs/architecture/batch-processing.md): the
    # backfill band's scrape surface — backlog is what the WVA counts as
    # deferrable demand (floor-not-scale-up), utilization is the LAST
    # step's budget fraction the band harvested.
    gauges["batch_backlog_jobs"] = stats.batch_backlog_jobs
    gauges["batch_backfill_utilization"] = round(
        stats.batch_backfill_utilization, 6
    )
    if stats.swa_ring_pages:
        gauges["swa_ring_usage_perc"] = round(stats.swa_ring_usage, 6)
        gauges["swa_ring_pages"] = stats.swa_ring_pages
        # Raw main-pool usage stays observable when the ring is busier
        # (gpu_cache_usage_perc above collapses to the max of the two).
        gauges["kv_main_usage_perc"] = round(stats.kv_usage, 6)
        # Hybrid-APC section retention
        gauges["swa_sections"] = stats.swa_sections
    gauges["kv_offload_cpu_pages"] = stats.offload_pages
    gauges["kv_offload_fs_pages"] = stats.offload_fs_pages
    # Decode-pager residency (long-context.md): LIVE-sequence bytes in
    # the offload tier — falls as windows stream back, so a gauge.
    gauges["kv_paged_out_bytes"] = stats.kv_paged_out_bytes
    # Last streamed import's first-group latency: the admission-gate
    # leg of the layer-streamed transfer waterfall (kv-cache.md).
    gauges["kv_stream_first_group_ms"] = round(
        stats.kv_stream_first_group_ms, 2
    )
    counters = {
        "prompt_tokens_total": stats.prompt_tokens,
        "generation_tokens_total": stats.generation_tokens,
        "request_success_total": stats.requests_finished,
        "num_preemptions_total": stats.preemptions,
        "kv_offload_saves_total": stats.offload_saves,
        "kv_offload_restores_total": stats.offload_restores,
        # Batch tier counters: tokens the band backfilled and batch rows
        # recompute-preempted when interactive load returned.
        "batch_tokens_total": stats.batch_tokens,
        "batch_preemptions_total": stats.batch_preemptions,
        # Cross-replica KV federation (kv-federation.md): store-client
        # reads (peer pulls / failures / locate misses), publications
        # the master accepted, pages fetched from the store, and the
        # prompt tokens whose fleet-wide re-prefill those pages avoided
        # — the federation's headline counter.
        "kvstore_pulls_total": stats.kvstore_pulls,
        "kvstore_pull_failures_total": stats.kvstore_pull_failures,
        "kvstore_misses_total": stats.kvstore_misses,
        "kv_federation_published_total": stats.kv_federation_published,
        "kv_federation_hits_total": stats.kv_federation_hits,
        "recompute_avoided_tokens_total": stats.recompute_avoided_tokens,
        # P/D transfer accounting (producer exports / consumer pulls)
        "kv_transfer_exported_requests_total": stats.kv_exported_requests,
        "kv_transfer_exported_bytes_total": stats.kv_exported_bytes,
        "kv_transfer_imported_requests_total": stats.kv_imported_requests,
        "kv_transfer_imported_bytes_total": stats.kv_imported_bytes,
        "kv_transfer_import_failures_total": stats.kv_import_failures,
        # Layer-streamed transfer (the v3 group-framed wire): streamed
        # (layer-group x chunk) cells landed on this consumer.
        "kv_stream_groups_total": stats.kv_stream_groups_total,
        # Publish-budget pacing (LLMD_KV_PUBLISH_BYTES_PER_S): bytes the
        # federation publisher delayed to protect the transfer NIC.
        "kv_publish_paced_bytes_total": stats.kv_publish_paced_bytes_total,
        # Million-token context tier (docs/architecture/long-context.md):
        # late pager window fetches and ring collective steps from
        # context-parallel prefill (paged-out residency is a gauge above).
        "kv_pager_prefetch_late_total": stats.kv_pager_prefetch_late_total,
        "cp_ring_steps_total": stats.cp_ring_steps_total,
        # Async stepping (speculate/rollback contract)
        "engine_steps_total": stats.engine_steps_total,
        "step_host_gap_ms_total": round(stats.step_host_gap_ms_total, 3),
        "async_rollbacks_total": stats.async_rollbacks_total,
        "decode_dispatches_total": stats.decode_dispatches_total,
        # Unified single-dispatch steps (the family split of
        # decode_dispatches_total) and EVERY program engine steps
        # dispatched — step_dispatches_total / engine_steps_total is the
        # unified step's dispatches-per-step headline.
        "unified_steps_total": stats.unified_steps_total,
        "step_dispatches_total": stats.step_dispatches_total,
        # Padding efficiency (flattened-token step, --ragged-qlens):
        # tokens the dispatched programs computed for real vs the pad
        # lanes the traced shapes paid on top; padded/live is the
        # padding-waste gauge the ragged_step bench part bounds.
        "live_tokens_total": stats.live_tokens_total,
        "padded_tokens_total": stats.padded_tokens_total,
        # Robustness trail (docs/architecture/fault-tolerance.md):
        # watchdog trips on the step loop, CRC-rejected bundles, and
        # transfers that degraded to local recompute.
        "engine_watchdog_stalls_total": stats.engine_watchdog_stalls_total,
        "kv_bundle_crc_failures_total": stats.kv_bundle_crc_failures_total,
        "kv_recompute_fallbacks_total": stats.kv_recompute_fallbacks_total,
        # Mid-stream failover (the stream-continuation contract,
        # fault-tolerance.md): resume admissions, the delivered tokens
        # they replayed as committed prefix, and resume requests the
        # serving layer rejected.
        "stream_resumes_total": stats.stream_resumes_total,
        "resume_replayed_tokens_total": stats.resume_replayed_tokens_total,
        "stream_resume_failures_total": stats.stream_resume_failures_total,
    }
    if stats.swa_ring_pages:
        # Hybrid-APC section retention activity
        counters["swa_section_hits_total"] = stats.swa_section_hits
        counters["swa_section_captures_total"] = stats.swa_section_captures
    lines: list[str] = []
    if stats.kv_transfer_failures:
        # Per-(stage, policy) transfer-failure breakdown (llmd-family
        # extension): which leg swallowed the failure and what
        # degradation was applied — the detail behind
        # kv_transfer_import_failures_total's flat count.
        lines.append("# TYPE llmd:kv_transfer_failures_total counter")
        for (stage, policy), n in stats.kv_transfer_failures:
            lines.append(
                f'llmd:kv_transfer_failures_total{{stage="{stage}",'
                f'policy="{policy}",model_name="{model_name}"}} {n}'
            )
    if stats.moe_expert_tokens:
        # Wide-EP MoE (docs/architecture/wide-ep.md): per-logical-expert
        # routed-token counts — the EPLB control loop's input, and the
        # skew panel's series. llmd-family only (vLLM has no per-expert
        # load contract). Dropped slots and the live/peak capacity
        # numbers ride the flat namespaces below.
        lines.append("# TYPE llmd:moe_expert_tokens_total counter")
        for e, n in enumerate(stats.moe_expert_tokens):
            lines.append(
                f'llmd:moe_expert_tokens_total{{expert="{e}",'
                f'model_name="{model_name}"}} {n}'
            )
        gauges["moe_capacity_factor"] = round(stats.moe_capacity_factor, 4)
        gauges["moe_peak_demand"] = round(stats.moe_peak_demand, 4)
        counters["moe_dropped_slots_total"] = stats.moe_dropped_slots_total
        counters["moe_rebalances_total"] = stats.moe_rebalances_total
    injected = faults.injected_counts()
    if injected:
        # Only present while a fault plan is armed (chaos runs): how many
        # injections each site actually delivered, so a matrix leg can
        # assert its fault fired from the same surface it asserts the
        # degradation on.
        lines.append("# TYPE llmd:faults_injected_total counter")
        for site, n in sorted(injected.items()):
            lines.append(
                f'llmd:faults_injected_total{{site="{site}",'
                f'model_name="{model_name}"}} {n}'
            )
    if stats.max_lora:
        # reference model-servers.md:78-89: adapter state rides labels on
        # a gauge named vllm:lora_requests_info. available_lora_adapters
        # is this framework's extension: the FULL registered set — the
        # DYNAMIC registry on paged-pool engines (runtime load/unload),
        # falling back to the build-time static map — so the router can
        # fold adapter identity into prefix hashes even for adapters
        # with nothing in flight. resident_lora_adapters is the HBM
        # working set the tri-state LoraAffinityScorer routes on
        # (docs/architecture/multi-tenant-lora.md).
        running = ",".join(stats.running_lora_adapters)
        waiting = ",".join(stats.waiting_lora_adapters)
        available = ",".join(
            stats.available_lora_adapters or sorted(lora_adapters or ())
        )
        resident = ",".join(stats.resident_lora_adapters) or available
        lines.append("# TYPE vllm:lora_requests_info gauge")
        lines.append(
            f'vllm:lora_requests_info{{max_lora="{stats.max_lora}",'
            f'running_lora_adapters="{running}",'
            f'waiting_lora_adapters="{waiting}",'
            f'available_lora_adapters="{available}",'
            f'resident_lora_adapters="{resident}",'
            f'model_name="{model_name}"}} 1'
        )
        # Paged adapter pool (multi-tenant-lora.md): HBM residency vs
        # the unbounded registry — evictions, cold-load waits, and load
        # API failures are the thrash/degradation trail.
        gauges["lora_pool_resident_adapters"] = (
            stats.lora_pool_resident_adapters
        )
        counters["lora_pool_evictions_total"] = stats.lora_pool_evictions_total
        counters["lora_cold_loads_total"] = stats.lora_cold_loads_total
        counters["lora_load_failures_total"] = stats.lora_load_failures_total
    if stats.spec_accepted_len_hist:
        # Speculative decoding (propose/verify/accept contract,
        # docs/architecture/speculative-decoding.md + observability.md).
        # llmd-family ONLY: these names are this engine's, not vLLM's
        # (vLLM's spec-decode metrics are named differently), so they
        # must not masquerade in the vllm: namespace a stock dashboard
        # keys on.
        lines.append("# TYPE llmd:spec_acceptance_rate gauge")
        lines.append(
            f"llmd:spec_acceptance_rate{label} "
            f"{round(stats.spec_acceptance_rate, 6)}"
        )
        for name, v in (
            ("spec_proposed_tokens_total", stats.spec_proposed_tokens_total),
            ("spec_accepted_tokens_total", stats.spec_accepted_tokens_total),
            # Fused verify windows (spec x decode_window): verify
            # row-iterations run inside fused windows, and windowed
            # rows that hit their emission limit before the window's
            # last iteration.
            ("spec_window_iters_total", stats.spec_window_iters_total),
            (
                "spec_window_early_exit_total",
                stats.spec_window_early_exit_total,
            ),
        ):
            lines.append(f"# TYPE llmd:{name} counter")
            lines.append(f"llmd:{name}{label} {v}")
        # Per-step accepted-draft-length histogram (Prometheus histogram
        # text form; one bucket per accepted length 0..k).
        hist = stats.spec_accepted_len_hist
        lines.append("# TYPE llmd:spec_accepted_len histogram")
        cum = 0
        for ln, cnt in enumerate(hist):
            cum += cnt
            lines.append(
                f'llmd:spec_accepted_len_bucket{{le="{ln}",'
                f'model_name="{model_name}"}} {cum}'
            )
        lines.append(
            f'llmd:spec_accepted_len_bucket{{le="+Inf",'
            f'model_name="{model_name}"}} {cum}'
        )
        total = sum(j * c for j, c in enumerate(hist))
        lines.append(
            f'llmd:spec_accepted_len_sum{{model_name="{model_name}"}} {total}'
        )
        lines.append(
            f'llmd:spec_accepted_len_count{{model_name="{model_name}"}} {cum}'
        )
    if stats.spec_row_depth_hist:
        # Per-row verify depth histogram (--ragged-qlens adaptive depth:
        # bucket d counts decode rows dispatched at a 1 + draft width of
        # exactly d tokens; two buckets populated on one step means two
        # rows ran DIFFERENT verify depths in the same program).
        hist = stats.spec_row_depth_hist
        lines.append("# TYPE llmd:spec_row_depth histogram")
        cum = 0
        for d, cnt in enumerate(hist):
            cum += cnt
            lines.append(
                f'llmd:spec_row_depth_bucket{{le="{d}",'
                f'model_name="{model_name}"}} {cum}'
            )
        lines.append(
            f'llmd:spec_row_depth_bucket{{le="+Inf",'
            f'model_name="{model_name}"}} {cum}'
        )
        total = sum(d * c for d, c in enumerate(hist))
        lines.append(
            f'llmd:spec_row_depth_sum{{model_name="{model_name}"}} {total}'
        )
        lines.append(
            f'llmd:spec_row_depth_count{{model_name="{model_name}"}} {cum}'
        )
    for family in ("vllm", "llmd"):
        for name, v in gauges.items():
            lines.append(f"# TYPE {family}:{name} gauge")
            lines.append(f"{family}:{name}{label} {v}")
        for name, v in counters.items():
            lines.append(f"# TYPE {family}:{name} counter")
            lines.append(f"{family}:{name}{label} {v}")
        lines.append(f"# TYPE {family}:cache_config_info gauge")
        lines.append(
            f'{family}:cache_config_info{{block_size="{stats.page_size}",'
            f'num_gpu_blocks="{stats.num_pages}",model_name="{model_name}"}} 1'
        )
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> dict[str, float]:
    """Parse a Prometheus text page into {metric_name: value}.

    Labels are dropped; repeated names keep the first sample (single-model
    engines emit one series per name). This is the scrape-side half of the
    metrics contract used by the EPP data layer.
    """
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value = line.rsplit(None, 1)
        except ValueError:
            continue
        name = name_part.split("{", 1)[0]
        if name not in out:
            try:
                out[name] = float(value)
            except ValueError:
                continue
    return out
