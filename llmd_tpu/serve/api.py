"""aiohttp OpenAI-compatible API server over AsyncEngine.

Surface (the model-server contract of the reference,
docs/architecture/core/model-servers.md:38-100):
  POST /v1/completions, /v1/chat/completions   (stream + non-stream)
  GET  /v1/models, /health
  GET  /metrics                                 (EPP scrape protocol)
  POST /v1/completions/render, /v1/chat/completions/render, /tokenize
       (the tokenizer surface the router's token-producer calls,
        kv-indexer.md:104-113)
"""

from __future__ import annotations

import asyncio
import dataclasses
import hmac
import json
import logging
import os
import re
import time

import aiohttp
from typing import Any

import pydantic
from aiohttp import web

from llmd_tpu import faults
from llmd_tpu.engine.request import PriorityClass, RequestOutput, SamplingParams
from llmd_tpu.epp.types import (
    HDR_EC_HOST,
    HDR_PRIORITY,
    HDR_RESUME,
    HDR_STREAM_TOKENS,
)
from llmd_tpu.obs.tracing import get_tracer
from llmd_tpu.serve import protocol as P
from llmd_tpu.serve.async_engine import (
    AsyncEngine,
    DeadlineExceeded,
    EngineError,
    RequestFailed,
)
from llmd_tpu.serve.metrics import render_metrics

log = logging.getLogger(__name__)

ENGINE_KEY = web.AppKey("llmd_engine", AsyncEngine)
TOK_KEY = web.AppKey("llmd_tokenizer", object)
MODEL_KEY = web.AppKey("llmd_model_name", str)
MAXLEN_KEY = web.AppKey("llmd_max_model_len", int)
MM_SESSION_KEY = web.AppKey("llmd_mm_session", object)
# adapter name -> slot id (1-based; the base model is slot 0)
LORA_KEY = web.AppKey("llmd_lora_adapters", dict)

_EC_HOST_RE = re.compile(r"[A-Za-z0-9_.\-]{1,253}:\d{1,5}")
_EC_DIGEST_RE = re.compile(r"[0-9a-f]{16,64}")


async def _resolve_ec_parts(request: web.Request, messages: list) -> int:
    """E-disaggregation consumer side: pull EC embedding handles placed by
    the sidecar (parts of type `ec_embedding`), free-notify the encode
    worker, and substitute a digest-stable placeholder marker.

    The pull + free exercises the full EC-connector lease lifecycle
    (multimodal-serving/README.md:44-46). The pulled embeddings are the
    injection point for a trained VLM checkpoint (soft tokens at the
    placeholder positions); with random-init weights the engine consumes
    the stable `<|image:digest|>` marker, which keeps prefix caching
    content-correct across identical images.
    """
    pulled = 0
    session = request.app.get(MM_SESSION_KEY)
    # SSRF guard. When LLMD_EC_ALLOWED_HOSTS is set it is authoritative:
    # only those encoder hosts are ever pulled from, even with a vouching
    # header (a direct-to-engine client can forge headers). Without the
    # allowlist, trust the sidecar's x-llm-d-ec-host (the sidecar strips
    # the client's copy) — this stops clients routed through the sidecar
    # but NOT a caller with direct engine-port access; deployments where
    # that matters must set the allowlist (and front encoders with a
    # stable Service name) or network-police the engine port.
    env_allowed = {
        h.strip()
        for h in os.environ.get("LLMD_EC_ALLOWED_HOSTS", "").split(",")
        if h.strip()
    }
    if env_allowed:
        allowed = env_allowed
    else:
        vouched = request.headers.get(HDR_EC_HOST, "")
        allowed = {vouched} if vouched else set()
    for m in messages:
        content = m.get("content") if isinstance(m, dict) else None
        if not isinstance(content, list):
            continue
        for part in content:
            if not (isinstance(part, dict) and part.get("type") == "ec_embedding"):
                continue
            ec = part.get("ec_embedding") or {}
            host, digest = str(ec.get("host") or ""), str(ec.get("digest") or "")
            if (
                host not in allowed
                or not _EC_HOST_RE.fullmatch(host)
                or not _EC_DIGEST_RE.fullmatch(digest)
            ):
                host = ""
            if session is not None and host and digest:
                try:
                    async with session.get(
                        f"http://{host}/v1/ec/{digest}"
                    ) as resp:
                        if resp.status == 200:
                            await resp.read()
                            pulled += 1
                except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                    log.warning("EC pull %s/%s failed: %s", host, digest, e)
            # No free-notify: EC entries are content-addressed and may be
            # shared by concurrent requests and by the P and D engines of
            # one request; the producer's lease (+ LRU) reclaims them.
            # POST /v1/ec/{digest}/free remains for explicit invalidation.
            part.clear()
            part["type"] = "text"
            part["text"] = f"<|image:{digest}|>"
    return pulled


class Detokenizer:
    """Incremental detokenization with stop-string scanning.

    Decodes the full output each call and diffs against the previously
    emitted text so multi-token/multi-byte characters stream correctly.
    While stop strings are configured, the longest possible stop-string
    prefix (max stop length - 1 chars) is held back from emission so a stop
    match never requires retracting text already sent to the client; the
    held-back tail is flushed with ``feed([], final=True)``.
    """

    def __init__(self, tokenizer, stops: list[str]) -> None:
        self.tok = tokenizer
        self.stops = stops
        self._holdback = max((len(s) for s in stops), default=1) - 1
        self.ids: list[int] = []
        self.emitted = ""
        self.stopped = False

    def feed(self, new_ids: list[int], final: bool = False) -> str:
        """Returns the text delta to emit; sets .stopped on a stop match."""
        self.ids.extend(new_ids)
        text = self.tok.decode(self.ids)
        if text.endswith("�"):
            # Incomplete UTF-8 sequence: hold back until it completes.
            text = text[: text.rfind("�")]
        if len(text) < len(self.emitted):
            return ""
        # Earliest occurrence across ALL stop strings wins.
        idx = min(
            (i for i in (text.find(s) for s in self.stops) if i != -1), default=-1
        )
        if idx != -1:
            self.stopped = True
            text = text[:idx]
            final = True
        if final or not self.stops:
            limit = len(text)
        else:
            limit = max(len(self.emitted), len(text) - self._holdback)
        delta = text[len(self.emitted) : limit]
        self.emitted = text[:limit]
        return delta


def _tokenize_prompt(tokenizer, prompt) -> list[int]:
    if isinstance(prompt, str):
        return tokenizer.encode(prompt)
    if isinstance(prompt, list):
        if not prompt:
            raise ValueError("empty prompt")
        if isinstance(prompt[0], int):
            return list(prompt)
        if isinstance(prompt[0], str):
            if len(prompt) != 1:
                raise ValueError("batched prompts unsupported; send one request per prompt")
            return tokenizer.encode(prompt[0])
        if isinstance(prompt[0], list):
            if len(prompt) != 1:
                raise ValueError("batched prompts unsupported; send one request per prompt")
            return list(prompt[0])
    raise ValueError("invalid prompt type")


def _chat_prompt_ids(tokenizer, messages: list) -> list[int]:
    """messages: ChatMessage models or plain dicts."""
    msgs = [
        m.model_dump() if isinstance(m, P.ChatMessage) else m for m in messages
    ]
    ids = tokenizer.apply_chat_template(msgs, add_generation_prompt=True, tokenize=True)
    return list(ids)


def _error(status: int, message: str) -> web.Response:
    return web.json_response(P.error_body(message, code=status), status=status)


def _error_status(e: BaseException) -> int:
    """Engine-exception -> HTTP status, shared by every generate surface
    (streamed terminal frames and non-streaming bodies alike)."""
    if isinstance(e, RequestFailed):
        return 400
    if isinstance(e, DeadlineExceeded):
        return 504
    return 500


async def _collect(
    engine: AsyncEngine,
    rid: str,
    prompt_ids: list[int],
    sampling: SamplingParams,
    detok: Detokenizer,
    priority: int,
    kv_transfer_params: dict | None,
    lora_id: int = 0,
    lora_name: str = "",
    deadline_s: float | None = None,
    resume_output_tokens: int = 0,
):
    """Run to completion; returns (text, finish_reason, final RequestOutput)."""
    finish = None
    final: RequestOutput | None = None
    async for out in engine.generate(rid, prompt_ids, sampling, priority,
                                     kv_transfer_params, lora_id, lora_name,
                                     deadline_s, resume_output_tokens):
        detok.feed(out.new_token_ids, final=out.finished)
        final = out
        if detok.stopped:
            engine.abort(rid)
            finish = "stop"
            break
        if out.finished:
            finish = out.finish_reason.value if out.finish_reason else None
    return detok.emitted, finish, final


# --------------------------------------------------------------------- #
# handlers


def _request_deadline_s(request: web.Request) -> float | None:
    """Per-request deadline: `x-request-deadline-s` header, falling back
    to LLMD_REQUEST_DEADLINE_S. Malformed values degrade to no deadline
    (a bad header must not reject a request the engine could serve)."""
    raw = request.headers.get("x-request-deadline-s") or os.environ.get(
        "LLMD_REQUEST_DEADLINE_S", ""
    )
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def _effective_priority(request: web.Request, body_priority: int) -> int:
    """Fold the batch-band header into the request's priority.

    `x-llmd-priority: batch` (sent by the batch processor,
    docs/architecture/batch-processing.md) clamps the request to the
    offline backfill band (PriorityClass.BATCH) regardless of what the
    body claimed — a batch job must never smuggle itself into the
    interactive band by omitting the body field. Other header values
    are ignored (the body integer stands)."""
    if request.headers.get(HDR_PRIORITY, "").strip().lower() == "batch":
        return min(int(body_priority), int(PriorityClass.BATCH))
    return int(body_priority)


async def handle_health(request: web.Request) -> web.Response:
    # Liveness stays cheap — but a watchdog-stalled engine IS dead for
    # serving purposes: flip 503 so the platform restarts/ejects us
    # instead of routing into a wedge.
    engine = request.app[ENGINE_KEY]
    if engine.stalled:
        return web.json_response(
            {"status": "stalled", "watchdog_s": engine.watchdog_s},
            status=503,
        )
    return web.json_response({"status": "ok"})


async def handle_ready(request: web.Request) -> web.Response:
    """Readiness (engine warmed + watchdog fresh + not draining/paused):
    the gateway's routing gate, distinct from /health liveness."""
    engine = request.app[ENGINE_KEY]
    if engine.ready:
        return web.json_response({"status": "ready"})
    return web.json_response(
        {
            "status": "not-ready",
            "draining": engine.draining,
            "paused": engine.paused,
            "stalled": engine.stalled,
        },
        status=503,
    )


async def handle_models(request: web.Request) -> web.Response:
    model = request.app[MODEL_KEY]
    entries = [
        {
            "id": model,
            "object": "model",
            "created": int(time.time()),
            "owned_by": "llmd-tpu",
            "max_model_len": request.app[MAXLEN_KEY],
        }
    ]
    # LoRA adapters serve under their own model ids (vLLM convention).
    # Dynamic-pool engines list the runtime registry (load/unload moves
    # this set); static engines the build-time map.
    registry = _adapter_registry(request)
    names = (
        registry.names()
        if registry is not None
        else list(request.app.get(LORA_KEY) or {})
    )
    for name in names:
        entries.append(
            {
                "id": name,
                "object": "model",
                "created": int(time.time()),
                "owned_by": "llmd-tpu",
                "parent": model,
                "max_model_len": request.app[MAXLEN_KEY],
            }
        )
    return web.json_response(
        {
            "object": "list",
            "data": entries,
        }
    )


async def handle_metrics(request: web.Request) -> web.Response:
    engine = request.app[ENGINE_KEY]
    return web.Response(
        text=render_metrics(
            engine.stats, request.app[MODEL_KEY],
            request.app.get(LORA_KEY) or None,
        ),
        content_type="text/plain",
    )


async def handle_tokenize(request: web.Request) -> web.Response:
    tokenizer = request.app[TOK_KEY]
    try:
        body = await request.json()
        if "messages" in body:
            ids = _chat_prompt_ids(
                tokenizer, [P.ChatMessage(**m) for m in body["messages"]]
            )
        else:
            ids = _tokenize_prompt(tokenizer, body.get("prompt", ""))
    except (json.JSONDecodeError, ValueError, TypeError, AttributeError,
            pydantic.ValidationError) as e:
        return _error(400, str(e))
    return web.json_response({"tokens": ids, "count": len(ids)})


async def handle_embeddings(request: web.Request) -> web.Response:
    """OpenAI /v1/embeddings: mean-pooled L2-normalized hidden states.

    `input` accepts a string, a list of strings, a token array, or a list
    of token arrays (the OpenAI surface; reference request-handling.md:
    50-51 routes /embeddings, and the vllmgrpc parser's Embed verb is the
    token-in form)."""
    tokenizer = request.app[TOK_KEY]
    engine: AsyncEngine = request.app[ENGINE_KEY]
    try:
        body = await request.json()
        if not isinstance(body, dict):
            return _error(400, "request body must be a JSON object")
        # Same model-id discipline as the generate endpoints: adapter ids
        # embed through their slot; unknown ids 404 rather than silently
        # embedding with the base model.
        try:
            lora_id, lora_name = _resolve_lora(
                request, body.get("model") or ""
            )
        except UnknownModelError as e:
            return _error(404, f"unknown model {e}")
        raw = body.get("input")
        if isinstance(raw, str):
            items = [raw]
        elif isinstance(raw, list) and raw and isinstance(raw[0], int):
            items = [raw]
        elif isinstance(raw, list):
            items = raw
        else:
            return _error(400, "input must be a string, list of strings, "
                               "or token array(s)")
        prompts = []
        for item in items:
            if isinstance(item, str):
                prompts.append(_tokenize_prompt(tokenizer, item))
            elif isinstance(item, list) and all(isinstance(t, int) for t in item):
                prompts.append(item)
            else:
                return _error(400, "mixed or invalid input items")
        if not prompts or any(not p for p in prompts):
            return _error(400, "empty input")
    except (json.JSONDecodeError, ValueError, TypeError) as e:
        return _error(400, str(e))
    try:
        vectors = await engine.embed(prompts, lora_id, lora_name)
    except ValueError as e:  # over max_model_len
        return _error(400, str(e))
    total_tokens = sum(len(p) for p in prompts)
    return web.json_response({
        "object": "list",
        "model": body.get("model") or request.app[MODEL_KEY],
        "data": [
            {"object": "embedding", "index": i, "embedding": row}
            for i, row in enumerate(vectors.tolist())
        ],
        "usage": {"prompt_tokens": total_tokens, "total_tokens": total_tokens},
    })


async def handle_cache_probe(request: web.Request) -> web.Response:
    """POST /v1/cache/probe — the P/D byte-diet question: how many
    leading FULL pages of this request's prompt are already cached here?

    Accepts the same body shape as /v1/completions ("prompt") or
    /v1/chat/completions ("messages"); the sidecar calls it on the local
    decode engine before phase 1 so the prefiller can skip staging pages
    the decode side already holds (reference disagg decider,
    scheduling.md:113)."""
    engine: AsyncEngine = request.app[ENGINE_KEY]
    tokenizer = request.app[TOK_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error(400, f"invalid JSON: {e}")
    try:
        if body.get("messages") is not None:
            ids = _chat_prompt_ids(tokenizer, body["messages"])
        elif body.get("prompt") is not None:
            ids = _tokenize_prompt(tokenizer, body["prompt"])
        else:
            return _error(400, "prompt or messages is required")
    except (ValueError, TypeError) as e:
        return _error(400, str(e))
    eng = engine.engine
    return web.json_response({
        "cached_full_pages": eng.cached_prefix_pages(ids),
        "page_size": eng.allocator.page_size,
        "num_full_pages": len(ids) // eng.allocator.page_size,
    })


async def handle_completions_render(request: web.Request) -> web.Response:
    """vLLM-style render: return the token ids the engine would see."""
    tokenizer = request.app[TOK_KEY]
    try:
        req = P.CompletionRequest(**await request.json())
        ids = _tokenize_prompt(tokenizer, req.prompt)
    except (ValueError, TypeError) as e:
        return _error(400, str(e))
    return web.json_response({"prompt_token_ids": ids, "model": req.model})


async def handle_chat_render(request: web.Request) -> web.Response:
    tokenizer = request.app[TOK_KEY]
    try:
        req = P.ChatCompletionRequest(**await request.json())
        ids = _chat_prompt_ids(tokenizer, req.messages)
    except (ValueError, TypeError) as e:
        return _error(400, str(e))
    return web.json_response({"prompt_token_ids": ids, "model": req.model})


def _sse(data: dict) -> bytes:
    return b"data: " + json.dumps(data, separators=(",", ":")).encode() + b"\n\n"


async def _stream_response(
    request: web.Request,
    engine: AsyncEngine,
    rid: str,
    model: str,
    prompt_ids: list[int],
    sampling: SamplingParams,
    detok: Detokenizer,
    priority: int,
    kv_transfer_params: dict | None,
    chat: bool,
    span=None,
    lora_id: int = 0,
    lora_name: str = "",
    deadline_s: float | None = None,
    resume_output_tokens: int = 0,
    stream_token_ids: bool = False,
    resume_leg: bool = False,
) -> web.StreamResponse:
    resp = web.StreamResponse(
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "x-request-id": rid,
        }
    )
    await resp.prepare(request)
    if chat and not resume_leg:
        # A resume leg continues an already-opened client stream: the
        # role preamble went out with the first leg. `resume_leg` covers
        # the empty-history replay too (HDR_RESUME: the upstream died
        # after the preamble but before the first token frame).
        await resp.write(_sse(P.chat_chunk(rid, model, {"role": "assistant"}, None)))
    finish = None
    n_out = resume_output_tokens
    cached = 0
    try:
        async for out in engine.generate(rid, prompt_ids, sampling, priority,
                                         kv_transfer_params, lora_id, lora_name,
                                         deadline_s, resume_output_tokens):
            delta = detok.feed(out.new_token_ids, final=out.finished)
            n_out = out.num_output_tokens
            cached = out.num_cached_tokens
            if detok.stopped:
                engine.abort(rid)
                finish = "stop"
            elif out.finished:
                finish = out.finish_reason.value if out.finish_reason else None
            # Emit a chunk per engine output even when the detokenizer
            # holds text back (incomplete UTF-8 / stop-string holdback):
            # the empty delta is what tells a streaming client the first
            # token EXISTS — without it, TTFT degrades to time-to-full-
            # response whenever the text buffer never flushes early.
            if delta or out.new_token_ids:
                chunk = (
                    P.chat_chunk(rid, model, {"content": delta}, None)
                    if chat
                    else P.completion_chunk(rid, model, delta, None)
                )
                if stream_token_ids:
                    # Raw token ids ride the frame for the router's
                    # resume history (HDR_STREAM_TOKENS contract); the
                    # router strips them before the client sees bytes.
                    chunk["token_ids"] = list(out.new_token_ids)
                await resp.write(_sse(chunk))
                # Injection site: the replica "dies" mid-stream — the
                # transport is severed without an SSE terminator, which
                # is exactly what a crashed engine looks like to the
                # router's upstream read loop.
                if faults.fires("serve.stream.cut", rid):
                    engine.abort(rid)
                    if request.transport is not None:
                        request.transport.close()
                    return resp
            if finish is not None:
                break
    except (RequestFailed, EngineError) as e:
        # The stream is already committed: a terminal error frame (504
        # for deadline, 500 engine, 400 client) instead of a hang.
        await resp.write(_sse(P.error_body(str(e), code=_error_status(e))))
        await resp.write(b"data: [DONE]\n\n")
        return resp
    except (asyncio.CancelledError, ConnectionResetError):
        engine.abort(rid)
        raise
    if span is not None:
        span.set("gen_ai.usage.completion_tokens", n_out)
        span.set("llm_d.cache.hit_tokens", cached)
    final = (
        P.chat_chunk(rid, model, {}, finish)
        if chat
        else P.completion_chunk(rid, model, "", finish)
    )
    final["usage"] = P.usage_dict(
        len(prompt_ids) - resume_output_tokens, n_out, cached
    )
    await resp.write(_sse(final))
    await resp.write(b"data: [DONE]\n\n")
    await resp.write_eof()
    return resp


async def _stream_response_multi(
    request: web.Request,
    engine: AsyncEngine,
    rid: str,
    model: str,
    prompt_ids: list[int],
    sampling: SamplingParams,
    tokenizer,
    stops: list[str],
    n: int,
    priority: int,
    kv_transfer_params: dict | None,
    chat: bool,
    span=None,
    lora_id: int = 0,
    lora_name: str = "",
    deadline_s: float | None = None,
) -> web.StreamResponse:
    """SSE with n>1: one engine stream per choice, chunks multiplexed onto
    the response with their choice index (OpenAI interleave semantics).
    Choice i derives seed+i when seeded; only choice 0 carries the remote
    KV pull — mirroring the non-streaming n>1 path."""
    resp = web.StreamResponse(
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "x-request-id": rid,
        }
    )
    await resp.prepare(request)
    if chat:
        for i in range(n):
            await resp.write(_sse(
                P.chat_chunk(rid, model, {"role": "assistant"}, None, index=i)
            ))
    queue: asyncio.Queue = asyncio.Queue()
    totals = {"out": 0, "cached": 0}

    async def pump(i: int) -> None:
        sp = (
            dataclasses.replace(sampling, seed=sampling.seed + i)
            if sampling.seed is not None
            else sampling
        )
        crid = f"{rid}-{i}"
        detok = Detokenizer(tokenizer, stops)
        terminal = False
        try:
            async for out in engine.generate(
                crid, prompt_ids, sp, priority,
                kv_transfer_params if i == 0 else None, lora_id, lora_name,
                deadline_s,
            ):
                delta = detok.feed(out.new_token_ids, final=out.finished)
                finish = None
                if detok.stopped:
                    engine.abort(crid)
                    finish = "stop"
                elif out.finished:
                    finish = (
                        out.finish_reason.value if out.finish_reason else None
                    )
                # Empty deltas still signal token arrival (UTF-8 / stop
                # holdback) — same TTFT honesty as the single-stream path.
                if delta or out.new_token_ids:
                    await queue.put(("delta", i, delta))
                if finish is not None or out.finished:
                    totals["out"] += out.num_output_tokens
                    totals["cached"] = max(
                        totals["cached"], out.num_cached_tokens
                    )
                    terminal = True
                    await queue.put(("finish", i, finish))
                    return
            # Generator exhausted without a finished output (defensive):
            # still emit a terminal item or the consumer loop waits forever.
            terminal = True
            await queue.put(("finish", i, None))
        except asyncio.CancelledError:
            raise
        # llmd: allow(broad-except) -- the failure IS surfaced: forwarded to the consumer loop as a terminal error item
        except Exception as e:
            # ANY pump failure must surface as a terminal item — a silent
            # exit deadlocks the `while done < n` consumer.
            if not terminal:
                await queue.put(("error", i, e))

    tasks = [asyncio.ensure_future(pump(i)) for i in range(n)]
    done = 0
    try:
        while done < n:
            kind, i, payload = await queue.get()
            if kind == "error":
                await resp.write(_sse(P.error_body(
                    str(payload), code=_error_status(payload),
                )))
                await resp.write(b"data: [DONE]\n\n")
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                return resp
            if kind == "delta":
                chunk = (
                    P.chat_chunk(rid, model, {"content": payload}, None, index=i)
                    if chat
                    else P.completion_chunk(rid, model, payload, None, index=i)
                )
            else:
                done += 1
                chunk = (
                    P.chat_chunk(rid, model, {}, payload, index=i)
                    if chat
                    else P.completion_chunk(rid, model, "", payload, index=i)
                )
            await resp.write(_sse(chunk))
    except (asyncio.CancelledError, ConnectionResetError):
        for i in range(n):
            engine.abort(f"{rid}-{i}")
        for t in tasks:
            t.cancel()
        raise
    if span is not None:
        span.set("gen_ai.usage.completion_tokens", totals["out"])
        span.set("llm_d.cache.hit_tokens", totals["cached"])
    usage_chunk = {
        "id": rid,
        "object": "chat.completion.chunk" if chat else "text_completion",
        "model": model,
        "choices": [],
        "usage": P.usage_dict(len(prompt_ids), totals["out"], totals["cached"]),
    }
    await resp.write(_sse(usage_chunk))
    await resp.write(b"data: [DONE]\n\n")
    await resp.write_eof()
    return resp


def _validate_resume(resume_ids, max_tokens: int, n: int = 1) -> str | None:
    """Shared resume-admission validation for every generate surface
    (OpenAI + vllmgrpc): None = admissible, else the 400 message. The
    caller counts `stream_resume_failures_total` on rejection."""
    if n != 1:
        return "resume_token_ids requires n == 1"
    if not (
        isinstance(resume_ids, list)
        and all(isinstance(t, int) and 0 <= t for t in resume_ids)
    ):
        return "resume_token_ids must be non-negative token ids"
    if len(resume_ids) > max_tokens:
        return (
            f"resume history of {len(resume_ids)} tokens exceeds the "
            f"request's max_tokens {max_tokens}"
        )
    return None


def _resume_finished(
    prompt_len: int,
    resume_ids: list[int],
    sampling: SamplingParams,
    max_len: int,
) -> str | None:
    """Finish reason already reached by the DELIVERED history — the dead
    replica emitted the terminal token but its finish frame was lost.
    Mirrors the engine's stop-check order (stop token, then length)."""
    if (
        not sampling.ignore_eos
        and resume_ids
        and resume_ids[-1] in sampling.stop_token_ids
    ):
        return "stop"
    if len(resume_ids) >= sampling.max_tokens:
        return "length"
    if prompt_len + len(resume_ids) >= max_len:
        return "length"
    return None


async def _finish_only_stream(
    request: web.Request,
    rid: str,
    model: str,
    chat: bool,
    finish: str,
    usage: dict,
) -> web.StreamResponse:
    """Resume leg with nothing left to generate: only the terminal frame
    (+ usage + [DONE]) was lost with the dead replica — emit exactly
    that, so the stitched client stream matches an uninterrupted one."""
    resp = web.StreamResponse(
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "x-request-id": rid,
        }
    )
    await resp.prepare(request)
    final = (
        P.chat_chunk(rid, model, {}, finish)
        if chat
        else P.completion_chunk(rid, model, "", finish)
    )
    final["usage"] = usage
    await resp.write(_sse(final))
    await resp.write(b"data: [DONE]\n\n")
    await resp.write_eof()
    return resp


class UnknownModelError(Exception):
    pass


def _adapter_registry(request: web.Request):
    """The engine's DYNAMIC adapter registry (paged-pool engines,
    docs/architecture/multi-tenant-lora.md), or None on static/no-LoRA
    engines."""
    engine = request.app.get(ENGINE_KEY)
    return getattr(getattr(engine, "engine", None), "adapter_registry", None)


def _resolve_lora(request: web.Request, model: str) -> tuple[int, str]:
    """Model id -> (lora slot, adapter name). With adapters configured,
    an id that is neither the base model nor an adapter is a client error
    (adapters are advertised as distinct model ids; silently serving the
    base for a typo'd name masks misconfiguration).

    Dynamic-pool engines resolve by NAME: the engine owns the name->slot
    map (residency moves at runtime), so the returned slot is 0 and the
    name alone rides to add_request."""
    adapters = request.app.get(LORA_KEY) or {}
    if model in adapters:
        return adapters[model], model
    registry = _adapter_registry(request)
    if registry is not None and model and registry.has(model):
        return 0, model
    known = bool(adapters) or (registry is not None and len(registry))
    if known and model and model != request.app[MODEL_KEY]:
        raise UnknownModelError(model)
    return 0, ""


async def _handle_generate(request: web.Request, chat: bool) -> web.StreamResponse:
    engine = request.app[ENGINE_KEY]
    tokenizer = request.app[TOK_KEY]
    model = request.app[MODEL_KEY]
    max_len = request.app[MAXLEN_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error(400, f"invalid JSON: {e}")
    try:
        if chat:
            req = P.ChatCompletionRequest(**body)
            msgs = [m.model_dump() for m in req.messages]
            await _resolve_ec_parts(request, msgs)
            prompt_ids = _chat_prompt_ids(tokenizer, msgs)
            req_max = req.max_completion_tokens or req.max_tokens
        else:
            req = P.CompletionRequest(**body)
            prompt_ids = _tokenize_prompt(tokenizer, req.prompt)
            req_max = req.max_tokens
    except (ValueError, TypeError, pydantic.ValidationError) as e:
        return _error(400, str(e))
    if req.n < 1 or req.n > 16:
        return _error(400, "n must be in [1, 16]")
    if len(prompt_ids) >= max_len:
        return _error(400, f"prompt length {len(prompt_ids)} >= max_model_len {max_len}")
    budget = max_len - len(prompt_ids)
    max_tokens = min(req_max if req_max is not None else budget, budget)
    eos = getattr(tokenizer, "eos_token_id", None)
    sampling = P.to_sampling(req, eos, max_tokens)
    rid = request.headers.get("x-request-id") or P.request_id(
        "chatcmpl" if chat else "cmpl"
    )
    # Mid-stream failover resume (docs/architecture/fault-tolerance.md):
    # the delivered history becomes committed prefix; the response
    # carries ONLY the continuation, starting at the exact next output
    # position (byte-identical for greedy and seeded streams).
    resume_ids = list(req.resume_token_ids or [])
    if resume_ids:
        reject = _validate_resume(resume_ids, max_tokens, req.n)
        if reject is not None:
            engine.stats.stream_resume_failures_total += 1
            return _error(400, reject)
    try:
        lora_id, lora_name = _resolve_lora(request, req.model)
    except UnknownModelError:
        return _error(404, f"model {req.model!r} not found")
    if lora_name:
        model = lora_name  # responses echo the requested adapter id
    detok = Detokenizer(tokenizer, P.stop_strings(req.stop))
    # Engine-side span continues the router's traceparent (reference
    # tracing.md: per-hop spans; cache-hit attribution via cached tokens).
    span = get_tracer().start_span(
        "engine.generate",
        traceparent=request.headers.get("traceparent"),
        kind="SPAN_KIND_SERVER",
    )
    span.set("gen_ai.request.model", model)
    span.set("gen_ai.usage.prompt_tokens", len(prompt_ids))
    span.set("llm_d.request.streaming", bool(req.stream))
    deadline_s = _request_deadline_s(request)
    priority = _effective_priority(request, req.priority)
    stream_token_ids = request.headers.get(HDR_STREAM_TOKENS, "") == "1"
    resume_leg = bool(resume_ids) or (
        request.headers.get(HDR_RESUME, "") == "1"
    )

    engine_prompt_ids = prompt_ids
    resume_text_base = 0
    if resume_ids:
        span.set("llm_d.resume.tokens", len(resume_ids))
        fin = _resume_finished(len(prompt_ids), resume_ids, sampling, max_len)
        # Replaying the history through a fresh detokenizer reproduces
        # the exact text the first leg emitted (decode-then-diff is
        # deterministic), so deltas continue mid-UTF-8 and mid-holdback.
        detok.feed(resume_ids, final=fin is not None)
        if fin is None and detok.stopped:
            fin = "stop"  # history ends exactly on a stop string
        resume_text_base = len(detok.emitted)
        if fin is not None:
            span.end()
            usage = P.usage_dict(len(prompt_ids), len(resume_ids))
            if req.stream:
                return await _finish_only_stream(
                    request, rid, model, chat, fin, usage
                )
            builder = P.chat_response if chat else P.completion_response
            return web.json_response(
                builder(rid, model, "", fin, usage),
                headers={"x-request-id": rid},
            )
        engine_prompt_ids = prompt_ids + resume_ids

    if req.stream:
        try:
            if req.n > 1:
                return await _stream_response_multi(
                    request, engine, rid, model, prompt_ids, sampling,
                    tokenizer, P.stop_strings(req.stop), req.n,
                    priority, req.kv_transfer_params, chat, span,
                    lora_id, lora_name, deadline_s,
                )
            return await _stream_response(
                request, engine, rid, model, engine_prompt_ids, sampling,
                detok, priority, req.kv_transfer_params, chat, span,
                lora_id, lora_name, deadline_s,
                resume_output_tokens=len(resume_ids),
                stream_token_ids=stream_token_ids,
                resume_leg=resume_leg,
            )
        except BaseException as e:
            span.error(str(e))
            raise
        finally:
            span.end()
    try:
        if req.n == 1:
            choices = [await _collect(
                engine, rid, engine_prompt_ids, sampling, detok, priority,
                req.kv_transfer_params, lora_id, lora_name, deadline_s,
                resume_output_tokens=len(resume_ids),
            )]
        else:
            # n parallel samples share the prompt (and its cached prefix).
            # With a seed set, choice i derives seed+i so the batch is
            # reproducible; unseeded choices draw independent randomness.
            # Greedy (temperature=0) necessarily yields identical choices,
            # matching OpenAI semantics. Only choice 0 carries the remote
            # KV pull (one transfer; siblings reuse the cached prefix or
            # recompute locally).
            async def one(i: int):
                sp = (
                    dataclasses.replace(sampling, seed=sampling.seed + i)
                    if sampling.seed is not None
                    else sampling
                )
                return await _collect(
                    engine, f"{rid}-{i}", prompt_ids, sp,
                    Detokenizer(tokenizer, P.stop_strings(req.stop)),
                    priority,
                    req.kv_transfer_params if i == 0 else None,
                    lora_id, lora_name, deadline_s,
                )

            tasks = [asyncio.ensure_future(one(i)) for i in range(req.n)]
            try:
                choices = list(await asyncio.gather(*tasks))
            except BaseException:
                # First failure: stop the siblings (cancellation aborts
                # their engine requests) and drain their exceptions.
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                raise
        text, finish, final = choices[0]
        if resume_text_base:
            # The response body carries ONLY the continuation; the
            # client already holds the replayed history's text.
            text = text[resume_text_base:]
    except RequestFailed as e:
        span.error(str(e))
        span.end()
        return _error(400, str(e))
    except DeadlineExceeded as e:
        span.error(str(e))
        span.end()
        return web.json_response(
            P.error_body(str(e), etype="timeout_error", code=504), status=504
        )
    except EngineError as e:
        span.error(str(e))
        span.end()
        return web.json_response(
            P.error_body(str(e), etype="internal_error", code=500), status=500
        )
    except BaseException as e:
        # CancelledError on client disconnect etc.: the span for the
        # anomalous request must still export.
        span.error(str(e) or type(e).__name__)
        span.end()
        raise
    completion_tokens = sum(f.num_output_tokens for _, _, f in choices if f)
    span.set("gen_ai.usage.completion_tokens", completion_tokens)
    span.set("llm_d.cache.hit_tokens", final.num_cached_tokens if final else 0)
    span.end()
    usage = P.usage_dict(
        len(prompt_ids),
        completion_tokens,
        final.num_cached_tokens if final else 0,
    )
    kvp = final.kv_transfer_params if final else None
    builder = P.chat_response if chat else P.completion_response
    resp = builder(rid, model, text, finish, usage, kvp)
    if req.n > 1:
        tmpl = resp["choices"][0]
        resp["choices"] = [
            {
                **tmpl,
                "index": i,
                **(
                    {"message": {"role": "assistant", "content": txt}}
                    if chat
                    else {"text": txt}
                ),
                "finish_reason": fin,
            }
            for i, (txt, fin, _) in enumerate(choices)
        ]
    return web.json_response(resp, headers={"x-request-id": rid})


async def handle_grpc_embed(request: web.Request) -> web.Response:
    """vLLM gRPC Embed, JSON-transcoded: token-in / vector-out."""
    engine = request.app[ENGINE_KEY]
    max_len = request.app[MAXLEN_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error(400, f"invalid JSON: {e}")
    if not isinstance(body, dict):
        return _error(400, "request body must be a JSON object")
    try:
        lora_id, lora_name = _resolve_lora(
            request, str(body.get("model") or "")
        )
    except UnknownModelError as e:
        return _error(404, f"unknown model {e}")
    ids = body.get("prompt_token_ids") or body.get("token_ids") or []
    if not (isinstance(ids, list) and ids):
        return _error(400, "prompt_token_ids must be a non-empty list")
    # single token array or batch of arrays
    prompts = ids if isinstance(ids[0], list) else [ids]
    for p in prompts:
        if not (isinstance(p, list) and p and all(isinstance(t, int) for t in p)):
            return _error(400, "prompt_token_ids must be int token array(s)")
        if len(p) > max_len:
            return _error(400, f"prompt length {len(p)} > max_model_len {max_len}")
    try:
        vectors = await engine.embed(prompts, lora_id, lora_name)
    except ValueError as e:  # over the embed batch-token limit
        return _error(400, str(e))
    return web.json_response({"embeddings": vectors.tolist()})


async def handle_grpc_generate(request: web.Request) -> web.StreamResponse:
    """vLLM gRPC Generate, JSON-transcoded: token-in / token-out.

    The EPP's `vllmgrpc-parser` routes these (reference
    request-handling.md:50-86); the engine surface never detokenizes —
    clients own the tokenizer. Streamed form emits SSE frames of
    {"token_ids": [...]}, final frame carries finish_reason + usage.
    """
    engine = request.app[ENGINE_KEY]
    max_len = request.app[MAXLEN_KEY]
    model = request.app[MODEL_KEY]
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error(400, f"invalid JSON: {e}")
    if not isinstance(body, dict):
        return _error(400, "request body must be a JSON object")
    ids = body.get("prompt_token_ids") or body.get("token_ids") or []
    if not isinstance(ids, list) or not all(isinstance(t, int) for t in ids):
        return _error(400, "prompt_token_ids must be a list of ints")
    if not ids:
        return _error(400, "empty prompt_token_ids")
    if len(ids) >= max_len:
        return _error(400, f"prompt length {len(ids)} >= max_model_len {max_len}")
    sp = body.get("sampling_params") or {}
    if not isinstance(sp, dict):
        return _error(400, "sampling_params must be an object")
    budget = max_len - len(ids)
    eos = getattr(request.app[TOK_KEY], "eos_token_id", None)
    try:
        stops = [int(t) for t in (sp.get("stop_token_ids") or [])]
        if eos is not None and not sp.get("ignore_eos", False):
            stops.append(int(eos))
        req_max = sp.get("max_tokens")
        max_tokens = budget if req_max is None else min(int(req_max), budget)
        if max_tokens < 0:
            return _error(400, "max_tokens must be >= 0")
        seed = sp.get("seed")
        sampling = SamplingParams(
            max_tokens=max_tokens,
            temperature=float(sp.get("temperature", 1.0)),
            top_k=int(sp.get("top_k", 0) or 0),
            top_p=float(sp.get("top_p", 1.0)),
            stop_token_ids=tuple(stops),
            ignore_eos=bool(sp.get("ignore_eos", False)),
            seed=None if seed is None else int(seed),
        )
        priority = int(sp.get("priority", 0) or 0)
    except (TypeError, ValueError) as e:
        return _error(400, f"invalid sampling_params: {e}")
    rid = request.headers.get("x-request-id") or P.request_id("grpcgen")
    kvp = body.get("kv_transfer_params")
    deadline_s = _request_deadline_s(request)
    try:
        lora_id, lora_name = _resolve_lora(request, str(body.get("model") or ""))
    except UnknownModelError as e:
        return _error(404, f"model {e.args[0]!r} not found")
    resume_ids = body.get("resume_token_ids") or []
    if resume_ids:
        reject = _validate_resume(resume_ids, sampling.max_tokens)
        if reject is not None:
            engine.stats.stream_resume_failures_total += 1
            return _error(400, reject)
        fin = _resume_finished(len(ids), resume_ids, sampling, max_len)
        if fin is not None:
            usage = P.usage_dict(len(ids), len(resume_ids))
            if body.get("stream", False):
                resp = web.StreamResponse(
                    headers={
                        "Content-Type": "text/event-stream",
                        "Cache-Control": "no-cache",
                        "x-request-id": rid,
                    }
                )
                await resp.prepare(request)
                await resp.write(_sse({"finish_reason": fin, "usage": usage}))
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
                return resp
            return web.json_response(
                {"id": rid, "model": model, "token_ids": [],
                 "finish_reason": fin, "usage": usage,
                 "kv_transfer_params": None},
                headers={"x-request-id": rid},
            )
        ids = ids + resume_ids
    n_resume = len(resume_ids)

    if body.get("stream", False):
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "x-request-id": rid,
            }
        )
        await resp.prepare(request)
        final = None
        try:
            async for out in engine.generate(rid, ids, sampling, priority, kvp,
                                             lora_id, lora_name, deadline_s,
                                             n_resume):
                final = out
                if out.new_token_ids:
                    await resp.write(_sse({"token_ids": list(out.new_token_ids)}))
                    # Same mid-stream kill site as the OpenAI surface.
                    if faults.fires("serve.stream.cut", rid):
                        engine.abort(rid)
                        if request.transport is not None:
                            request.transport.close()
                        return resp
        except (RequestFailed, EngineError) as e:
            await resp.write(_sse(P.error_body(str(e), code=_error_status(e))))
            await resp.write(b"data: [DONE]\n\n")
            return resp
        except (asyncio.CancelledError, ConnectionResetError):
            engine.abort(rid)
            raise
        await resp.write(
            _sse(
                {
                    "finish_reason": (
                        final.finish_reason.value
                        if final is not None and final.finish_reason
                        else None
                    ),
                    "usage": P.usage_dict(
                        len(ids) - n_resume,
                        final.num_output_tokens if final else n_resume,
                        final.num_cached_tokens if final else 0,
                    ),
                }
            )
        )
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    out_ids: list[int] = []
    final = None
    try:
        async for out in engine.generate(rid, ids, sampling, priority, kvp,
                                         lora_id, lora_name, deadline_s,
                                         n_resume):
            final = out
            out_ids.extend(out.new_token_ids)
    except RequestFailed as e:
        return _error(400, str(e))
    except DeadlineExceeded as e:
        return web.json_response(
            P.error_body(str(e), etype="timeout_error", code=504), status=504
        )
    except EngineError as e:
        return web.json_response(
            P.error_body(str(e), etype="internal_error", code=500), status=500
        )
    return web.json_response(
        {
            "id": rid,
            "model": model,
            "token_ids": out_ids,
            "finish_reason": (
                final.finish_reason.value
                if final is not None and final.finish_reason
                else None
            ),
            "usage": P.usage_dict(
                len(ids) - n_resume,
                final.num_output_tokens if final else n_resume,
                final.num_cached_tokens if final else 0,
            ),
            "kv_transfer_params": final.kv_transfer_params if final else None,
        },
        headers={"x-request-id": rid},
    )


# --------------------------------------------------------------------- #
# IRO engine-coordination surface (proposals/inference-resilience-operator.md:
# pause/resume/drain called by the resilience operator's EngineAdapter
# around infrastructure recovery actions).
#
# Auth: pause halts serving, so these must not be client-callable. With
# LLMD_ADMIN_TOKEN set, requests need `x-admin-token` (or Bearer) to
# match; without it, only loopback peers are accepted (the IRO runs on
# the same host in no-K8s mode; on K8s, mount a token).


def _admin_denied(request: web.Request) -> web.Response | None:
    token = os.environ.get("LLMD_ADMIN_TOKEN", "")
    if token.startswith("REPLACE-ME"):
        # The committed recipe placeholder is public knowledge — treating
        # it as a valid credential would be worse than no token at all.
        return _error(403, "placeholder admin token; set a real secret")
    if token:
        given = request.headers.get("x-admin-token", "")
        auth = request.headers.get("authorization", "")
        if auth.lower().startswith("bearer "):
            given = given or auth[7:]
        if hmac.compare_digest(given, token):
            return None
        return _error(403, "admin token required")
    peer = request.transport.get_extra_info("peername") if request.transport else None
    host = peer[0] if isinstance(peer, (tuple, list)) and peer else ""
    if host in ("127.0.0.1", "::1", "::ffff:127.0.0.1"):
        return None
    return _error(403, "admin surface is loopback-only without LLMD_ADMIN_TOKEN")


async def handle_admin_pause(request: web.Request) -> web.Response:
    denied = _admin_denied(request)
    if denied is not None:
        return denied
    request.app[ENGINE_KEY].pause()
    return web.json_response({"paused": True})


async def handle_admin_resume(request: web.Request) -> web.Response:
    denied = _admin_denied(request)
    if denied is not None:
        return denied
    request.app[ENGINE_KEY].resume()
    return web.json_response({"paused": False})


async def handle_admin_drain(request: web.Request) -> web.Response:
    denied = _admin_denied(request)
    if denied is not None:
        return denied
    try:
        timeout_s = float(request.query.get("timeout", 60.0))
    except ValueError:
        return _error(400, "timeout must be a number")
    drained = await request.app[ENGINE_KEY].drain(timeout_s)
    return web.json_response({"drained": drained}, status=200 if drained else 504)


async def handle_admin_status(request: web.Request) -> web.Response:
    denied = _admin_denied(request)
    if denied is not None:
        return denied
    engine = request.app[ENGINE_KEY]
    stats = engine.stats
    return web.json_response(
        {
            "paused": engine.paused,
            "running": stats.num_running,
            "waiting": stats.num_waiting,
        }
    )


# --------------------------------------------------------------------- #
# Runtime adapter load/unload (the vLLM dynamic-LoRA contract;
# docs/architecture/multi-tenant-lora.md). Registration is unbounded —
# the paged pool bounds HBM residency, not the servable set. Loads are
# lockstep-broadcast slot installs, so multi-host replicas flip
# atomically; a failed fetch degrades to a counted 4xx
# (lora_load_failures_total), never a wedged batch.

_LORA_NAME_RE = re.compile(r"[A-Za-z0-9._:/-]+")


async def handle_load_lora_adapter(request: web.Request) -> web.Response:
    engine: AsyncEngine = request.app[ENGINE_KEY]
    if _adapter_registry(request) is None:
        return _error(
            400,
            "dynamic adapter serving is disabled (start the server with "
            "--lora-pool-slots)",
        )
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error(400, f"invalid JSON: {e}")
    if not isinstance(body, dict):
        return _error(400, "request body must be a JSON object")
    name = str(body.get("lora_name") or "")
    source = str(
        body.get("lora_path") or body.get("lora_url") or body.get("source")
        or ""
    )
    if not name or not _LORA_NAME_RE.fullmatch(name):
        # Names interpolate into Prometheus label values and model ids.
        return _error(
            400, f"invalid lora_name {name!r}: use letters, digits, ._:/-"
        )
    if name == request.app[MODEL_KEY]:
        return _error(400, f"lora_name {name!r} shadows the base model id")
    if not source:
        return _error(
            400, "lora_path (or lora_url / source) is required"
        )
    from llmd_tpu.lora import AdapterFetchError

    try:
        await engine.load_adapter(name, source)
    except (AdapterFetchError, ValueError) as e:
        # Fetch/decode/duplicate failures are CLIENT errors: counted
        # (lora_load_failures_total covers the fetch leg) and surfaced;
        # base-model rows and resident adapters are untouched.
        return _error(400, str(e))
    except RuntimeError as e:  # dynamic serving disabled
        return _error(400, str(e))
    return web.json_response(
        {
            "status": "ok",
            "message": f"Success: LoRA adapter '{name}' added successfully",
            "lora_name": name,
        }
    )


async def handle_unload_lora_adapter(request: web.Request) -> web.Response:
    engine: AsyncEngine = request.app[ENGINE_KEY]
    if _adapter_registry(request) is None:
        return _error(400, "dynamic adapter serving is disabled")
    try:
        body = await request.json()
    except json.JSONDecodeError as e:
        return _error(400, f"invalid JSON: {e}")
    if not isinstance(body, dict):
        return _error(400, "request body must be a JSON object")
    name = str(body.get("lora_name") or "")
    if not name:
        return _error(400, "lora_name is required")
    try:
        await engine.unload_adapter(name)
    except KeyError as e:
        return _error(404, str(e.args[0]) if e.args else name)
    except RuntimeError as e:
        # In-flight rows reference the adapter: conflict, retry later.
        return _error(409, str(e))
    return web.json_response(
        {
            "status": "ok",
            "message": f"Success: LoRA adapter '{name}' removed successfully",
            "lora_name": name,
        }
    )


async def handle_completions(request: web.Request) -> web.StreamResponse:
    return await _handle_generate(request, chat=False)


async def handle_chat(request: web.Request) -> web.StreamResponse:
    return await _handle_generate(request, chat=True)


# --------------------------------------------------------------------- #


def _responses_routes() -> list:
    from llmd_tpu.serve.responses import make_handlers

    return make_handlers(ENGINE_KEY, TOK_KEY, MODEL_KEY, MAXLEN_KEY)


def build_app(
    engine: AsyncEngine,
    tokenizer,
    model_name: str,
    max_model_len: int,
    extra_routes: list | None = None,
    lora_adapters: dict[str, int] | None = None,
) -> web.Application:
    app = web.Application()
    app[ENGINE_KEY] = engine
    app[TOK_KEY] = tokenizer
    app[MODEL_KEY] = model_name
    app[MAXLEN_KEY] = max_model_len
    app[LORA_KEY] = dict(lora_adapters or {})
    from llmd_tpu.serve.responses import STORE_KEY, ResponsesStore

    app[STORE_KEY] = ResponsesStore()
    app.add_routes(
        [
            web.get("/health", handle_health),
            web.get("/ready", handle_ready),
            web.get("/v1/models", handle_models),
            web.get("/metrics", handle_metrics),
            web.post("/tokenize", handle_tokenize),
            web.post("/v1/completions", handle_completions),
            web.post("/v1/embeddings", handle_embeddings),
            web.post("/vllm.Generation/Generate", handle_grpc_generate),
            web.post("/vllm.Generation/Embed", handle_grpc_embed),
            web.post("/v1/chat/completions", handle_chat),
            web.post("/v1/completions/render", handle_completions_render),
            web.post("/v1/chat/completions/render", handle_chat_render),
            web.post("/v1/cache/probe", handle_cache_probe),
            web.post("/v1/load_lora_adapter", handle_load_lora_adapter),
            web.post("/v1/unload_lora_adapter", handle_unload_lora_adapter),
            *_responses_routes(),
            web.post("/admin/pause", handle_admin_pause),
            web.post("/admin/resume", handle_admin_resume),
            web.post("/admin/drain", handle_admin_drain),
            web.get("/admin/status", handle_admin_status),
        ]
    )
    if extra_routes:
        app.add_routes(extra_routes)

    async def _start_engine(app: web.Application):
        engine.start(asyncio.get_event_loop())
        # EC-connector pulls (E-disaggregation consumer side).
        app[MM_SESSION_KEY] = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=60, sock_connect=5)
        )
        yield
        await app[MM_SESSION_KEY].close()
        engine.stop()

    app.cleanup_ctx.append(_start_engine)
    return app
