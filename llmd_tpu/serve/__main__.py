"""`python -m llmd_tpu.serve` — the model-server entry point.

Flag names mirror the vLLM flags the reference's deployment patches set
(e.g. guides/pd-disaggregation/modelserver/tpu/v6/vllm/patch-decode.yaml:
--tensor-parallel-size, --max-model-len, --block-size,
--max-num-batched-tokens, --kv-transfer-config).
"""

from __future__ import annotations

import argparse
import json
import logging


def parse_lora_adapters(spec: str | None) -> dict[str, tuple[int, str | None]]:
    """'a,b=/path' -> {'a': (1, None), 'b': (2, '/path')}.

    Deduplicated, order-preserving. A bare name reserves an empty slot
    (identity adapter until weights install); `name=dir` loads an HF PEFT
    adapter directory into the slot at startup. Names are restricted to
    Prometheus-label-safe characters: they are interpolated into the
    lora_requests_info label values, and a quote or backslash would
    corrupt the exposition page."""
    if not spec:
        return {}
    import re

    entries: dict[str, str | None] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, path = part.partition("=")
        name = name.strip()
        if not re.fullmatch(r"[A-Za-z0-9._:/-]+", name):
            raise ValueError(
                f"invalid adapter name {name!r}: use letters, digits, ._:/-"
            )
        path = path.strip() or None
        if name in entries:
            if entries[name] != path:
                raise ValueError(
                    f"adapter {name!r} listed twice with conflicting paths "
                    f"({entries[name]!r} vs {path!r})"
                )
            continue
        entries[name] = path
    return {
        name: (i + 1, path) for i, (name, path) in enumerate(entries.items())
    }


def make_engine_config(args, lora_adapters=None):
    from llmd_tpu.config import (
        CacheConfig,
        EngineConfig,
        OffloadConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from llmd_tpu.models.loader import config_from_hf, is_model_dir
    from llmd_tpu.models.registry import get_model_config

    def _multihost_world() -> bool:
        import jax

        return jax.process_count() > 1

    overrides = {}
    if args.max_model_len is not None:
        overrides["max_model_len"] = args.max_model_len
    if args.quantization:
        overrides["quantization"] = args.quantization
    if getattr(args, "lora_pool_slots", 0):
        # Paged adapter pool (docs/architecture/multi-tenant-lora.md):
        # the slot count bounds HBM residency only; the servable set is
        # the runtime registry (/v1/load_lora_adapter), seeded from any
        # --lora-adapters entries at startup.
        overrides["num_lora_adapters"] = args.lora_pool_slots
        overrides["lora_rank"] = args.lora_rank
        overrides["lora_dynamic"] = True
    elif lora_adapters:
        overrides["num_lora_adapters"] = len(lora_adapters)
        overrides["lora_rank"] = args.lora_rank
    weights_path = args.weights_path
    tokenizer_path = args.tokenizer
    if is_model_dir(args.model):
        # --model <hf-dir>: architecture, weights, and tokenizer all come
        # from the checkpoint directory (vLLM-style); max_model_len
        # defaults to the checkpoint's max_position_embeddings.
        model = config_from_hf(args.model, **overrides)
        weights_path = weights_path or args.model
        tokenizer_path = tokenizer_path or args.model
    else:
        overrides.setdefault("max_model_len", 8192)
        model = get_model_config(args.model, **overrides)
    kv_cfg = json.loads(args.kv_transfer_config) if args.kv_transfer_config else {}
    return EngineConfig(
        model=model,
        cache=CacheConfig(
            page_size=args.block_size,
            num_blocks=args.num_gpu_blocks_override or 2048,
            dtype=args.kv_cache_dtype,
            enable_prefix_caching=not args.no_enable_prefix_caching,
            swa_ring=args.kv_swa_ring,
        ),
        scheduler=SchedulerConfig(
            max_num_seqs=args.max_num_seqs,
            max_num_batched_tokens=args.max_num_batched_tokens,
            decode_window=args.decode_window,
            async_scheduling=args.async_scheduling,
            speculative_ngram=args.speculative_ngram,
            spec_ngram_k=args.spec_ngram_k,
            spec_ngram_min_match=args.spec_ngram_min_match,
            spec_verify_window=args.spec_verify_window,
            unified_step=args.unified_step,
            ragged_qlens=args.ragged_qlens,
            batch_backfill=args.batch_backfill,
            batch_max_seqs=args.batch_max_seqs,
            batch_kv_watermark=args.batch_kv_watermark,
        ),
        parallel=ParallelConfig(
            tensor_parallel_size=args.tensor_parallel_size,
            # Single-process: DP across processes is the supervisor's job,
            # so the in-process mesh is TP-only. In a jax.distributed
            # world (mode B) ONE engine owns the global (dp, tp) mesh and
            # --data-parallel-size is a real mesh axis.
            data_parallel_size=(
                args.data_parallel_size if _multihost_world() else 1
            ),
            moe_backend=args.moe_backend,
            enable_dbo=args.enable_dbo,
            cp_prefill=(
                args.cp_prefill if _multihost_world() else 1
            ),
            cp_prefill_min_tokens=args.cp_prefill_min_tokens,
        ),
        seed=args.seed,
        weights_path=weights_path,
        tokenizer_path=tokenizer_path,
        kv_role=kv_cfg.get("kv_role"),
        kv_side_channel_port=int(kv_cfg.get("side_channel_port", 9600)),
        kv_transfer_port=int(kv_cfg.get("transfer_port", 9100)),
        kv_transfer_dtype=str(kv_cfg.get("transfer_dtype", "auto")),
        kv_stream_groups=int(kv_cfg.get("stream_groups", 4)),
        kv_events_endpoint=args.kv_events_endpoint,
        offload=(
            OffloadConfig(
                cpu_chunks=args.kv_offload_chunks,
                fs_dir=args.kv_offload_fs_dir,
                store_master_url=args.kv_store_master_url,
                store_segment_bytes=args.kv_store_segment_bytes,
                store_data_port=args.kv_store_data_port,
                publish_policy=args.kv_publish_policy,
                publish_min_hits=args.kv_publish_min_hits,
                decode_paging=args.kv_decode_paging,
                pager_horizon_tokens=args.kv_pager_horizon_tokens,
            )
            if args.kv_offload_chunks
            else None
        ),
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("llmd-tpu serve")
    p.add_argument("--model", default="tiny-llama")
    p.add_argument("--served-model-name", default=None)
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--weights-path", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument(
        "--max-model-len", type=int, default=None,
        help="default: checkpoint max_position_embeddings (dir models) or 8192",
    )
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-gpu-blocks-override", type=int, default=None)
    p.add_argument("--kv-cache-dtype", default="bfloat16")
    p.add_argument(
        "--enable-dbo", action="store_true",
        help="dual-batch overlap: overlap the EP all-to-all of one half-"
        "batch with the other half's attention (wide-EP decode; the vLLM "
        "--enable-dbo role)",
    )
    p.add_argument(
        "--quantization", default=None, choices=["int8"],
        help="weight quantization (int8 W8A8; the vLLM --quantization "
        "role — the reference serves its headline path FP8)",
    )
    p.add_argument("--no-enable-prefix-caching", action="store_true")
    p.add_argument(
        "--kv-swa-ring", action="store_true",
        help="ring-buffer KV pages for sliding-window layers (the "
        "reference's hybrid KV cache manager role, pd patch-decode.yaml "
        "--no-disable-hybrid-kv-cache-manager): sliding layers hold a "
        "fixed per-sequence page ring instead of full-length pages — "
        "~2x KV capacity on gpt-oss-class models. Prefix caching "
        "becomes HYBRID: full-attention pages stay reusable, and a "
        "repeated prefix hits when its retained sliding-window section "
        "(CacheConfig.swa_section_cache) can seed the fresh ring",
    )
    p.add_argument("--max-num-seqs", type=int, default=64)
    p.add_argument("--max-num-batched-tokens", type=int, default=2048)
    p.add_argument("--decode-window", type=int, default=1)
    p.add_argument(
        "--async-scheduling", action="store_true",
        help="overlap host scheduling with device execution (vLLM v1 "
             "--async-scheduling role): the next step is scheduled and "
             "staged while the current one runs; tokens stream one step "
             "late. Auto-disabled for multi-host lockstep engines and "
             "P/D producers (docs/architecture/async-scheduling.md)",
    )
    p.add_argument(
        "--speculative-ngram", action="store_true",
        help="model-free speculative decoding: n-gram prompt-lookup "
             "drafting verified in one [B, 1+k] pass. Token streams stay "
             "byte-identical to the non-speculative engine for greedy "
             "and seeded sampling "
             "(docs/architecture/speculative-decoding.md)",
    )
    p.add_argument(
        "--spec-ngram-k", type=int, default=4,
        help="max draft tokens per sequence per step (the k in the "
             "[B, 1+k] verify shape family)",
    )
    p.add_argument(
        "--spec-ngram-min-match", type=int, default=2,
        help="minimum trailing n-gram length that must recur in the "
             "sequence's own history before a draft is proposed",
    )
    p.add_argument(
        "--spec-verify-window", type=int, default=0,
        help="max verify iterations fused into one dispatch when "
             "--speculative-ngram composes with fused decode windows: "
             "accept/reject runs ON DEVICE and the host pays one "
             "round-trip per window. 0 (default) inherits "
             "--decode-window; 1 pins one-shot verify steps "
             "(docs/architecture/speculative-decoding.md)",
    )
    p.add_argument(
        "--unified-step", action=argparse.BooleanOptionalAction, default=True,
        help="pack each window=1 engine step (prefill chunks + decode "
             "rows + one-shot verify rows) into ONE ragged device "
             "program with one coalesced readback; --no-unified-step "
             "restores the split per-family dispatch paths. Streams are "
             "byte-identical either way for greedy and seeded sampling "
             "(docs/architecture/async-scheduling.md)",
    )
    p.add_argument(
        "--ragged-qlens", action=argparse.BooleanOptionalAction, default=True,
        help="genuinely ragged flattened-token unified step (cu_q_lens): "
             "the window=1 step runs over the packed token stream — a "
             "decode row costs 1 token, a verify row 1 + its own draft "
             "length (per-row adaptive verify depth) — instead of "
             "padding every row to the bucketed [B, Q] sub-row width; "
             "--no-ragged-qlens restores the bucketed unified program. "
             "Greedy and seeded streams are byte-identical either way "
             "(docs/architecture/async-scheduling.md)",
    )
    p.add_argument(
        "--batch-backfill", action=argparse.BooleanOptionalAction,
        default=True,
        help="batch serving tier: requests at or below "
             "PriorityClass.BATCH (the x-llmd-priority: batch header) "
             "ride the SAME continuous batch but only backfill "
             "token-budget/page headroom interactive rows left unused, "
             "never displace an interactive admission, and are "
             "recompute-preempted the moment interactive load returns; "
             "interactive streams stay byte-identical batch-on vs "
             "batch-off. --no-batch-backfill degrades batch-priority "
             "rows to plain low-priority rows "
             "(docs/architecture/batch-processing.md)",
    )
    p.add_argument(
        "--batch-max-seqs", type=int, default=0,
        help="cap on concurrently RUNNING batch-band rows (0 = no "
             "dedicated cap: batch may fill whatever --max-num-seqs "
             "slots interactive left idle)",
    )
    p.add_argument(
        "--batch-kv-watermark", type=float, default=0.85,
        help="admit new batch-band rows only while main-pool KV "
             "utilization is at or below this fraction, so backfill "
             "never pushes the pool into the preemption regime "
             "interactive rows pay for",
    )
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--data-parallel-size", type=int, default=1)
    p.add_argument(
        "--data-parallel-rank", type=int, default=0,
        help="this process's global DP rank (set by the DP supervisor)",
    )
    p.add_argument(
        "--moe-backend", default="grouped", choices=["grouped", "dense", "ep"],
        help="MoE path: grouped GEMM (DeepGEMM role, default), dense "
             "combine (oracle), or shard_map all-to-all (wide-EP)",
    )
    p.add_argument(
        "--cp-prefill", type=int, default=1,
        help="context-parallel ring prefill degree (long-context.md): "
        "shard long prompts' chunks over the dp mesh axis and compute "
        "attention as a ppermute ring; must equal --data-parallel-size "
        "(1 disables; forced to 1 outside a jax.distributed world, "
        "like DP itself)",
    )
    p.add_argument(
        "--cp-prefill-min-tokens", type=int, default=512,
        help="smallest chunk that rides the ring — shorter chunks are "
        "dispatch-bound and take the monolithic arm",
    )
    p.add_argument(
        "--kv-decode-paging", action="store_true",
        help="decode-time KV pager (long-context.md): spill live-"
        "sequence pages below the attention window to the offload tier "
        "and stream them back ahead of the window; requires "
        "--kv-offload-chunks and a sliding-window model",
    )
    p.add_argument(
        "--kv-pager-horizon-tokens", type=int, default=256,
        help="prefetch horizon the pager keeps resident beyond the "
        "attention window",
    )
    p.add_argument(
        "--platform", default=None,
        help="force a JAX platform (e.g. cpu for the sim backend)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kv-transfer-config", default=None, help="JSON, vLLM-style")
    p.add_argument("--kv-events-endpoint", default=None, help="ZMQ pub endpoint")
    p.add_argument(
        "--advertised-address", default=None,
        help="host:port this pod is reachable at (pod IP in-cluster); used "
        "to attribute KV events and kv-transfer params. Defaults to "
        "host:port, which is wrong when binding 0.0.0.0.",
    )
    p.add_argument(
        "--kv-offload-chunks", type=int, default=0,
        help="host-DRAM KV page budget (0 disables tiered offload; the "
        "reference TPU recipe uses 25000, tiered-prefix-cache/README.md:41-48)",
    )
    p.add_argument("--kv-offload-fs-dir", default=None, help="FS spill tier dir")
    p.add_argument(
        "--kv-store-master-url", default=None,
        help="cross-slice KV store master URL (Mooncake-Store role); "
        "enables the shared tier behind host-DRAM/FS",
    )
    p.add_argument(
        "--kv-store-segment-bytes", type=int, default=8 << 30,
        help="DRAM this host contributes to the shared pool",
    )
    p.add_argument("--kv-store-data-port", type=int, default=9200)
    p.add_argument(
        "--kv-publish-policy", default="save",
        choices=["save", "evict-hot", "off"],
        help="federation publish policy (kv-federation.md): save = "
        "publish every host-tier save (eager); evict-hot = publish only "
        "device-evicted pages used >= --kv-publish-min-hits times; off = "
        "read-only store participation",
    )
    p.add_argument(
        "--kv-publish-min-hits", type=int, default=2,
        help="hotness gate for --kv-publish-policy evict-hot: distinct "
        "uses of a page's hash chain before eviction earns a store copy",
    )
    p.add_argument("--skip-warmup", action="store_true")
    p.add_argument(
        "--lora-adapters", default=None,
        help="comma-separated adapter names to serve (each becomes a model "
        "id; random-init weights of --lora-rank until checkpoint loading)",
    )
    p.add_argument("--lora-rank", type=int, default=16)
    p.add_argument(
        "--lora-pool-slots", type=int, default=0,
        help="paged adapter pool: N HBM rank-(--lora-rank) slots over an "
        "UNBOUNDED runtime adapter registry "
        "(/v1/load_lora_adapter + /v1/unload_lora_adapter, the vLLM "
        "dynamic-LoRA contract) — idle residents are LRU-evicted for "
        "incoming tenants, slots referenced by in-flight rows are "
        "pinned, and a request naming a cold adapter parks in a "
        "loading queue instead of stalling the batch. 0 (default) "
        "keeps the fixed build-time --lora-adapters slot mapping "
        "(docs/architecture/multi-tenant-lora.md)",
    )
    p.add_argument(
        "--otlp-traces-endpoint", default=None,
        help="OTLP/HTTP collector base URL (e.g. http://otel:4318)",
    )
    p.add_argument("--trace-file", default=None, help="JSONL span log path")
    p.add_argument("--trace-sample-ratio", type=float, default=0.1)
    # Multi-host: join a jax.distributed world (reference LWS leader/worker
    # shape, --data-parallel-address $LWS_LEADER_ADDRESS; here the env
    # contract LLMD_COORDINATOR/LWS_LEADER_ADDRESS + LWS_GROUP_SIZE +
    # LWS_WORKER_INDEX also works without flags).
    p.add_argument(
        "--distributed-coordinator", default=None,
        help="host:port of the jax.distributed coordinator (LWS leader)",
    )
    p.add_argument("--distributed-num-processes", type=int, default=None)
    p.add_argument("--distributed-process-id", type=int, default=None)
    return p


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)

    if args.platform:
        # Must run before any jax import; env alone is overridden by site
        # customization on some hosts, so set the config too.
        import jax

        jax.config.update("jax_platforms", args.platform)

    from aiohttp import web

    from llmd_tpu.engine import LLMEngine
    from llmd_tpu.parallel import distributed as dist
    from llmd_tpu.serve.api import build_app
    from llmd_tpu.serve.async_engine import AsyncEngine
    from llmd_tpu.serve.tokenizer import load_tokenizer

    multihost = dist.maybe_initialize(
        coordinator=args.distributed_coordinator,
        num_processes=args.distributed_num_processes,
        process_id=args.distributed_process_id,
    )

    adapter_specs = parse_lora_adapters(args.lora_adapters) or None
    lora_adapters = (
        {name: slot for name, (slot, _) in adapter_specs.items()}
        if adapter_specs
        else None
    )
    config = make_engine_config(args, lora_adapters)
    advertised = args.advertised_address or f"{args.host}:{args.port}"
    if advertised.startswith("0.0.0.0"):
        logging.warning(
            "advertised address %s binds all interfaces; set "
            "--advertised-address to the pod IP or KV-event attribution "
            "and P/D transfers will not resolve", advertised,
        )
    config.kv_host = advertised.rsplit(":", 1)[0]
    event_sink = None
    if config.kv_events_endpoint:
        from llmd_tpu.events.publisher import ZMQEventSink

        event_sink = ZMQEventSink(
            endpoint=config.kv_events_endpoint,
            pod=advertised,
        )
    if args.otlp_traces_endpoint or args.trace_file:
        from llmd_tpu.obs.tracing import configure_tracing

        configure_tracing(
            "llmd-engine",
            otlp_endpoint=args.otlp_traces_endpoint,
            trace_file=args.trace_file,
            sample_ratio=args.trace_sample_ratio,
        )
    engine = LLMEngine(config, event_sink=event_sink)
    if multihost and not dist.is_leader():
        # Worker rank of a multi-host deployment: no HTTP frontend — mirror
        # the leader's device dispatches until it broadcasts shutdown (the
        # LWS worker role; the leader serves the API for the whole group).
        import jax

        logging.info(
            "multi-host worker %d/%d: entering follower loop",
            jax.process_index(), jax.process_count(),
        )
        engine.runner.follower_loop()
        return
    if args.lora_pool_slots:
        # Dynamic pool: --lora-adapters entries seed the runtime
        # registry (bare names register identity adapters until weights
        # load through the API); names resolve engine-side thereafter.
        for name, (_slot, path) in (adapter_specs or {}).items():
            if path:
                engine.load_adapter(name, path)
            else:
                engine.load_adapter(name, weights={})
            logging.info("registered LoRA adapter %r (source=%s)",
                         name, path or "<identity>")
        lora_adapters = None
    else:
        for name, (slot, path) in (adapter_specs or {}).items():
            if path:
                from llmd_tpu.models.loader import load_lora_adapter

                engine.set_lora_weights(
                    slot, load_lora_adapter(config.model, path)
                )
                logging.info("loaded LoRA adapter %r from %s into slot %d",
                             name, path, slot)
    if not args.skip_warmup:
        n = engine.runner.warmup()
        logging.info("warmup compiled %d programs", n)
    tokenizer = load_tokenizer(config.tokenizer_path)
    app = build_app(
        AsyncEngine(engine),
        tokenizer,
        args.served_model_name or args.model,
        config.model.max_model_len,
        lora_adapters=lora_adapters,
    )

    async def _close_engine(app):
        # Unregisters the KV-store segment (peers stop being routed to a
        # dead address) and closes the transfer connector.
        engine.close()

    app.on_cleanup.append(_close_engine)
    web.run_app(app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
