"""OpenAI-compatible HTTP serving layer for the TPU engine.

Plays the role of vLLM's api_server in the reference stack: the model-server
HTTP surface the router targets (reference
docs/architecture/core/model-servers.md:38-100 — OpenAI API + Prometheus
metrics protocol + /health).
"""

from llmd_tpu.serve.async_engine import AsyncEngine
from llmd_tpu.serve.tokenizer import ByteTokenizer, load_tokenizer

__all__ = ["AsyncEngine", "ByteTokenizer", "load_tokenizer"]
