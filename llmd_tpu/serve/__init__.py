"""OpenAI-compatible HTTP serving layer for the TPU engine.

Plays the role of vLLM's api_server in the reference stack: the model-server
HTTP surface the router targets (reference
docs/architecture/core/model-servers.md:38-100 — OpenAI API + Prometheus
metrics protocol + /health).
"""

# Lazy (PEP 562): AsyncEngine pulls the whole jax engine at import.
# Accelerator-free consumers — the EPP data layer and the fleet
# simulator's control-plane imports reach llmd_tpu.serve.metrics
# (parse_prometheus, pure stdlib) — must not pay for (or require) jax
# just to touch the package.

__all__ = ["AsyncEngine", "ByteTokenizer", "load_tokenizer"]


def __getattr__(name):
    if name == "AsyncEngine":
        from llmd_tpu.serve.async_engine import AsyncEngine

        return AsyncEngine
    if name in ("ByteTokenizer", "load_tokenizer"):
        from llmd_tpu.serve import tokenizer

        return getattr(tokenizer, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
