"""OpenAI-compatible API schema (the engine's HTTP contract).

Mirrors the API surface the reference's router parses (`openai-parser`
handles /chat/completions, /completions, ... — reference
docs/architecture/core/router/epp/request-handling.md:50-86) plus the llm-d
extensions that ride on it: `kv_transfer_params` / `do_remote_decode` for
P/D disaggregation (disaggregation/README.md:104-131) and request priority.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Union

import pydantic

from llmd_tpu.engine.request import SamplingParams


class _Base(pydantic.BaseModel):
    model_config = pydantic.ConfigDict(extra="allow")


class CompletionRequest(_Base):
    model: str = ""
    prompt: Union[str, list[str], list[int], list[list[int]]] = ""
    max_tokens: int = 16
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    n: int = 1
    stream: bool = False
    stop: Union[str, list[str], None] = None
    seed: int | None = None
    logprobs: int | None = None
    # --- llm-d / vLLM extensions ---
    ignore_eos: bool = False
    priority: int = 0
    stop_token_ids: list[int] | None = None
    kv_transfer_params: dict[str, Any] | None = None
    # Mid-stream failover resume (docs/architecture/fault-tolerance.md):
    # output tokens a previous replica already delivered for this exact
    # request. The engine admits them as committed prefix and continues
    # generation at the next output position; the response carries ONLY
    # the continuation.
    resume_token_ids: list[int] | None = None


class ChatMessage(_Base):
    role: str = "user"
    content: Union[str, list[dict], None] = ""


class ChatCompletionRequest(_Base):
    model: str = ""
    messages: list[ChatMessage] = pydantic.Field(default_factory=list)
    max_tokens: int | None = None
    max_completion_tokens: int | None = None
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    n: int = 1
    stream: bool = False
    stop: Union[str, list[str], None] = None
    seed: int | None = None
    logprobs: bool = False
    # --- llm-d / vLLM extensions ---
    ignore_eos: bool = False
    priority: int = 0
    stop_token_ids: list[int] | None = None
    kv_transfer_params: dict[str, Any] | None = None
    # Mid-stream failover resume: see CompletionRequest.resume_token_ids.
    resume_token_ids: list[int] | None = None


def stop_strings(stop: Union[str, list[str], None]) -> list[str]:
    if stop is None:
        return []
    if isinstance(stop, str):
        return [stop]
    return [s for s in stop if isinstance(s, str)]


def to_sampling(
    req: Union[CompletionRequest, ChatCompletionRequest],
    eos_token_id: int | None,
    max_tokens: int,
) -> SamplingParams:
    stops: list[int] = list(req.stop_token_ids or [])
    if eos_token_id is not None:
        stops.append(int(eos_token_id))
    return SamplingParams(
        max_tokens=max_tokens,
        temperature=req.temperature,
        top_k=req.top_k,
        top_p=req.top_p,
        stop_token_ids=tuple(stops),
        ignore_eos=req.ignore_eos,
        seed=req.seed,
        logprobs=bool(req.logprobs),
    )


def request_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def usage_dict(prompt_tokens: int, completion_tokens: int, cached: int = 0) -> dict:
    out = {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }
    if cached:
        out["prompt_tokens_details"] = {"cached_tokens": cached}
    return out


def completion_response(
    rid: str, model: str, text: str, finish_reason: str | None, usage: dict,
    kv_transfer_params: dict | None = None,
) -> dict:
    out = {
        "id": rid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {
                "index": 0,
                "text": text,
                "logprobs": None,
                "finish_reason": finish_reason,
            }
        ],
        "usage": usage,
    }
    if kv_transfer_params is not None:
        out["kv_transfer_params"] = kv_transfer_params
    return out


def chat_response(
    rid: str, model: str, text: str, finish_reason: str | None, usage: dict,
    kv_transfer_params: dict | None = None,
) -> dict:
    out = {
        "id": rid,
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish_reason,
            }
        ],
        "usage": usage,
    }
    if kv_transfer_params is not None:
        out["kv_transfer_params"] = kv_transfer_params
    return out


def completion_chunk(
    rid: str, model: str, text: str, finish_reason: str | None, index: int = 0
) -> dict:
    return {
        "id": rid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {"index": index, "text": text, "logprobs": None,
             "finish_reason": finish_reason}
        ],
    }


def chat_chunk(
    rid: str, model: str, delta: dict, finish_reason: str | None,
    index: int = 0,
) -> dict:
    return {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {"index": index, "delta": delta, "finish_reason": finish_reason}
        ],
    }


def error_body(message: str, etype: str = "invalid_request_error", code: int = 400) -> dict:
    return {"error": {"message": message, "type": etype, "code": code}}
