"""OpenAI Responses + Conversations API at the engine.

The EPP already parses /v1/responses and /v1/conversations bodies for
routing (reference openai-parser surface, request-handling.md:50-51;
llmd_tpu/epp/handler.py) — this module makes those paths SERVABLE at the
backend so a routed request never 404s.

Surface (the agentic subset):

  POST   /v1/responses                  create (stream or not; `store`,
                                        `previous_response_id`, and
                                        `conversation` chain turns)
  GET    /v1/responses/{id}             retrieve a stored response
  DELETE /v1/responses/{id}
  POST   /v1/conversations              create a conversation
  GET    /v1/conversations/{id}
  POST   /v1/conversations/{id}/items   append items
  GET    /v1/conversations/{id}/items

State is in-memory and LRU-bounded per engine (the reference's vLLM
backend keeps response state in-process the same way; durable storage is
the Batch gateway's job). Streaming emits the typed Responses SSE events
(response.created / response.output_text.delta / response.completed).
"""

from __future__ import annotations

import asyncio
import collections
import json
import time
import uuid

from aiohttp import web

from llmd_tpu.serve import protocol as P
from llmd_tpu.serve.async_engine import AsyncEngine, EngineError, RequestFailed

STORE_KEY = web.AppKey("responses_store", object)
MAX_STORED = 1024


class ResponsesStore:
    """LRU-bounded response + conversation state."""

    def __init__(self, max_items: int = MAX_STORED) -> None:
        self.responses: collections.OrderedDict[str, dict] = collections.OrderedDict()
        # cid -> {"items": [...], "created_at": int, "metadata": dict}
        self.conversations: collections.OrderedDict[str, dict] = collections.OrderedDict()
        self.max_items = max_items

    def put_response(self, resp: dict, history: list[dict]) -> None:
        self.responses[resp["id"]] = {"response": resp, "history": history}
        self.responses.move_to_end(resp["id"])
        while len(self.responses) > self.max_items:
            self.responses.popitem(last=False)

    def get_response(self, rid: str) -> dict | None:
        entry = self.responses.get(rid)
        if entry is not None:
            self.responses.move_to_end(rid)
        return entry

    def new_conversation(self, metadata: dict | None) -> dict:
        cid = f"conv_{uuid.uuid4().hex}"
        self.conversations[cid] = {
            "items": [],
            "created_at": int(time.time()),
            "metadata": metadata or {},
        }
        while len(self.conversations) > self.max_items:
            self.conversations.popitem(last=False)
        return self.conversation_object(cid)

    def conversation_object(self, cid: str) -> dict:
        entry = self.conversations[cid]
        return {
            "id": cid,
            "object": "conversation",
            "created_at": entry["created_at"],
            "metadata": entry["metadata"],
        }


def _input_to_messages(inp) -> list[dict]:
    """Responses `input` (string or item list) -> chat messages."""
    if isinstance(inp, str):
        return [{"role": "user", "content": inp}]
    msgs: list[dict] = []
    for item in inp or []:
        if not isinstance(item, dict):
            continue
        itype = item.get("type", "message")
        if itype != "message":
            continue  # tool calls etc.: not executable by a bare engine
        content = item.get("content")
        if isinstance(content, list):
            content = "".join(
                part.get("text", "")
                for part in content
                if isinstance(part, dict)
                and part.get("type") in ("input_text", "output_text", "text")
            )
        msgs.append({"role": item.get("role", "user"), "content": content or ""})
    return msgs


def _response_object(
    rid: str, model: str, text: str, usage: dict, status: str = "completed"
) -> dict:
    return {
        "id": rid,
        "object": "response",
        "created_at": int(time.time()),
        "status": status,
        "model": model,
        "output": [
            {
                "type": "message",
                "id": f"msg_{uuid.uuid4().hex}",
                "status": status,
                "role": "assistant",
                "content": [
                    {"type": "output_text", "text": text, "annotations": []}
                ],
            }
        ],
        "usage": usage,
    }


def _responses_usage(prompt_tokens: int, output_tokens: int) -> dict:
    return {
        "input_tokens": prompt_tokens,
        "output_tokens": output_tokens,
        "total_tokens": prompt_tokens + output_tokens,
    }


def _event(name: str, data: dict) -> bytes:
    return (
        b"event: " + name.encode()
        + b"\ndata: " + json.dumps(data, separators=(",", ":")).encode()
        + b"\n\n"
    )


def make_handlers(engine_key, tok_key, model_key, maxlen_key):
    """Route handlers bound to the api module's app keys."""

    def _err(status: int, message: str) -> web.Response:
        return web.json_response(P.error_body(message, code=status), status=status)

    async def create_response(request: web.Request) -> web.StreamResponse:
        engine: AsyncEngine = request.app[engine_key]
        tokenizer = request.app[tok_key]
        model = request.app[model_key]
        max_len = request.app[maxlen_key]
        store: ResponsesStore = request.app[STORE_KEY]
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return _err(400, f"invalid JSON: {e}")

        # ``context`` is the chainable conversation state (input/output
        # items only). ``instructions`` are per-request and NOT carried
        # over via previous_response_id (OpenAI Responses semantics): they
        # join the prompt below but never the stored history.
        context: list[dict] = []
        instructions = body.get("instructions")
        conv_id = body.get("conversation")
        if isinstance(conv_id, dict):
            conv_id = conv_id.get("id")
        prev = body.get("previous_response_id")
        if conv_id and prev:
            # Both sources would duplicate prior turns in the prompt;
            # OpenAI rejects the combination the same way.
            return _err(
                400,
                "previous_response_id and conversation are mutually exclusive",
            )
        if conv_id:
            conv = store.conversations.get(conv_id)
            if conv is None:
                return _err(404, f"conversation {conv_id!r} not found")
            context.extend(conv["items"])
        if prev:
            entry = store.get_response(prev)
            if entry is None:
                return _err(404, f"previous response {prev!r} not found")
            context.extend(entry["history"])
        new_msgs = _input_to_messages(body.get("input"))
        if not new_msgs and not context:
            return _err(400, "input is required")
        context.extend(new_msgs)
        messages = (
            [{"role": "system", "content": instructions}] if instructions else []
        ) + context

        from llmd_tpu.serve.api import Detokenizer, _chat_prompt_ids

        prompt_ids = _chat_prompt_ids(tokenizer, messages)
        if len(prompt_ids) >= max_len:
            return _err(
                400, f"input length {len(prompt_ids)} >= max_model_len {max_len}"
            )
        budget = max_len - len(prompt_ids)
        req_max = body.get("max_output_tokens")
        if req_max is not None and (
            not isinstance(req_max, int)
            or isinstance(req_max, bool)
            or req_max < 1
        ):
            return _err(400, "max_output_tokens must be a positive integer")
        max_tokens = min(req_max if req_max is not None else budget, budget)
        eos = getattr(tokenizer, "eos_token_id", None)
        from llmd_tpu.engine import SamplingParams

        sampling = SamplingParams(
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            max_tokens=max_tokens,
            seed=body.get("seed"),
            stop_token_ids=(int(eos),) if eos is not None else (),
        )
        rid = f"resp_{uuid.uuid4().hex}"
        detok = Detokenizer(tokenizer, [])
        stream = bool(body.get("stream"))

        def remember(resp_obj: dict, text: str) -> None:
            if body.get("store", True):
                store.put_response(
                    resp_obj,
                    context + [{"role": "assistant", "content": text}],
                )
            if conv_id is not None and conv_id in store.conversations:
                # Append only THIS request's turns: prepended context from
                # previous_response_id (or instructions) is per-request and
                # must not leak into the conversation's stored items.
                store.conversations[conv_id]["items"].extend(
                    new_msgs + [{"role": "assistant", "content": text}]
                )

        if not stream:
            text = ""
            n_out = 0
            try:
                async for out in engine.generate(rid, prompt_ids, sampling):
                    text += detok.feed(out.new_token_ids, final=out.finished)
                    n_out = out.num_output_tokens
            except RequestFailed as e:
                return _err(400, str(e))
            except EngineError as e:
                return web.json_response(
                    P.error_body(str(e), etype="internal_error", code=500),
                    status=500,
                )
            except (asyncio.CancelledError, ConnectionResetError):
                # Client gone: free the batch slot + KV pages (same abort
                # contract as the completions/chat handlers).
                engine.abort(rid)
                raise
            resp_obj = _response_object(
                rid, model, text, _responses_usage(len(prompt_ids), n_out)
            )
            remember(resp_obj, text)
            return web.json_response(resp_obj)

        sse = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "x-request-id": rid,
            }
        )
        await sse.prepare(request)
        created = _response_object(
            rid, model, "", _responses_usage(len(prompt_ids), 0), "in_progress"
        )
        created["output"] = []
        await sse.write(_event("response.created", {"response": created}))
        text = ""
        n_out = 0
        try:
            async for out in engine.generate(rid, prompt_ids, sampling):
                delta = detok.feed(out.new_token_ids, final=out.finished)
                n_out = out.num_output_tokens
                if delta:
                    text += delta
                    await sse.write(_event(
                        "response.output_text.delta",
                        {"delta": delta, "output_index": 0},
                    ))
        except (RequestFailed, EngineError) as e:
            await sse.write(_event(
                "response.failed",
                {"response": {"id": rid, "status": "failed",
                              "error": {"message": str(e)}}},
            ))
            await sse.write_eof()
            return sse
        except (asyncio.CancelledError, ConnectionResetError):
            engine.abort(rid)
            raise
        resp_obj = _response_object(
            rid, model, text, _responses_usage(len(prompt_ids), n_out)
        )
        remember(resp_obj, text)
        await sse.write(_event("response.completed", {"response": resp_obj}))
        await sse.write_eof()
        return sse

    async def get_response(request: web.Request) -> web.Response:
        store: ResponsesStore = request.app[STORE_KEY]
        entry = store.get_response(request.match_info["rid"])
        if entry is None:
            return _err(404, "response not found")
        return web.json_response(entry["response"])

    async def delete_response(request: web.Request) -> web.Response:
        store: ResponsesStore = request.app[STORE_KEY]
        rid = request.match_info["rid"]
        if store.responses.pop(rid, None) is None:
            return _err(404, "response not found")
        return web.json_response({"id": rid, "object": "response", "deleted": True})

    async def create_conversation(request: web.Request) -> web.Response:
        store: ResponsesStore = request.app[STORE_KEY]
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError:
            body = {}
        conv = store.new_conversation(body.get("metadata"))
        for item in _input_to_messages(body.get("items")):
            store.conversations[conv["id"]]["items"].append(item)
        return web.json_response(conv)

    async def get_conversation(request: web.Request) -> web.Response:
        store: ResponsesStore = request.app[STORE_KEY]
        cid = request.match_info["cid"]
        if cid not in store.conversations:
            return _err(404, "conversation not found")
        return web.json_response(store.conversation_object(cid))

    async def add_items(request: web.Request) -> web.Response:
        store: ResponsesStore = request.app[STORE_KEY]
        cid = request.match_info["cid"]
        if cid not in store.conversations:
            return _err(404, "conversation not found")
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return _err(400, f"invalid JSON: {e}")
        items = _input_to_messages(body.get("items"))
        store.conversations[cid]["items"].extend(items)
        return web.json_response({
            "object": "list",
            "data": [
                {"type": "message", **m}
                for m in store.conversations[cid]["items"]
            ],
        })

    async def list_items(request: web.Request) -> web.Response:
        store: ResponsesStore = request.app[STORE_KEY]
        cid = request.match_info["cid"]
        if cid not in store.conversations:
            return _err(404, "conversation not found")
        return web.json_response({
            "object": "list",
            "data": [
                {"type": "message", **m}
                for m in store.conversations[cid]["items"]
            ],
        })

    return [
        web.post("/v1/responses", create_response),
        web.get("/v1/responses/{rid}", get_response),
        web.delete("/v1/responses/{rid}", delete_response),
        web.post("/v1/conversations", create_conversation),
        web.get("/v1/conversations/{cid}", get_conversation),
        web.post("/v1/conversations/{cid}/items", add_items),
        web.get("/v1/conversations/{cid}/items", list_items),
    ]
