"""Async bridge between the HTTP front end and the blocking engine loop.

The engine (like vLLM's EngineCore in the reference's model-server layer,
docs/architecture/core/model-servers.md:5-7) steps on a dedicated thread;
request submission and incremental outputs cross the thread boundary through
a lock-guarded inbox and per-request asyncio queues. The asyncio side never
blocks on device work.
"""

from __future__ import annotations

import asyncio
import functools
import logging
import threading
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

from llmd_tpu.engine.engine import LLMEngine
from llmd_tpu.engine.request import RequestOutput, SamplingParams

log = logging.getLogger(__name__)


@dataclass
class _Pending:
    request_id: str
    prompt_token_ids: list[int]
    sampling: SamplingParams
    priority: int = 0
    kv_transfer_params: dict[str, Any] | None = None
    lora_id: int = 0
    lora_name: str = ""


def _release_pulled(engine, kv_transfer_params) -> None:
    """Release a fetched-but-never-applied bundle riding in
    ``kv_transfer_params["__pulled__"]``: a streamed multi-host fetch
    pre-allocates pool pages that leak permanently unless every path
    that drops the bundle before apply funnels through here."""
    conn = getattr(engine, "kv_connector", None)
    if conn is None or not kv_transfer_params:
        return
    b = kv_transfer_params.get("__pulled__")
    if b is not None:
        conn.release_bundle(b)


class RequestFailed(Exception):
    """Client-side error (invalid request); maps to HTTP 400."""


class EngineError(Exception):
    """Internal engine failure (device fault, compile error); maps to 500."""


class AsyncEngine:
    """Runs an LLMEngine on a background thread with an asyncio surface."""

    def __init__(self, engine: LLMEngine) -> None:
        self.engine = engine
        self._lock = threading.Condition()
        self._inbox: list[_Pending] = []
        self._aborts: list[str] = []
        self._stop = False
        # IRO pause gate (proposals/inference-resilience-operator.md): a
        # paused engine stops stepping entirely — in-flight sequences stay
        # scheduled with their KV intact and continue on resume. Used to
        # quiesce the device before a RESET_DEVICE / REBOOT_NODE action.
        self._paused = False
        self._loop: asyncio.AbstractEventLoop | None = None
        # request_id -> asyncio.Queue of RequestOutput | Exception
        self._subs: dict[str, asyncio.Queue] = {}
        self._thread: threading.Thread | None = None
        # P/D fetch pool (see generate): owning the concurrent futures is
        # what makes abandoned-fetch cleanup possible. Sized like the
        # default loop executor — fetches block in pull_wait for long
        # stretches, so a small cap would head-of-line-block TTFT under
        # concurrent prefill handoffs.
        import concurrent.futures
        import os

        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=min(32, (os.cpu_count() or 1) + 4),
            thread_name_prefix="llmd-kv-fetch",
        )

    # ------------------------------------------------------------------ #

    def start(self, loop: asyncio.AbstractEventLoop | None = None) -> None:
        self._loop = loop or asyncio.get_event_loop()
        self._thread = threading.Thread(
            target=self._run, name="llmd-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self._fetch_pool.shutdown(wait=False, cancel_futures=True)

    @property
    def stats(self):
        return self.engine.stats

    # ------------------------------------------------------------------ #
    # IRO engine-coordination surface

    @property
    def paused(self) -> bool:
        return self._paused

    def pause(self) -> None:
        with self._lock:
            self._paused = True
            self._lock.notify_all()

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._lock.notify_all()

    async def drain(self, timeout_s: float = 60.0) -> bool:
        """Wait until no requests are in flight (queued or running).
        New submissions keep being accepted; callers gate those upstream
        (the router stops routing to a draining endpoint)."""
        deadline = asyncio.get_running_loop().time() + timeout_s
        while asyncio.get_running_loop().time() < deadline:
            with self._lock:
                idle = not self._inbox and not self.engine.has_work()
            if idle:
                return True
            await asyncio.sleep(0.05)
        return False

    # ------------------------------------------------------------------ #

    def submit(
        self,
        request_id: str,
        prompt_token_ids: list[int],
        sampling: SamplingParams,
        priority: int = 0,
        kv_transfer_params: dict[str, Any] | None = None,
        lora_id: int = 0,
        lora_name: str = "",
    ) -> asyncio.Queue:
        """Queue a request for the engine thread; returns its output queue."""
        q: asyncio.Queue = asyncio.Queue()
        with self._lock:
            if request_id in self._subs:
                raise RequestFailed(f"duplicate request id {request_id}")
            self._subs[request_id] = q
            self._inbox.append(
                _Pending(request_id, prompt_token_ids, sampling, priority,
                         kv_transfer_params, lora_id, lora_name)
            )
            self._lock.notify_all()
        return q

    async def embed(self, prompts: list[list[int]], lora_id: int = 0):
        """Pooled embeddings off the event loop (the forward runs on an
        executor thread; params are read-only so it coexists with the
        step thread)."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, functools.partial(self.engine.embed, prompts, lora_id)
        )

    def abort(self, request_id: str) -> None:
        with self._lock:
            self._subs.pop(request_id, None)
            self._aborts.append(request_id)
            self._lock.notify_all()

    async def generate(
        self,
        request_id: str,
        prompt_token_ids: list[int],
        sampling: SamplingParams,
        priority: int = 0,
        kv_transfer_params: dict[str, Any] | None = None,
        lora_id: int = 0,
        lora_name: str = "",
    ) -> AsyncIterator[RequestOutput]:
        """Async stream of incremental outputs until the request finishes."""
        # P/D consumer: run the (potentially slow) remote-KV pull on an
        # executor so it never blocks the engine step thread or the event
        # loop; the engine thread only applies the pre-fetched bundle.
        conn = getattr(self.engine, "kv_connector", None)
        if conn is not None and conn.wants_import(kv_transfer_params):
            # Submitted on OUR executor so the CONCURRENT future is in
            # hand: cancelling the awaiting task cancels only the
            # asyncio wrapper (which then DISCARDS the executor's real
            # result), so cleanup must attach to the concurrent future —
            # it alone still observes the fetched bundle whose streamed
            # multi-host fetch pre-allocated pool pages.
            cfut = self._fetch_pool.submit(
                conn.fetch_remote_policy,
                list(prompt_token_ids), kv_transfer_params,
            )
            try:
                bundle = await asyncio.wrap_future(cfut)
            except asyncio.CancelledError:

                def _release(f):
                    try:
                        b = f.result()
                    except BaseException:
                        return  # fetch failed/cancelled: nothing to free
                    _release_pulled(self.engine, {"__pulled__": b})

                cfut.add_done_callback(_release)
                raise
            except Exception as e:  # KVLoadError under policy='fail'
                raise EngineError(f"remote KV load failed: {e}") from e
            kv_transfer_params = {**kv_transfer_params, "__pulled__": bundle}
        try:
            q = self.submit(request_id, prompt_token_ids, sampling, priority,
                            kv_transfer_params, lora_id, lora_name)
        except Exception:
            # A bundle that never reaches apply must release its pages.
            _release_pulled(self.engine, kv_transfer_params)
            raise
        try:
            while True:
                item = await q.get()
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            with self._lock:
                # Identity check: only abort OUR registration — the id may
                # have finished and been reused by a newer request.
                if self._subs.get(request_id) is q:
                    # Consumer bailed early (client disconnect): abort.
                    self._subs.pop(request_id, None)
                    self._aborts.append(request_id)
                    self._lock.notify_all()

    # ------------------------------------------------------------------ #

    def _deliver(self, request_id: str, item) -> None:
        q = self._subs.get(request_id)
        if q is None:
            return
        if isinstance(item, RequestOutput) and item.finished:
            self._subs.pop(request_id, None)
        assert self._loop is not None
        self._loop.call_soon_threadsafe(q.put_nowait, item)

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._stop and (
                    self._paused
                    or (
                        not self._inbox
                        and not self._aborts
                        and not self.engine.has_work()
                    )
                ):
                    self._lock.wait()
                if self._stop:
                    # Queued entries die with the loop — their fetched
                    # bundles (stream-reserved pool pages) must not.
                    for p in self._inbox:
                        _release_pulled(self.engine, p.kv_transfer_params)
                    self._inbox = []
                    return
                pending, self._inbox = self._inbox, []
                aborts, self._aborts = self._aborts, []
            for rid in aborts:
                self.engine.abort_request(rid)
            for p in pending:
                try:
                    self.engine.add_request(
                        p.prompt_token_ids,
                        p.sampling,
                        request_id=p.request_id,
                        priority=p.priority,
                        kv_transfer_params=p.kv_transfer_params,
                        lora_id=p.lora_id,
                        lora_name=p.lora_name,
                    )
                except Exception as e:  # validation errors -> caller
                    _release_pulled(self.engine, p.kv_transfer_params)
                    self._deliver(p.request_id, RequestFailed(str(e)))
            if not self.engine.has_work():
                continue
            try:
                outputs = self.engine.step()
            except Exception:
                log.exception("engine step failed")
                with self._lock:
                    subs = list(self._subs)
                for rid in subs:
                    self._deliver(rid, EngineError("engine step failed"))
                continue
            for out in outputs:
                self._deliver(out.request_id, out)
