"""Async bridge between the HTTP front end and the blocking engine loop.

The engine (like vLLM's EngineCore in the reference's model-server layer,
docs/architecture/core/model-servers.md:5-7) steps on a dedicated thread;
request submission and incremental outputs cross the thread boundary through
a lock-guarded inbox and per-request asyncio queues. The asyncio side never
blocks on device work.
"""

from __future__ import annotations

import asyncio
import functools
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

from llmd_tpu.engine.engine import LLMEngine
from llmd_tpu.engine.request import RequestOutput, SamplingParams

log = logging.getLogger(__name__)


@dataclass
class _Pending:
    request_id: str
    prompt_token_ids: list[int]
    sampling: SamplingParams
    priority: int = 0
    kv_transfer_params: dict[str, Any] | None = None
    lora_id: int = 0
    lora_name: str = ""
    # Mid-stream failover: the prompt's last N tokens are output already
    # delivered to the client by a dead replica; generation continues at
    # output position N (docs/architecture/fault-tolerance.md).
    resume_output_tokens: int = 0


def _release_pulled(engine, kv_transfer_params) -> None:
    """Release a fetched-but-never-applied bundle riding in
    ``kv_transfer_params["__pulled__"]`` (or abandon an in-flight
    group-stream handle in ``"__stream__"``): a streamed fetch
    pre-allocates pool pages that leak permanently unless every path
    that drops the bundle before apply funnels through here."""
    conn = getattr(engine, "kv_connector", None)
    if conn is None or not kv_transfer_params:
        return
    b = kv_transfer_params.get("__pulled__")
    if b is not None:
        conn.release_bundle(b)
    handle = kv_transfer_params.get("__stream__")
    if handle is not None:
        handle.abandon()


class RequestFailed(Exception):
    """Client-side error (invalid request); maps to HTTP 400."""


class EngineError(Exception):
    """Internal engine failure (device fault, compile error); maps to 500."""


class DeadlineExceeded(EngineError):
    """Per-request deadline elapsed before the stream finished; maps to
    504 (non-streaming) or a terminal error frame (streaming)."""


class WatchdogStalled(EngineError):
    """The engine step loop blew past the watchdog budget: the device
    program (or a collective peer) is wedged. In-flight streams get this
    as a terminal frame instead of hanging forever."""


class AsyncEngine:
    """Runs an LLMEngine on a background thread with an asyncio surface."""

    def __init__(
        self, engine: LLMEngine, watchdog_s: float | None = None
    ) -> None:
        self.engine = engine
        # Step watchdog: last-step-heartbeat liveness for the engine
        # thread. A step outliving the budget means the device program
        # (or a lockstep peer) is wedged — /health flips 503 and every
        # in-flight stream gets a terminal WatchdogStalled frame instead
        # of hanging until the client gives up. 0/None disables.
        if watchdog_s is None:
            try:
                watchdog_s = float(
                    os.environ.get("LLMD_STEP_WATCHDOG_S", "0") or 0
                )
            except ValueError:
                watchdog_s = 0.0
        self.watchdog_s = watchdog_s or 0.0
        self._step_started: float | None = None
        self.last_step_done = time.monotonic()
        # The FIRST step carries jit compilation (seconds to minutes on a
        # cold cache) — that's the startup probe's domain, not a wedge.
        # The watchdog arms once one step has completed.
        self._steps_done = 0
        self._stall_flagged = False
        self._watchdog_task: asyncio.Task | None = None
        # Graceful-shutdown readiness: flipped by drain() so /ready goes
        # 503 before the gateway sees connection errors.
        self.draining = False
        self._lock = threading.Condition()
        self._inbox: list[_Pending] = []  # llmd: guarded_by(_lock)
        self._aborts: list[str] = []  # llmd: guarded_by(_lock)
        self._stop = False  # llmd: guarded_by(_lock)
        # IRO pause gate (proposals/inference-resilience-operator.md): a
        # paused engine stops stepping entirely — in-flight sequences stay
        # scheduled with their KV intact and continue on resume. Used to
        # quiesce the device before a RESET_DEVICE / REBOOT_NODE action.
        self._paused = False  # llmd: guarded_by(_lock)
        self._loop: asyncio.AbstractEventLoop | None = None
        # request_id -> asyncio.Queue of RequestOutput | Exception
        self._subs: dict[str, asyncio.Queue] = {}  # llmd: guarded_by(_lock)
        self._thread: threading.Thread | None = None
        # P/D fetch pool (see generate): owning the concurrent futures is
        # what makes abandoned-fetch cleanup possible. Sized like the
        # default loop executor — fetches block in pull_wait for long
        # stretches, so a small cap would head-of-line-block TTFT under
        # concurrent prefill handoffs.
        import concurrent.futures

        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=min(32, (os.cpu_count() or 1) + 4),
            thread_name_prefix="llmd-kv-fetch",
        )

    # ------------------------------------------------------------------ #

    def start(self, loop: asyncio.AbstractEventLoop | None = None) -> None:
        self._loop = loop or asyncio.get_event_loop()
        self._thread = threading.Thread(
            target=self._run, name="llmd-engine", daemon=True
        )
        self._thread.start()
        if self.watchdog_s and self._loop.is_running():
            self._watchdog_task = self._loop.create_task(
                self._watchdog_loop()
            )

    def stop(self) -> None:
        if self._watchdog_task is not None:
            self._watchdog_task.cancel()
            self._watchdog_task = None
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self._fetch_pool.shutdown(wait=False, cancel_futures=True)

    # ------------------------------------------------------------------ #
    # step watchdog (liveness for the engine thread)

    @property
    def stalled(self) -> bool:
        """True while the current step has outlived the watchdog budget
        (warmed engines only: the first step's jit compile is startup-
        probe territory)."""
        if not self.watchdog_s or not self._steps_done:
            return False
        t0 = self._step_started
        return t0 is not None and time.monotonic() - t0 > self.watchdog_s

    @property
    def ready(self) -> bool:
        """Readiness (vs /health liveness): engine thread up, stepping
        within budget, not paused, not draining."""
        return (
            self._thread is not None
            and self._thread.is_alive()
            # llmd: allow(concurrency) -- single atomic bool read for a health probe; a probe racing pause() legitimately reports either state
            and not self._paused
            and not self.draining
            and not self.stalled
        )

    async def _watchdog_loop(self) -> None:
        period = max(self.watchdog_s / 4.0, 0.05)
        while True:
            await asyncio.sleep(period)
            if not self.stalled:
                continue
            if not self._stall_flagged:
                self._stall_flagged = True
                self.engine.stats.engine_watchdog_stalls_total += 1
                log.error(
                    "engine step watchdog: step running > %.1fs; failing "
                    "in-flight streams and turning /health 503",
                    self.watchdog_s,
                )
            # Terminal frames for every in-flight stream; their engine-
            # side sequences are queued for abort so a recovering thread
            # doesn't keep burning device time on abandoned requests.
            with self._lock:
                subs, self._subs = dict(self._subs), {}
                self._aborts.extend(subs)
                self._lock.notify_all()
            err = WatchdogStalled(
                f"engine step exceeded the {self.watchdog_s}s watchdog "
                "budget; the engine is wedged"
            )
            for q in subs.values():
                q.put_nowait(err)

    @property
    def stats(self):
        return self.engine.stats

    # ------------------------------------------------------------------ #
    # IRO engine-coordination surface

    @property
    def paused(self) -> bool:
        # llmd: allow(concurrency) -- single atomic bool read; IRO polls this, and racing a concurrent pause() legitimately returns either side
        return self._paused

    def pause(self) -> None:
        with self._lock:
            self._paused = True
            self._lock.notify_all()

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self.draining = False  # a resumed engine serves again
            self._lock.notify_all()

    async def drain(self, timeout_s: float = 60.0) -> bool:
        """Wait until no requests are in flight (queued or running).
        New submissions keep being accepted; callers gate those upstream
        — /ready flips 503 HERE so the gateway stops routing before the
        engine goes away (resume() re-readies after maintenance)."""
        self.draining = True
        deadline = asyncio.get_running_loop().time() + timeout_s
        while asyncio.get_running_loop().time() < deadline:
            with self._lock:
                idle = not self._inbox and not self.engine.has_work()
            if idle:
                return True
            await asyncio.sleep(0.05)
        return False

    # ------------------------------------------------------------------ #

    def submit(
        self,
        request_id: str,
        prompt_token_ids: list[int],
        sampling: SamplingParams,
        priority: int = 0,
        kv_transfer_params: dict[str, Any] | None = None,
        lora_id: int = 0,
        lora_name: str = "",
        resume_output_tokens: int = 0,
    ) -> asyncio.Queue:
        """Queue a request for the engine thread; returns its output queue."""
        q: asyncio.Queue = asyncio.Queue()
        with self._lock:
            if request_id in self._subs:
                raise RequestFailed(f"duplicate request id {request_id}")
            self._subs[request_id] = q
            self._inbox.append(
                _Pending(request_id, prompt_token_ids, sampling, priority,
                         kv_transfer_params, lora_id, lora_name,
                         resume_output_tokens)
            )
            self._lock.notify_all()
        return q

    async def embed(
        self,
        prompts: list[list[int]],
        lora_id: int = 0,
        lora_name: str = "",
    ):
        """Pooled embeddings off the event loop (the forward runs on an
        executor thread; params are read-only so it coexists with the
        step thread)."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None,
            functools.partial(self.engine.embed, prompts, lora_id, lora_name),
        )

    async def load_adapter(self, name: str, source: str = "") -> None:
        """Runtime adapter registration (/v1/load_lora_adapter): the
        fetch + lockstep slot install run on an executor thread — the
        event loop and the step thread never block on the weight
        transfer (docs/architecture/multi-tenant-lora.md)."""
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, functools.partial(self.engine.load_adapter, name, source)
        )

    async def unload_adapter(self, name: str) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, functools.partial(self.engine.unload_adapter, name)
        )

    def abort(self, request_id: str) -> None:
        with self._lock:
            self._subs.pop(request_id, None)
            self._aborts.append(request_id)
            self._lock.notify_all()

    async def generate(
        self,
        request_id: str,
        prompt_token_ids: list[int],
        sampling: SamplingParams,
        priority: int = 0,
        kv_transfer_params: dict[str, Any] | None = None,
        lora_id: int = 0,
        lora_name: str = "",
        deadline_s: float | None = None,
        resume_output_tokens: int = 0,
    ) -> AsyncIterator[RequestOutput]:
        """Async stream of incremental outputs until the request finishes.

        ``deadline_s`` bounds the WHOLE request (fetch included): when it
        elapses the stream raises :class:`DeadlineExceeded` and the
        engine-side sequence is aborted — a wedged or starved engine can
        slow requests down, but never hold a caller hostage."""
        deadline = (
            None if deadline_s is None else time.monotonic() + deadline_s
        )
        # P/D consumer: run the (potentially slow) remote-KV pull on an
        # executor so it never blocks the engine step thread or the event
        # loop; the engine thread only applies the pre-fetched bundle.
        conn = getattr(self.engine, "kv_connector", None)
        if conn is not None and conn.streaming_import(kv_transfer_params):
            # Group-streamed import (v3 wire): the fetch thread scatters
            # each layer group into batch-allocated pool pages as it
            # lands; submit the request the moment the FIRST group is
            # resident, so engine admission, scheduling, and host
            # staging overlap the rest of the wire transfer. The engine
            # parks the request and finalizes when the stream resolves
            # (apply on success, local recompute on failure).
            handle = conn.make_stream_handle(kv_transfer_params)
            loop = asyncio.get_running_loop()
            admittable = asyncio.Event()
            # Signal the loop directly from the fetch thread: no thread
            # is parked for the wait, so a burst of concurrent streamed
            # imports cannot exhaust the default executor.
            handle.on_first_group = functools.partial(
                loop.call_soon_threadsafe, admittable.set
            )

            def _fetch_streamed() -> None:
                try:
                    conn.fetch_remote_policy(
                        list(prompt_token_ids), kv_transfer_params, handle
                    )
                finally:
                    # Policy='recompute' never raises, but an unexpected
                    # failure mode must not leave the parked request
                    # waiting forever — fail() degrades it to recompute.
                    if not handle.done.is_set():
                        handle.fail("streamed fetch died unresolved")

            self._fetch_pool.submit(_fetch_streamed)
            try:
                if deadline is None:
                    await admittable.wait()
                else:
                    try:
                        await asyncio.wait_for(
                            admittable.wait(),
                            max(deadline - time.monotonic(), 0.001),
                        )
                    except asyncio.TimeoutError:
                        pass  # surfaced via the is_set() check below
            except asyncio.CancelledError:
                handle.abandon()
                raise
            if not handle.first_group.is_set():
                # Deadline elapsed before the first group landed; the
                # fetch keeps running and the abandon hook frees its
                # stream-reserved pages whenever it resolves.
                handle.abandon()
                raise DeadlineExceeded(
                    f"request deadline of {deadline_s}s exceeded during "
                    "remote KV stream"
                )
            kv_transfer_params = {**kv_transfer_params, "__stream__": handle}
        elif conn is not None and conn.wants_import(kv_transfer_params):
            # Submitted on OUR executor so the CONCURRENT future is in
            # hand: cancelling the awaiting task cancels only the
            # asyncio wrapper (which then DISCARDS the executor's real
            # result), so cleanup must attach to the concurrent future —
            # it alone still observes the fetched bundle whose streamed
            # multi-host fetch pre-allocated pool pages.
            cfut = self._fetch_pool.submit(
                conn.fetch_remote_policy,
                list(prompt_token_ids), kv_transfer_params,
            )
            def _release(f):
                try:
                    b = f.result()
                # llmd: allow(broad-except) -- done-callback probe: a failed fetch has no bundle to release
                except BaseException:
                    return  # fetch failed/cancelled: nothing to free
                _release_pulled(self.engine, {"__pulled__": b})

            try:
                if deadline is None:
                    bundle = await asyncio.wrap_future(cfut)
                else:
                    # The deadline bounds the FETCH too: a slow/absent
                    # producer must not hold the caller past it. The
                    # executor's real fetch keeps running after the
                    # timeout; the release callback frees its stream-
                    # reserved pages when it eventually lands.
                    try:
                        bundle = await asyncio.wait_for(
                            asyncio.wrap_future(cfut),
                            max(deadline - time.monotonic(), 0.001),
                        )
                    except asyncio.TimeoutError:
                        cfut.add_done_callback(_release)
                        raise DeadlineExceeded(
                            f"request deadline of {deadline_s}s exceeded "
                            "during remote KV fetch"
                        ) from None
            except asyncio.CancelledError:
                cfut.add_done_callback(_release)
                raise
            except DeadlineExceeded:
                raise
            except Exception as e:  # KVLoadError under policy='fail'
                raise EngineError(f"remote KV load failed: {e}") from e
            kv_transfer_params = {**kv_transfer_params, "__pulled__": bundle}
        try:
            q = self.submit(request_id, prompt_token_ids, sampling, priority,
                            kv_transfer_params, lora_id, lora_name,
                            resume_output_tokens)
        except Exception:
            # A bundle that never reaches apply must release its pages.
            _release_pulled(self.engine, kv_transfer_params)
            raise
        try:
            while True:
                if deadline is None:
                    item = await q.get()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise DeadlineExceeded(
                            f"request deadline of {deadline_s}s exceeded"
                        )
                    try:
                        item = await asyncio.wait_for(q.get(), remaining)
                    except asyncio.TimeoutError:
                        raise DeadlineExceeded(
                            f"request deadline of {deadline_s}s exceeded"
                        ) from None
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            with self._lock:
                # Identity check: only abort OUR registration — the id may
                # have finished and been reused by a newer request.
                if self._subs.get(request_id) is q:
                    # Consumer bailed early (client disconnect): abort.
                    self._subs.pop(request_id, None)
                    self._aborts.append(request_id)
                    self._lock.notify_all()

    # ------------------------------------------------------------------ #

    def _deliver(self, request_id: str, item) -> None:
        # Engine-thread side of the _subs registry. The get/pop pair
        # must hold the lock: the loop thread concurrently registers
        # (submit), deregisters-and-aborts (generate's finally, with an
        # identity check this pop must be ordered against), and swaps
        # the whole dict (watchdog) — an unlocked pop here could race a
        # same-id resubmit and silently drop the NEW stream's queue.
        with self._lock:
            q = self._subs.get(request_id)
            if q is None:
                return
            if isinstance(item, RequestOutput) and item.finished:
                self._subs.pop(request_id, None)
        assert self._loop is not None
        self._loop.call_soon_threadsafe(q.put_nowait, item)

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._stop and (
                    self._paused
                    or (
                        not self._inbox
                        and not self._aborts
                        and not self.engine.has_work()
                    )
                ):
                    self._lock.wait()
                if self._stop:
                    # Queued entries die with the loop — their fetched
                    # bundles (stream-reserved pool pages) must not.
                    for p in self._inbox:
                        _release_pulled(self.engine, p.kv_transfer_params)
                    self._inbox = []
                    return
                pending, self._inbox = self._inbox, []
                aborts, self._aborts = self._aborts, []
            for rid in aborts:
                self.engine.abort_request(rid)
            for p in pending:
                try:
                    self.engine.add_request(
                        p.prompt_token_ids,
                        p.sampling,
                        request_id=p.request_id,
                        priority=p.priority,
                        kv_transfer_params=p.kv_transfer_params,
                        lora_id=p.lora_id,
                        lora_name=p.lora_name,
                        resume_output_tokens=p.resume_output_tokens,
                    )
                # llmd: allow(broad-except) -- surfaced: the caller receives it as a RequestFailed terminal item
                except Exception as e:  # validation errors -> caller
                    _release_pulled(self.engine, p.kv_transfer_params)
                    self._deliver(p.request_id, RequestFailed(str(e)))
            if not self.engine.has_work():
                continue
            try:
                # Watchdog heartbeat brackets the one blocking call.
                self._step_started = time.monotonic()
                outputs = self.engine.step()
            # llmd: allow(broad-except) -- surfaced: every subscriber receives the EngineError as a terminal item (HTTP 500)
            except Exception:
                log.exception("engine step failed")
                with self._lock:
                    subs = list(self._subs)
                for rid in subs:
                    self._deliver(rid, EngineError("engine step failed"))
                continue
            finally:
                self._step_started = None
                self.last_step_done = time.monotonic()
                self._steps_done += 1
                self._stall_flagged = False
            for out in outputs:
                self._deliver(out.request_id, out)
