"""DP supervisor: manages per-rank engine processes on one host.

Re-implements the reference's vLLM DP supervisor deployment shape
(wide-ep-lws/modelserver/gpu/vllm/base/decode.yaml:101-121, 223-247):

  * N local engine ranks, each an independent serving process listening on
    ``port_base + i`` (the ``--data-parallel-multi-port-external-lb``
    pattern — every rank is externally addressable and the EPP lists all
    rank ports in targetPorts, wide-ep-lws.values.yaml:41-52);
  * global rank = ``start_rank + i`` for multi-host DP
    (``--data-parallel-start-rank`` math, decode.yaml:112);
  * a supervisor health endpoint (reference :8208) aggregating rank health;
  * restart policy: per-rank restart with backoff, or all-or-nothing
    (the LWS semantics, docs/infrastructure/multi-node.md:5).

On TPU each rank owns its chips via JAX process-local devices; the
supervisor is deliberately engine-agnostic — it execs the serve CLI.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import sys
import time

import aiohttp
from aiohttp import web

log = logging.getLogger(__name__)


@dataclasses.dataclass
class DPConfig:
    data_parallel_size: int = 1  # global DP world
    data_parallel_size_local: int = 1  # ranks on this host
    data_parallel_start_rank: int = 0
    port_base: int = 8200
    health_port: int = 8208
    all_or_nothing: bool = False  # LWS-style: one rank dies => restart all
    restart_backoff_s: float = 2.0
    max_restarts: int = 10
    engine_args: tuple[str, ...] = ()  # passed through to the serve CLI


@dataclasses.dataclass
class _Rank:
    local_rank: int
    global_rank: int
    port: int
    proc: asyncio.subprocess.Process | None = None
    restarts: int = 0
    started_at: float = 0.0


class DPSupervisor:
    def __init__(self, cfg: DPConfig) -> None:
        if cfg.data_parallel_start_rank + cfg.data_parallel_size_local > cfg.data_parallel_size:
            raise ValueError(
                f"start rank {cfg.data_parallel_start_rank} + local "
                f"{cfg.data_parallel_size_local} exceeds DP world "
                f"{cfg.data_parallel_size}"
            )
        self.cfg = cfg
        self.ranks = [
            _Rank(
                local_rank=i,
                global_rank=cfg.data_parallel_start_rank + i,
                port=cfg.port_base + i,
            )
            for i in range(cfg.data_parallel_size_local)
        ]
        self._stopping = False

    # ------------------------------------------------------------------ #

    def _cmd(self, rank: _Rank) -> list[str]:
        return [
            sys.executable, "-m", "llmd_tpu.serve",
            "--port", str(rank.port),
            "--data-parallel-rank", str(rank.global_rank),
            "--data-parallel-size", str(self.cfg.data_parallel_size),
            *self.cfg.engine_args,
        ]

    async def _spawn(self, rank: _Rank) -> None:
        cmd = self._cmd(rank)
        log.info("dp rank %d (global %d): %s", rank.local_rank, rank.global_rank,
                 " ".join(cmd))
        rank.proc = await asyncio.create_subprocess_exec(*cmd)
        rank.started_at = time.monotonic()

    async def _monitor(self) -> None:
        """Restart dead ranks (or everything, in all-or-nothing mode)."""
        while not self._stopping:
            await asyncio.sleep(0.5)
            for rank in self.ranks:
                p = rank.proc
                if p is None or p.returncode is None:
                    continue
                log.warning(
                    "dp rank %d exited rc=%s", rank.local_rank, p.returncode
                )
                if self.cfg.all_or_nothing:
                    log.warning("all-or-nothing: restarting every rank")
                    await self._kill_all()
                    for r in self.ranks:
                        r.restarts += 1
                    if any(r.restarts > self.cfg.max_restarts for r in self.ranks):
                        raise RuntimeError("dp ranks exceeded max restarts")
                    await asyncio.sleep(self.cfg.restart_backoff_s)
                    for r in self.ranks:
                        await self._spawn(r)
                    break
                rank.restarts += 1
                if rank.restarts > self.cfg.max_restarts:
                    raise RuntimeError(
                        f"dp rank {rank.local_rank} exceeded max restarts"
                    )
                await asyncio.sleep(
                    self.cfg.restart_backoff_s * min(rank.restarts, 5)
                )
                await self._spawn(rank)

    async def _kill_all(self) -> None:
        for rank in self.ranks:
            if rank.proc is not None and rank.proc.returncode is None:
                rank.proc.terminate()
        for rank in self.ranks:
            if rank.proc is not None:
                try:
                    await asyncio.wait_for(rank.proc.wait(), timeout=10)
                except asyncio.TimeoutError:
                    rank.proc.kill()
                    await rank.proc.wait()

    # ------------------------------------------------------------------ #
    # health aggregation (reference supervisor health on :8208)

    async def _rank_health(
        self, session: aiohttp.ClientSession, rank: _Rank
    ) -> dict:
        alive = rank.proc is not None and rank.proc.returncode is None
        healthy = False
        if alive:
            try:
                async with session.get(
                    f"http://127.0.0.1:{rank.port}/health",
                    timeout=aiohttp.ClientTimeout(total=2),
                ) as r:
                    healthy = r.status == 200
            except (aiohttp.ClientError, asyncio.TimeoutError):
                healthy = False
        return {
            "local_rank": rank.local_rank,
            "global_rank": rank.global_rank,
            "port": rank.port,
            "process_alive": alive,
            "healthy": healthy,
            "restarts": rank.restarts,
        }

    def build_health_app(self) -> web.Application:
        async def on_startup(app):
            app["session"] = aiohttp.ClientSession()

        async def on_cleanup(app):
            await app["session"].close()

        async def health(request: web.Request) -> web.Response:
            rs = await asyncio.gather(
                *[self._rank_health(request.app["session"], r) for r in self.ranks]
            )
            ok = all(r["healthy"] for r in rs)
            return web.json_response(
                {"healthy": ok, "ranks": rs}, status=200 if ok else 503
            )

        app = web.Application()
        app.on_startup.append(on_startup)
        app.on_cleanup.append(on_cleanup)
        app.router.add_get("/health", health)
        app.router.add_get("/healthz", health)
        return app

    # ------------------------------------------------------------------ #

    async def run(self) -> None:
        for rank in self.ranks:
            await self._spawn(rank)
        runner = web.AppRunner(self.build_health_app())
        await runner.setup()
        site = web.TCPSite(runner, "0.0.0.0", self.cfg.health_port)
        await site.start()
        try:
            await self._monitor()
        finally:
            self._stopping = True
            await self._kill_all()
            await runner.cleanup()

    async def stop(self) -> None:
        self._stopping = True
        await self._kill_all()


def main(argv=None) -> None:
    import argparse

    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(
        "llmd-tpu dp supervisor",
        epilog="arguments after -- are passed to each rank's serve CLI",
    )
    ap.add_argument("--data-parallel-size", type=int, default=1)
    ap.add_argument("--data-parallel-size-local", type=int, default=None)
    ap.add_argument("--data-parallel-start-rank", type=int, default=0)
    ap.add_argument("--port-base", type=int, default=8200)
    ap.add_argument("--health-port", type=int, default=8208)
    ap.add_argument("--all-or-nothing", action="store_true")
    args, engine_args = ap.parse_known_args(argv)
    if engine_args and engine_args[0] == "--":
        engine_args = engine_args[1:]
    cfg = DPConfig(
        data_parallel_size=args.data_parallel_size,
        data_parallel_size_local=(
            args.data_parallel_size_local or args.data_parallel_size
        ),
        data_parallel_start_rank=args.data_parallel_start_rank,
        port_base=args.port_base,
        health_port=args.health_port,
        all_or_nothing=args.all_or_nothing,
        engine_args=tuple(engine_args),
    )
    asyncio.run(DPSupervisor(cfg).run())


if __name__ == "__main__":
    main()
